"""Llama model family — the flagship workload (BASELINE.md workload #2).

Two faces over ONE weight set:

* **Imperative module** (`LlamaForCausalLM`): paddle-shaped nn.Layer built
  from the TP meta_parallel layers; runs eagerly, under jit.TrainStep, or
  under the GSPMD HybridTrainStep (dp/mp/sharding/sp via NamedShardings).
  Reference surface: PaddleNLP LlamaForCausalLM over
  fleet meta_parallel mp_layers (SURVEY.md §2.4, §3.2).

* **Functional hybrid step** (`build_hybrid_train_step`): the TP×PP×DP×SP
  compiled path — one shard_map program over the full mesh with Megatron-style
  explicit collectives for mp, the fill-drain ppermute pipeline for pp
  (parallel/pipeline.py), batch sharding for dp/sharding, and sequence
  sharding for sp. Used by fleet PP training, __graft_entry__.dryrun_multichip
  and bench.py.

Decoder math follows Llama-2: RMSNorm → QKV (GQA) → RoPE → causal flash
attention → out-proj → residual; RMSNorm → SwiGLU MLP → residual.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.common_layers import RMSNorm
from ..ops import rope as rope_ops
from ..ops import flash_attention as fa
from ..ops.rms_norm import rms_norm_array
from ..distributed.meta_parallel.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)
from ..core.compat import shard_map

#: per-layer tensors in the stacked functional layout (leading L axis).
LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "ln1", "ln2")


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    dtype: Any = jnp.float32
    # context-parallel attention flavor when sep_degree > 1:
    # "ulysses" (all_to_all head repartition) or "ring" (ppermute KV ring)
    sep_mode: str = "ulysses"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def llama2_7b(**over) -> LlamaConfig:
    return LlamaConfig(**{**dict(
        hidden_size=4096, intermediate_size=11008, num_hidden_layers=32,
        num_attention_heads=32, num_key_value_heads=32), **over})


def llama2_13b(**over) -> LlamaConfig:
    return LlamaConfig(**{**dict(
        hidden_size=5120, intermediate_size=13824, num_hidden_layers=40,
        num_attention_heads=40, num_key_value_heads=40), **over})


def llama_tiny(**over) -> LlamaConfig:
    return LlamaConfig(**{**dict(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=128), **over})


# ===========================================================================
# Imperative model
# ===========================================================================
class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        h, d = config.hidden_size, config.head_dim
        self.q_proj = ColumnParallelLinear(h, h, has_bias=False, gather_output=False)
        self.k_proj = ColumnParallelLinear(
            h, config.num_key_value_heads * d, has_bias=False, gather_output=False)
        self.v_proj = ColumnParallelLinear(
            h, config.num_key_value_heads * d, has_bias=False, gather_output=False)
        self.o_proj = RowParallelLinear(h, h, has_bias=False, input_is_parallel=True)

    def forward(self, x, cos, sin):
        cfg = self.config
        b, s, _ = x.shape
        d = cfg.head_dim
        q = self.q_proj(x).reshape([b, s, -1, d])
        k = self.k_proj(x).reshape([b, s, -1, d])
        v = self.v_proj(x).reshape([b, s, -1, d])
        q, k = rope_ops.fused_rotary_position_embedding(q, k, cos, sin)
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        return self.o_proj(out.reshape([b, s, -1]))


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        self.gate_proj = ColumnParallelLinear(h, m, has_bias=False, gather_output=False)
        self.up_proj = ColumnParallelLinear(h, m, has_bias=False, gather_output=False)
        self.down_proj = RowParallelLinear(m, h, has_bias=False, input_is_parallel=True)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, cos, sin):
        x = x + self.self_attn(self.input_layernorm(x), cos, sin)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                   config.hidden_size)
        from ..nn.layer import LayerList
        self.layers = LayerList([LlamaDecoderLayer(config)
                                 for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, input_ids):
        cfg = self.config
        s = input_ids.shape[1]
        cos, sin = rope_ops.build_rope_cache(s, cfg.head_dim, cfg.rope_theta)
        x = self.embed_tokens(input_ids)
        for layer in self.layers:
            x = layer(x, cos, sin)
        return self.norm(x)


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None  # logits via embed weightᵀ
        else:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=True)

    def forward(self, input_ids):
        h = self.llama(input_ids)
        if self.lm_head is None:
            from ..core import math_ops as M
            return M.matmul(h, self.llama.embed_tokens.weight, transpose_y=True)
        return self.lm_head(h)

    def compute_loss(self, input_ids, labels):
        logits = self(input_ids)
        return F.cross_entropy(
            logits.reshape([-1, self.config.vocab_size]),
            labels.reshape([-1]), ignore_index=-100)


# ===========================================================================
# Functional forward (serial; single-device oracle + graft entry)
# ===========================================================================
def forward_stacked(params: Dict[str, Any], ids, config: LlamaConfig):
    """Pure single-device forward over the stacked param layout → logits."""
    cos, sin = rope_ops.build_rope_cache(ids.shape[-1], config.head_dim,
                                         config.rope_theta)
    x = jnp.take(params["embed"], ids.astype(jnp.int32), axis=0)

    def body(carry, lp):
        out = _decoder_layer_manual(lp, carry, cos, sin, config=config,
                                    mp_axis=None, fsdp_axis=None)
        return out.astype(carry.dtype), None

    layer_params = {k: params[k] for k in LAYER_KEYS}
    x, _ = lax.scan(body, x, layer_params)
    x = _rms(x, params["ln_f"], config.rms_norm_eps)
    return jnp.einsum("bsh,hv->bsv", x, _dense(params["lm_head"]))


def loss_stacked(params: Dict[str, Any], ids, labels, config: LlamaConfig):
    logits = forward_stacked(params, ids, config).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logp, labels.astype(jnp.int32)[..., None],
                                 axis=-1)[..., 0]
    return -jnp.mean(picked)


# ===========================================================================
# Functional TP×PP×DP×SP hybrid step
# ===========================================================================
def init_stacked_params(config: LlamaConfig, seed: int = 0) -> Dict[str, Any]:
    """Weights in the stacked functional layout: per-layer tensors stacked on
    a leading L axis (pipeline shards slice it)."""
    L, h, m = config.num_hidden_layers, config.hidden_size, config.intermediate_size
    d = config.head_dim
    kvh = config.num_key_value_heads * d
    key = jax.random.key(seed)
    ks = jax.random.split(key, 12)
    std = 0.02
    dt = config.dtype

    def rnd(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * std).astype(dt)

    return {
        "embed": rnd(ks[0], (config.vocab_size, h)),
        "wq": rnd(ks[1], (L, h, h)),
        "wk": rnd(ks[2], (L, h, kvh)),
        "wv": rnd(ks[3], (L, h, kvh)),
        "wo": rnd(ks[4], (L, h, h)),
        "w_gate": rnd(ks[5], (L, h, m)),
        "w_up": rnd(ks[6], (L, h, m)),
        "w_down": rnd(ks[7], (L, m, h)),
        "ln1": jnp.ones((L, h), dt),
        "ln2": jnp.ones((L, h), dt),
        "ln_f": jnp.ones((h,), dt),
        "lm_head": rnd(ks[8], (h, config.vocab_size)),
    }


def param_count(config: LlamaConfig) -> int:
    """Parameter count of the stacked layout (embed + L decoder layers +
    final norm + lm_head) — the analytic twin of walking a real pytree,
    for capacity planning before any weights exist."""
    L, h, m = (config.num_hidden_layers, config.hidden_size,
               config.intermediate_size)
    kvh = config.num_key_value_heads * config.head_dim
    per_layer = 2 * h * h + 2 * h * kvh + 3 * h * m + 2 * h
    return (config.vocab_size * h + L * per_layer + h
            + h * config.vocab_size)


def param_nbytes(config: LlamaConfig) -> int:
    """Device bytes the stacked weights occupy at ``config.dtype`` — the
    ``weight_bytes`` input of the HBM capacity planner
    (``observability.memory.plan_capacity``); matches
    ``pytree_nbytes(init_stacked_params(config))`` exactly."""
    return param_count(config) * jnp.dtype(config.dtype).itemsize


def kv_geometry(config: LlamaConfig, page_size: int) -> Dict[str, int]:
    """The paged-KV geometry kwargs of the HBM capacity planner: one
    call site for "what does a page of this model cost" so planner
    examples, benches and the engine agree byte-for-byte."""
    return {
        "num_layers": config.num_hidden_layers,
        "num_kv_heads": config.num_key_value_heads,
        "head_dim": config.head_dim,
        "page_size": page_size,
        "dtype_bytes": jnp.dtype(config.dtype).itemsize,
    }


def stacked_param_specs(config: LlamaConfig) -> Dict[str, P]:
    """PartitionSpecs: L axis over pp, Megatron dims over mp, row-sharded big
    matrices additionally over 'sharding' (ZeRO-3 style weight sharding)."""
    return {
        "embed": P("mp", None),
        "wq": P("pp", ("dp", "sharding"), "mp"),
        "wk": P("pp", ("dp", "sharding"), "mp"),
        "wv": P("pp", ("dp", "sharding"), "mp"),
        "wo": P("pp", "mp", ("dp", "sharding")),
        "w_gate": P("pp", ("dp", "sharding"), "mp"),
        "w_up": P("pp", ("dp", "sharding"), "mp"),
        "w_down": P("pp", "mp", ("dp", "sharding")),
        "ln1": P("pp", None),
        "ln2": P("pp", None),
        "ln_f": P(),
        "lm_head": P(None, "mp"),
    }


def serving_param_specs(config: LlamaConfig) -> Dict[str, P]:
    """Megatron TP specs for the SERVING path: ``mp`` only (serving
    replicas have no dp/pp/sharding state — one replica = one TP mesh).
    Attention projections are column-parallel (head-output dim over
    ``mp``, whole heads per chip so the head-sharded paged KV pool lines
    up), ``wo``/``w_down`` row-parallel (XLA inserts the all-reduce),
    and ``embed``/``lm_head``/norms replicate so the packed-token gather
    and the per-row logits stay chip-local and bitwise identical to the
    single-chip program."""
    col, row = P(None, None, "mp"), P(None, "mp", None)
    return {
        "embed": P(), "lm_head": P(), "ln_f": P(),
        "ln1": P(None, None), "ln2": P(None, None),
        "wq": col, "wk": col, "wv": col,
        "w_gate": col, "w_up": col,
        "wo": row, "w_down": row,
    }


def shard_params_tp(params: Dict[str, Any], mesh: Mesh,
                    config: LlamaConfig) -> Dict[str, Any]:
    """Place a stacked-param dict onto a serving TP mesh
    (``serving_param_specs``). Weight-only-quantized leaves
    (``{"q", "scale"}`` from ``quantization.quantize_stacked_params``)
    shard ``q`` like the dense weight and ``scale`` (L, out) along the
    output dim for column-parallel weights (row-parallel scales
    replicate — their out dim is unsharded)."""
    specs = serving_param_specs(config)
    out: Dict[str, Any] = {}
    for k, v in params.items():
        spec = specs.get(k, P())
        if isinstance(v, dict):           # weight-only int8: {"q","scale"}
            # scale is (..., out): it shards along out exactly when the
            # dense weight is column-parallel (row-parallel/replicated
            # weights keep their out dim whole -> replicated scale)
            out_axis = spec[-1] if len(spec) == 3 else None
            scale_spec = P(*([None] * (v["scale"].ndim - 1) + [out_axis]))
            out[k] = {
                "q": jax.device_put(v["q"], NamedSharding(mesh, spec)),
                "scale": jax.device_put(
                    v["scale"], NamedSharding(mesh, scale_spec)),
            }
        else:
            out[k] = jax.device_put(v, NamedSharding(mesh, spec))
    return out


def _rms(x, w, eps):
    # fused Pallas rms_norm on TPU (ops/rms_norm.py), XLA ref path elsewhere
    return rms_norm_array(x, w, eps)


def _dense(w):
    """Materialize a possibly weight-only-quantized weight ({"q","scale"}
    from paddle_tpu.quantization.quantize_stacked_params) into its dense
    form. Called inside the per-layer scan body so only ONE layer's weight
    is dequantized at a time and XLA fuses the multiply into the consuming
    einsum — int8 storage halves the HBM bytes the decode loop waits on.
    Dense arrays pass through untouched."""
    if isinstance(w, dict):
        from ..quantization import weight_dequantize
        return weight_dequantize(w["q"], w["scale"])
    return w


def _mm_prefill(x, w):
    """Prefill-side matmul ``x @ w`` with the A8W8 fast path.

    Prefill is COMPUTE-bound (decode is bandwidth-bound), so for int8-
    quantized weights the dequantize-then-bf16-matmul of `_dense` wastes
    the MXU's 2x int8 throughput AND pays the dequant tax that made CB
    int8 LOSE to bf16 at mixed workloads (VERDICT r4 missing #4a). With
    FLAGS_serving_a8w8_prefill (default on) quantized weights run
    int8 x int8 -> int32 with per-token activation scales — the reference
    fused_multi_transformer_int8's prefill arrangement
    (fused_multi_transformer_int8_op.cu:§0). Decode keeps weight-only
    dequant: there the fused dequant is free and avoids per-step
    activation-quant noise."""
    if isinstance(w, dict):
        from ..flags import flag_value
        # t (dim -2) == 1 is the decode shape: stay weight-only there
        if flag_value("serving_a8w8_prefill") and w["q"].ndim == 2 \
                and x.ndim >= 2 and x.shape[-2] > 1:
            from ..ops.fused_transformer_block import _int8_mm
            return _int8_mm(x, w["q"], w["scale"])
    return jnp.einsum("...h,hd->...d", x, _dense(w))


def _decoder_layer_manual(p, x, cos, sin, config: LlamaConfig, mp_axis,
                          fsdp_axis, sep_axis=None):
    """One decoder layer inside shard_map. Weight locals: wq (h, h/mp) etc.
    (the fsdp axis shards the *contraction* dim h — all-gathered here, which
    is the ZeRO-3 gather; XLA overlaps it with the previous layer).

    When ``sep_axis`` is set, activations arrive sequence-sharded and
    attention runs Ulysses-style (SURVEY.md §5.7 mechanism 2): all_to_all
    repartitions (heads_local → seq_full) before attention and back after, so
    causal attention always sees the full sequence per head subset.
    """
    b, s, h = x.shape
    d = config.head_dim

    def gather_in(w):
        if fsdp_axis is not None:
            return lax.all_gather(w, fsdp_axis, axis=0, tiled=True)
        return w

    def gather_out(w):
        if fsdp_axis is not None:
            return lax.all_gather(w, fsdp_axis, axis=1, tiled=True)
        return w

    xn = _rms(x, p["ln1"], config.rms_norm_eps)
    q = jnp.einsum("bsh,hd->bsd", xn, gather_in(_dense(p["wq"])))
    k = jnp.einsum("bsh,hd->bsd", xn, gather_in(_dense(p["wk"])))
    v = jnp.einsum("bsh,hd->bsd", xn, gather_in(_dense(p["wv"])))
    nh_local = q.shape[-1] // d
    nkv_local = k.shape[-1] // d
    q = q.reshape(b, s, nh_local, d)
    k = k.reshape(b, s, nkv_local, d)
    v = v.reshape(b, s, nkv_local, d)
    q, k = rope_ops.apply_rope_array(q, k, cos, sin)
    sep_mode = getattr(config, "sep_mode", "ulysses")
    if sep_axis is not None and sep_mode == "ring":
        # blockwise ring attention: KV rotates over the sep ICI ring with
        # online-softmax merge (ops/ring_attention.py, SURVEY.md §5.7 (3))
        from ..ops import ring_attention as ra
        attn = ra.ring_attention_array(q, k, v, sep_axis, causal=True,
                                       scale=1.0 / math.sqrt(d))
    else:
        if sep_axis is not None:
            # (b, s_local, nh, d) -> (b, s_full, nh/sep, d)
            q, k, v = (lax.all_to_all(t, sep_axis, split_axis=2, concat_axis=1,
                                      tiled=True) for t in (q, k, v))
        attn = fa._sdpa_array(q, k, v, scale=1.0 / math.sqrt(d), causal=True)
        if sep_axis is not None:
            attn = lax.all_to_all(attn, sep_axis, split_axis=1, concat_axis=2,
                                  tiled=True)
    # named for the selective remat policy (remat_policy="attn"). NOTE the
    # measured verdict (BASELINE.md): the flash custom_vjp still replays
    # its forward to rematerialize the unsaved LSE, so saving these
    # outputs buys little and the extra live memory made it SLOWER than
    # full remat (51.4% vs 52.0% at the 7B geometry) — kept as a knob
    from jax.ad_checkpoint import checkpoint_name as _ckpt_name
    attn = _ckpt_name(attn, "attn_out")
    attn = attn.reshape(b, s, -1)
    out = jnp.einsum("bsd,dh->bsh", attn, gather_out(_dense(p["wo"])))
    if mp_axis is not None:
        out = lax.psum(out, mp_axis)
    # int8-quantized weights dequantize to f32 (weight_dequantize): pin
    # the residual carry dtype exactly like the serving scan paths do,
    # or every layer silently widens the whole activation stream to f32
    # (tpu-lint dtype-flow triage; no-op cast for dense bf16 weights)
    x = x + out.astype(x.dtype)

    xn = _rms(x, p["ln2"], config.rms_norm_eps)
    g = jnp.einsum("bsh,hm->bsm", xn, gather_in(_dense(p["w_gate"])))
    u = jnp.einsum("bsh,hm->bsm", xn, gather_in(_dense(p["w_up"])))
    dn = jnp.einsum("bsm,mh->bsh", jax.nn.silu(g) * u, gather_out(_dense(p["w_down"])))
    if mp_axis is not None:
        dn = lax.psum(dn, mp_axis)
    return x + dn.astype(x.dtype)


#: fsdp-sharded dim of each stacked layer weight (leading dim is L)
_ZG_DIM = {"wq": 1, "wk": 1, "wv": 1, "w_gate": 1, "w_up": 1,
           "wo": 2, "w_down": 2}


def build_hybrid_train_step(config: LlamaConfig, mesh: Mesh,
                            learning_rate: float = 1e-3,
                            remat: bool = True,
                            seq_shard: bool = False,
                            virtual_pp: int = 1,
                            remat_policy: str = "full",
                            pipeline_schedule: str = "fill_drain",
                            zero_gather: str = "per_layer",
                            k_steps: int = 1):
    """Returns (step_fn, init_fn).

    step_fn(params, opt_state, batch_ids, batch_labels) ->
        (loss, params, opt_state) — jitted, fully sharded.

    ``k_steps > 1`` compiles k optimizer steps into ONE dispatch
    (lax.scan over a leading k axis the batch arrays must then carry;
    the returned loss is the last step's). One host round-trip per k
    steps instead of per step.

    Parallelism inside: dp (batch), pp (ppermute pipeline: fill-drain, or
    the interleaved virtual-pipeline schedule when ``virtual_pp > 1`` —
    each pp stage holds virtual_pp strided layer chunks, cutting the
    bubble by that factor), mp (Megatron collectives), sharding (ZeRO-3
    weight sharding with per-layer all_gather), and — with
    ``seq_shard=True`` and a ``sep`` mesh axis — Ulysses context
    parallelism (activations sequence-sharded; all_to_all head/seq
    repartition around attention).
    Optimizer: fused AdamW (state sharded like the weights).

    ``pipeline_schedule``: "fill_drain" (default; becomes the interleaved
    virtual-pipeline schedule when virtual_pp > 1) or "1f1b" — the
    memory-scheduled one-forward-one-backward program
    (parallel/pipeline.py::pipeline_1f1b): O(stages) activation memory
    instead of O(microbatches), the schedule the reference's
    PipelineParallel runs by default (SURVEY.md §2.4 PP row). 1f1b
    composes with dp/mp/sharding; virtual_pp and seq_shard are
    fill-drain/interleave-only.

    Note: with virtual_pp > 1 the stacked layer arrays are stored in the
    interleave-permuted order (init_fn applies it); checkpoints of these
    params carry that layout.
    """
    from ..parallel import pipeline as ppipe

    if pipeline_schedule not in ("fill_drain", "1f1b"):
        raise ValueError(f"unknown pipeline_schedule {pipeline_schedule!r}")
    if zero_gather not in ("per_layer", "per_step"):
        raise ValueError(f"unknown zero_gather {zero_gather!r} "
                         "(expected 'per_layer' or 'per_step')")
    if zero_gather == "per_step" and pipeline_schedule == "1f1b":
        raise ValueError("zero_gather='per_step' is a fill-drain-family "
                         "option (1f1b gathers per layer)")
    if remat_policy not in ("full", "dots", "attn", "offload"):
        raise ValueError(f"unknown remat_policy {remat_policy!r} "
                         "(expected 'full', 'dots', 'attn' or 'offload')")
    if pipeline_schedule == "1f1b":
        if mesh.shape.get("pp", 1) <= 1:
            raise ValueError("pipeline_schedule='1f1b' needs a pp axis > 1")
        if virtual_pp > 1:
            raise ValueError("1f1b and virtual_pp are mutually exclusive "
                             "(interleave is a fill-drain-family schedule)")
        if seq_shard:
            raise ValueError("1f1b with sequence parallelism is not "
                             "supported; use the fill-drain schedule")

    pp = mesh.shape.get("pp", 1)
    mp = mesh.shape.get("mp", 1)
    sep = mesh.shape.get("sep", 1)
    sep_axis = "sep" if (seq_shard and sep > 1) else None
    if seq_shard and sep <= 1:
        raise ValueError("seq_shard=True requires a 'sep' mesh axis of size>1")
    sep_mode = getattr(config, "sep_mode", "ulysses")
    if sep_mode not in ("ulysses", "ring"):
        raise ValueError(f"unknown sep_mode {sep_mode!r} "
                         f"(expected 'ulysses' or 'ring')")
    if sep_axis is not None:
        nh, nkv = config.num_attention_heads, config.num_key_value_heads
        if sep_mode == "ulysses":
            # Ulysses repartitions heads over sep; ring never splits heads
            if nh % (mp * sep) or nkv % (mp * sep):
                raise ValueError(
                    f"Ulysses sep={sep} with mp={mp} needs heads divisible "
                    f"by mp*sep (got q={nh}, kv={nkv})")
        elif nh % mp or nkv % mp:
            raise ValueError(
                f"ring sep with mp={mp} needs heads divisible by mp "
                f"(got q={nh}, kv={nkv})")
    fsdp = mesh.shape.get("sharding", 1) * mesh.shape.get("dp", 1)
    mp_axis = "mp" if mp > 1 else None
    fsdp_axes = ("dp", "sharding")
    fsdp_axis = fsdp_axes if fsdp > 1 else None
    specs = stacked_param_specs(config)
    eps = config.rms_norm_eps

    vpp = max(int(virtual_pp), 1)
    if vpp > 1 and pp <= 1:
        raise ValueError("virtual_pp > 1 requires a pp mesh axis of size > 1")
    if config.num_hidden_layers % (pp * vpp):
        raise ValueError(
            f"num_hidden_layers {config.num_hidden_layers} must divide by "
            f"pp*virtual_pp = {pp * vpp}")
    layers_per_chunk = config.num_hidden_layers // (pp * vpp)
    if vpp > 1:
        # storage order: device-contiguous blocks hold strided model chunks
        layer_order = np.asarray(
            [c * layers_per_chunk + r
             for c in ppipe.interleave_chunk_order(pp, vpp)
             for r in range(layers_per_chunk)])
    else:
        layer_order = None

    # ---- closures shared by the fill-drain and 1f1b spmd bodies ------------
    def make_embed(params):
        """Token-embedding lookup; vocab-parallel over mp when sharded.
        Returns (embed_fn, vocab_shard_start, vocab_shard_size)."""
        if mp_axis is not None:
            per = params["embed"].shape[0]
            start = lax.axis_index(mp_axis) * per

            def embed(i):
                i32 = i.astype(jnp.int32) - start
                ok = (i32 >= 0) & (i32 < per)
                e = jnp.take(params["embed"], jnp.where(ok, i32, 0), axis=0)
                return lax.psum(jnp.where(ok[..., None], e, 0.0), mp_axis)

            return embed, start, per

        def embed(i):
            return jnp.take(params["embed"], i.astype(jnp.int32), axis=0)

        return embed, None, None

    def make_stage_fn(cos, sin, use_sep, stage_fsdp="default"):
        ax = sep_axis if use_sep else None
        fsdp = fsdp_axis if stage_fsdp == "default" else stage_fsdp

        def stage_fn(sparams, x):
            def layer_body(carry, lp):
                fn = functools.partial(_decoder_layer_manual, config=config,
                                       mp_axis=mp_axis, fsdp_axis=fsdp,
                                       sep_axis=ax)
                if remat:
                    if remat_policy == "dots":
                        # save matmul outputs, recompute elementwise/norms:
                        # backward skips the FLOP-heavy recompute of full
                        # remat at a modest activation-memory cost
                        fn = jax.checkpoint(
                            fn, policy=jax.checkpoint_policies.dots_saveable)
                    elif remat_policy == "attn":
                        # save only the flash-attention outputs: the one
                        # recompute with superlinear (S^2) cost
                        fn = jax.checkpoint(
                            fn, policy=jax.checkpoint_policies
                            .save_only_these_names("attn_out"))
                    elif remat_policy == "offload":
                        # VERDICT r3 item 9: stream the attention outputs
                        # to pinned HOST memory during forward and fetch
                        # them back for backward — no recompute, no HBM
                        # residency (core/offload.py's memory kind)
                        fn = jax.checkpoint(
                            fn, policy=jax.checkpoint_policies
                            .save_and_offload_only_these_names(
                                names_which_can_be_saved=[],
                                names_which_can_be_offloaded=["attn_out"],
                                offload_src="device",
                                offload_dst="pinned_host"))
                    else:
                        fn = jax.checkpoint(fn)
                return fn(lp, carry, cos, sin), None

            layer_params = {k: sparams[k] for k in LAYER_KEYS}
            x, _ = lax.scan(layer_body, x, layer_params)
            return x

        return stage_fn

    def head_ce(hp, y, lab):
        """ln_f + lm_head + token CE over arbitrary leading dims (mean)."""
        out = _rms(y, hp["ln_f"], eps)
        logits = jnp.einsum("...sh,hv->...sv", out, _dense(hp["lm_head"]))
        lg = logits.astype(jnp.float32)
        lab32 = lab.astype(jnp.int32)
        if mp_axis is not None:
            from ..distributed.meta_parallel.mp_layers import \
                vocab_parallel_ce_array
            return jnp.mean(vocab_parallel_ce_array(lg, lab32, mp_axis))
        logp = jax.nn.log_softmax(lg, axis=-1)
        picked = jnp.take_along_axis(logp, lab32[..., None],
                                     axis=-1)[..., 0]
        return -jnp.mean(picked)

    def spmd_loss(params, ids, labels):
        """Runs per-device inside shard_map. ids/labels: (M, mb_local, S_local)."""
        M, mb, S = ids.shape
        s_glob = S * sep if sep_axis is not None else S
        cos, sin = rope_ops.build_rope_cache(s_glob, config.head_dim,
                                             config.rope_theta)
        if sep_axis is not None:
            # RoPE runs pre-all_to_all on the local chunk: slice its positions
            off = lax.axis_index(sep_axis) * S
            cos = lax.dynamic_slice_in_dim(cos, off, S, axis=0)
            sin = lax.dynamic_slice_in_dim(sin, off, S, axis=0)

        embed, _, _ = make_embed(params)

        local = {k: params[k] for k in LAYER_KEYS}
        if zero_gather == "per_step" and fsdp_axis is not None:
            # ZeRO gather HOISTED above the microbatch loop and the remat
            # scope: weights gather ONCE per step (AD transposes it to one
            # reduce_scatter of the summed grads) instead of per microbatch
            # x remat replay — the dossier (benchmarks/bench_hybrid_cost.py)
            # measured the per-layer mode's sharding traffic scaling with
            # Lpd x M x replays and saturating the axis at pod microbatch
            # counts. Cost: the stage's full unsharded weights stay live
            # through backward (ZeRO-1-style memory for ZeRO-3 comm).
            local = {k: (lax.all_gather(v, fsdp_axis, axis=_ZG_DIM[k],
                                        tiled=True) if k in _ZG_DIM else v)
                     for k, v in local.items()}
            stage_fn = make_stage_fn(cos, sin, use_sep=True,
                                     stage_fsdp=None)
        else:
            stage_fn = make_stage_fn(cos, sin, use_sep=True)

        x = embed(ids)  # (M, mb, S, h)

        if pp > 1:
            if vpp > 1:
                # local leaves: (L/pp, ...) -> (vpp, layers_per_chunk, ...);
                # stage_fn scans whatever layer dim it receives, so it IS
                # the chunk function
                chunks = jax.tree_util.tree_map(
                    lambda a: a.reshape((vpp, layers_per_chunk) + a.shape[1:]),
                    local)
                out = ppipe.pipeline_spmd_interleaved(
                    stage_fn, chunks, x, vpp, axis_name="pp")
            else:
                out = ppipe.pipeline_spmd(stage_fn, local, x, axis_name="pp")
            out = ppipe.last_stage_broadcast(out, "pp")
        else:
            def micro_body(_, xm):
                return None, stage_fn(local, xm)
            _, out = lax.scan(micro_body, None, x)

        # lm_head spec P(None, 'mp') is sliced by shard_map, so logits are
        # vocab-sharded when mp>1 and head_ce runs the vocab-parallel CE
        loss = head_ce({"ln_f": params["ln_f"],
                        "lm_head": params["lm_head"]}, out, labels)
        # mean over dp/sharding batch shards (+ sep sequence shards)
        for ax in ("dp", "sharding"):
            if mesh.shape.get(ax, 1) > 1:
                loss = lax.pmean(loss, ax)
        if sep_axis is not None:
            loss = lax.pmean(loss, sep_axis)
        return loss

    def spmd_1f1b_loss_grads(params, ids, labels):
        """Per-device 1F1B: loss AND hand-scheduled grads in one program.

        The pipeline computes layer grads internally (jax.vjp per tick);
        the replication sums shard_map's AD transpose would have inserted
        (for replicated/partial-view tensors) are added explicitly below.
        """
        M, mb, S = ids.shape
        cos, sin = rope_ops.build_rope_cache(S, config.head_dim,
                                             config.rope_theta)
        embed, start, per = make_embed(params)
        stage_fn = make_stage_fn(cos, sin, use_sep=False)

        x = embed(ids)                                   # (M, mb, S, h)
        h = x.shape[-1]
        ids32 = ids.astype(jnp.int32)
        layer_params = {k: params[k] for k in LAYER_KEYS}
        head_params = {"ln_f": params["ln_f"],
                       "lm_head": params["lm_head"]}

        def gin_reducer(acc, gx, m_b):
            # embedding backward folded per backward tick: scatter-add this
            # microbatch's d loss/d x rows into the local vocab shard, so no
            # O(M) input-grad buffer rides the scan. gx is this mp slice's
            # PARTIAL gradient — psum first so every vocab shard sees the
            # full rows.
            g = gx.astype(jnp.float32)
            if mp_axis is not None:
                g = lax.psum(g, mp_axis)
            gf = g.reshape(-1, h)
            idx = lax.dynamic_index_in_dim(ids32, m_b, 0,
                                           keepdims=False).reshape(-1)
            if mp_axis is not None:
                local = idx - start
                ok = (local >= 0) & (local < per)
                return acc.at[jnp.where(ok, local, 0)].add(
                    jnp.where(ok[:, None], gf, 0.0))
            return acc.at[idx].add(gf)

        loss, lgrads, hgrads, gembed = ppipe.pipeline_1f1b(
            stage_fn, layer_params, x, labels, head_ce, axis_name="pp",
            head_params=head_params, strip_stage_dim=False,
            input_grad_reducer=gin_reducer,
            input_grad_init=jnp.zeros(params["embed"].shape, jnp.float32))
        loss = ppipe.last_stage_broadcast(loss, "pp")
        hgrads = jax.tree_util.tree_map(
            lambda a: ppipe.last_stage_broadcast(a, "pp"), hgrads)
        gembed = lax.psum(gembed, "pp")    # valid on stage 0 only

        if mp_axis is not None:
            # jax transposes psum as psum: the REPLICATED unit seed at the
            # loss head inflates by mp at its first psum crossing (the CE
            # denom/target psums), after which partial cotangents sum
            # correctly at every later crossing — so every grad below the
            # head is uniformly mp x too large. Rescale once.
            inv_mp = 1.0 / mesh.shape["mp"]
            lgrads = jax.tree_util.tree_map(lambda a: a * inv_mp, lgrads)
            hgrads = jax.tree_util.tree_map(lambda a: a * inv_mp, hgrads)
            gembed = gembed * inv_mp
            # ln grads are per-mp-slice partials (their consumers are the
            # column-sharded matmuls): sum them
            hgrads = {"ln_f": lax.psum(hgrads["ln_f"], mp_axis),
                      "lm_head": hgrads["lm_head"]}
            lgrads = {k: (lax.psum(v, mp_axis) if k in ("ln1", "ln2") else v)
                      for k, v in lgrads.items()}

        # batch shards: matmul grads arrive summed over (dp, sharding) via
        # the ZeRO all_gather transpose; replicated tensors need the psum;
        # everything needs 1/R for global-batch-mean semantics
        R = mesh.shape.get("dp", 1) * mesh.shape.get("sharding", 1)
        if R > 1:
            loss = lax.pmean(loss, ("dp", "sharding"))
            gembed = lax.psum(gembed, ("dp", "sharding"))
            hgrads = jax.tree_util.tree_map(
                lambda a: lax.psum(a, ("dp", "sharding")), hgrads)
            lgrads = {k: (lax.psum(v, ("dp", "sharding"))
                          if k in ("ln1", "ln2") else v)
                      for k, v in lgrads.items()}
            inv = 1.0 / R
            lgrads = {k: v * inv for k, v in lgrads.items()}
            hgrads = jax.tree_util.tree_map(lambda a: a * inv, hgrads)
            gembed = gembed * inv

        grads = dict(lgrads)
        grads["ln_f"] = hgrads["ln_f"]
        grads["lm_head"] = hgrads["lm_head"]
        grads["embed"] = gembed
        grads = {k: g.astype(params[k].dtype) for k, g in grads.items()}
        return loss, grads

    batch_in_spec = P(None, ("dp", "sharding"),
                      "sep" if sep_axis is not None else None)

    def loss_shardmapped(params, ids, labels):
        f = shard_map(
            spmd_loss, mesh=mesh,
            in_specs=(specs, batch_in_spec, batch_in_spec),
            out_specs=P(), check_vma=False)
        return f(params, ids, labels)

    def loss_and_grads_1f1b(params, ids, labels):
        f = shard_map(
            spmd_1f1b_loss_grads, mesh=mesh,
            in_specs=(specs, batch_in_spec, batch_in_spec),
            out_specs=(P(), specs), check_vma=False)
        return f(params, ids, labels)

    # --- fused AdamW over the sharded pytree --------------------------------
    b1, b2, adam_eps, wd = 0.9, 0.95, 1e-8, 0.1

    def init_fn(seed: int = 0):
        params = init_stacked_params(config, seed)
        if layer_order is not None:
            params = {k: (v[layer_order] if k in LAYER_KEYS else v)
                      for k, v in params.items()}
        params = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                  for k, v in params.items()}
        opt_state = {
            "step": jnp.zeros((), jnp.int32),
            "m": jax.tree_util.tree_map(lambda v: jnp.zeros_like(v, jnp.float32), params),
            "v": jax.tree_util.tree_map(lambda v: jnp.zeros_like(v, jnp.float32), params),
        }
        return params, opt_state

    state_specs = {"step": P(), "m": specs, "v": specs}

    def step(params, opt_state, ids, labels):
        if pipeline_schedule == "1f1b":
            loss, grads = loss_and_grads_1f1b(params, ids, labels)
        else:
            loss, grads = jax.value_and_grad(loss_shardmapped)(
                params, ids, labels)
        t = opt_state["step"] + 1

        def upd(p, g, m, v):
            g32 = g.astype(jnp.float32)
            m2 = b1 * m + (1 - b1) * g32
            v2 = b2 * v + (1 - b2) * g32 * g32
            mh = m2 / (1 - b1 ** t.astype(jnp.float32))
            vh = v2 / (1 - b2 ** t.astype(jnp.float32))
            p2 = p.astype(jnp.float32) - learning_rate * (
                mh / (jnp.sqrt(vh) + adam_eps) + wd * p.astype(jnp.float32))
            return p2.astype(p.dtype), m2, v2

        new_p, new_m, new_v = {}, {}, {}
        for k in params:
            new_p[k], new_m[k], new_v[k] = upd(params[k], grads[k],
                                               opt_state["m"][k],
                                               opt_state["v"][k])
        return loss, new_p, {"step": t, "m": new_m, "v": new_v}

    ns = lambda spec_tree: jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), spec_tree,
        is_leaf=lambda x: isinstance(x, P))
    if k_steps > 1:
        # k TRAINING STEPS per dispatch: one lax.scan over a leading
        # k-axis of the batch with the (params, opt_state) carry donated.
        # Amortizes the per-dispatch host cost (under the axon tunnel,
        # ~11 ms of dispatch + plumbing per call — the same lever that
        # took packed BERT 45.8%→50.5%, benchmarks/bench_workloads.py).
        # step_fn(params, opt_state, ids, labels) with ids/labels carrying
        # a leading k axis; returns the LAST step's loss.
        def multi(params, opt_state, ids, labels):
            def body(carry, batch):
                p, o = carry
                loss, p, o = step(p, o, batch[0], batch[1])
                return (p, o), loss
            (p, o), losses = jax.lax.scan(
                body, (params, opt_state), (ids, labels))
            return losses[-1], p, o

        kb_spec = P(None, *batch_in_spec)
        step_jit = jax.jit(
            multi,
            in_shardings=(ns(specs), ns(state_specs), ns(kb_spec), ns(kb_spec)),
            out_shardings=(NamedSharding(mesh, P()), ns(specs), ns(state_specs)),
            donate_argnums=(0, 1),
        )
        return step_jit, init_fn
    step_jit = jax.jit(
        step,
        in_shardings=(ns(specs), ns(state_specs), ns(batch_in_spec), ns(batch_in_spec)),
        out_shardings=(NamedSharding(mesh, P()), ns(specs), ns(state_specs)),
        donate_argnums=(0, 1),
    )
    return step_jit, init_fn


def microbatch(ids: np.ndarray, labels: np.ndarray, num_micro: int):
    """(B, S) -> (M, B/M, S)."""
    B = ids.shape[0]
    assert B % num_micro == 0
    return (ids.reshape(num_micro, B // num_micro, -1),
            labels.reshape(num_micro, B // num_micro, -1))


# ===========================================================================
# KV-cache inference path (serving: prefill + single-token decode)
# ===========================================================================
def init_kv_cache(config: LlamaConfig, batch: int, max_len: int, dtype=None):
    """Contiguous per-layer KV cache (L, B, S_max, n_kv, d). The paged
    variant for ragged serving batches lives in ops/paged_attention.py."""
    L = config.num_hidden_layers
    d = config.head_dim
    nkv = config.num_key_value_heads
    dt = dtype or config.dtype
    shape = (L, batch, max_len, nkv, d)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def _cached_attention(q, k_cache, v_cache, kv_len, config: LlamaConfig):
    """q: (B, T, nh, d); caches: (B, S_max, nkv, d); attend over [0, kv_len)
    with causality inside the current T block (query i sits at absolute
    position kv_len - T + i)."""
    b, t, nh, d = q.shape
    s_max = k_cache.shape[1]
    nkv = k_cache.shape[2]
    rep = nh // nkv
    q_pos = kv_len - t + jnp.arange(t)                      # (T,)
    mask = jnp.arange(s_max)[None, :] <= q_pos[:, None]     # (T, S_max)
    if rep > 1:
        # grouped attention WITHOUT materializing repeated KV: a
        # jnp.repeat here would stream rep x the cache bytes every decode
        # step — exactly the bandwidth GQA exists to save. Group the
        # query heads instead: (B, T, nkv, rep, d) against (B, S, nkv, d).
        qg = q.reshape(b, t, nkv, rep, d)
        scores = jnp.einsum("btgrd,bsgd->bgrts", qg.astype(jnp.float32),
                            k_cache.astype(jnp.float32)) / math.sqrt(d)
        scores = jnp.where(mask[None, None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bgrts,bsgd->btgrd",
                         probs.astype(v_cache.dtype), v_cache)
        return out.reshape(b, t, nh, d)
    scores = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                        k_cache.astype(jnp.float32)) / math.sqrt(d)
    scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs.astype(v_cache.dtype), v_cache)
    return out


def _decoder_layer_cached_full(lp, l, x, cos, sin, kf, vf, kv_len,
                               config: LlamaConfig):
    """One cached decoder layer operating on the FULL stacked cache
    (L, B, S_max, nkv, d): the new tokens write a (1, B, T, nkv, d) slab at
    layer ``l`` and attention reads the layer slice (the slice read fuses
    into the attention matmuls). This keeps the caches in the scan CARRY —
    scanning them as xs/ys (the old structure) made XLA write fresh ys
    cache buffers, a full cache copy per decode step."""
    b, t, h = x.shape
    d = config.head_dim
    xn = _rms(x, lp["ln1"], config.rms_norm_eps)
    q = _mm_prefill(xn, lp["wq"]).reshape(b, t, -1, d)
    k = _mm_prefill(xn, lp["wk"]).reshape(b, t, -1, d)
    v = _mm_prefill(xn, lp["wv"]).reshape(b, t, -1, d)
    q, k = rope_ops.apply_rope_array(q, k, cos, sin)
    start = kv_len - t
    kf = lax.dynamic_update_slice(kf, k.astype(kf.dtype)[None],
                                  (l, 0, start, 0, 0))
    vf = lax.dynamic_update_slice(vf, v.astype(vf.dtype)[None],
                                  (l, 0, start, 0, 0))
    kc = lax.dynamic_index_in_dim(kf, l, 0, keepdims=False)
    vc = lax.dynamic_index_in_dim(vf, l, 0, keepdims=False)
    attn = _cached_attention(q, kc, vc, kv_len, config)
    x = x + _mm_prefill(attn.reshape(b, t, -1), lp["wo"]).astype(x.dtype)
    xn = _rms(x, lp["ln2"], config.rms_norm_eps)
    g = _mm_prefill(xn, lp["w_gate"])
    u = _mm_prefill(xn, lp["w_up"])
    x = x + _mm_prefill(jax.nn.silu(g) * u, lp["w_down"]).astype(x.dtype)
    return x, kf, vf


def prefill_stacked(params, ids, cache, config: LlamaConfig):
    """Process the whole prompt, filling the cache.
    ids: (B, T) int32 (pad to a bucket length for shape stability).
    Returns (per-position logits (B, T, V), cache') — the caller picks the
    last *real* prompt position (right-padding makes position T-1 a pad)."""
    t = ids.shape[1]
    s_max = cache["k"].shape[2]
    cos_full, sin_full = rope_ops.build_rope_cache(s_max, config.head_dim,
                                                   config.rope_theta)
    x = jnp.take(params["embed"], ids.astype(jnp.int32), axis=0)
    kv_len = jnp.asarray(t, jnp.int32)

    def body(carry, lp_l):
        xc, kf, vf = carry
        lp, l = lp_l
        xo, kf, vf = _decoder_layer_cached_full(
            lp, l, xc, cos_full[:t], sin_full[:t], kf, vf, kv_len, config)
        # int8-quantized weights dequantize to f32; keep the carry dtype
        return (xo.astype(xc.dtype), kf, vf), None

    layer_params = {k: params[k] for k in LAYER_KEYS}
    (x, k_new, v_new), _ = lax.scan(
        body, (x, cache["k"], cache["v"]),
        (layer_params, jnp.arange(config.num_hidden_layers)))
    x = _rms(x, params["ln_f"], config.rms_norm_eps)
    logits = jnp.einsum("bth,hv->btv", x, _dense(params["lm_head"]))
    return logits, {"k": k_new, "v": v_new}


def decode_step_stacked(params, tok, pos, cache, config: LlamaConfig):
    """One generated token. tok: (B,) int32; pos: scalar int32 — absolute
    position of ``tok`` (so kv_len becomes pos+1). Returns (logits, cache')."""
    s_max = cache["k"].shape[2]
    cos_full, sin_full = rope_ops.build_rope_cache(s_max, config.head_dim,
                                                   config.rope_theta)
    x = jnp.take(params["embed"], tok.astype(jnp.int32), axis=0)[:, None, :]
    cos = lax.dynamic_slice_in_dim(cos_full, pos, 1, 0)
    sin = lax.dynamic_slice_in_dim(sin_full, pos, 1, 0)
    kv_len = pos + 1

    def body(carry, lp_l):
        xc, kf, vf = carry
        lp, l = lp_l
        xo, kf, vf = _decoder_layer_cached_full(lp, l, xc, cos, sin, kf, vf,
                                                kv_len, config)
        return (xo.astype(xc.dtype), kf, vf), None

    layer_params = {k: params[k] for k in LAYER_KEYS}
    (x, k_new, v_new), _ = lax.scan(
        body, (x, cache["k"], cache["v"]),
        (layer_params, jnp.arange(config.num_hidden_layers)))
    x = _rms(x, params["ln_f"], config.rms_norm_eps)
    logits = jnp.einsum("bh,hv->bv", x[:, 0], _dense(params["lm_head"]))
    return logits, {"k": k_new, "v": v_new}


# ===========================================================================
# Paged KV-cache path (ragged serving batches; ops/paged_attention.py)
# ===========================================================================
def _paged_prefill_layer(carry, lp_l, *, config, b, t, cos, sin, phys,
                         page_off, pool_p, attn_fn, scatter_first):
    """One transformer layer of a paged prefill — the single body shared
    by the full path (:func:`prefill_paged`) and the prefix-cache suffix
    path (:func:`prefill_paged_suffix`). The two differ ONLY in attention
    (in-prompt causal vs page-gather at offset positions — ``attn_fn``)
    and in whether the K/V scatter must precede it (the suffix attends
    THROUGH the pool, so its keys must land there first)."""
    xc, kp, vp = carry
    lp, l = lp_l
    d = config.head_dim
    xn = _rms(xc, lp["ln1"], config.rms_norm_eps)
    q = _mm_prefill(xn, lp["wq"]).reshape(b, t, -1, d)
    k = _mm_prefill(xn, lp["wk"]).reshape(b, t, -1, d)
    v = _mm_prefill(xn, lp["wv"]).reshape(b, t, -1, d)
    q, k = rope_ops.apply_rope_array(q, k, cos, sin)
    if scatter_first:
        kp = kp.at[phys + l * pool_p, page_off].set(k.astype(kp.dtype))
        vp = vp.at[phys + l * pool_p, page_off].set(v.astype(vp.dtype))
    attn = attn_fn(q, k, v, kp, vp, l)
    xo = xc + _mm_prefill(attn.reshape(b, t, -1), lp["wo"]).astype(xc.dtype)
    xn2 = _rms(xo, lp["ln2"], config.rms_norm_eps)
    g = _mm_prefill(xn2, lp["w_gate"])
    u = _mm_prefill(xn2, lp["w_up"])
    xo = xo + jnp.einsum("btm,mh->bth", jax.nn.silu(g) * u,
                         _dense(lp["w_down"]))
    if not scatter_first:
        # scatter this layer's K/V into its slab of the flat pool
        kp = kp.at[phys + l * pool_p, page_off].set(k.astype(kp.dtype))
        vp = vp.at[phys + l * pool_p, page_off].set(v.astype(vp.dtype))
    # int8-quantized weights dequantize to f32; keep the carry dtype
    return (xo.astype(xc.dtype), kp, vp), None


def prefill_paged(params, ids, seq_lens, k_pages, v_pages, block_tables,
                  config: LlamaConfig):
    """Prefill a ragged batch into paged KV.

    ids: (B, T) right-padded prompts; seq_lens: (B,) true lengths;
    k_pages/v_pages: (L, P, page, nkv, d); block_tables: (B, max_pages),
    padded slots pointing at reserved page 0.
    Returns (logits (B, T, V), k_pages', v_pages').
    """
    b, t = ids.shape
    page = k_pages.shape[2]
    cos, sin = rope_ops.build_rope_cache(t, config.head_dim, config.rope_theta)
    x = jnp.take(params["embed"], ids.astype(jnp.int32), axis=0)

    # scatter indices for every (b, t) slot: pad tokens land in page 0
    tpos = jnp.arange(t)
    page_idx = tpos[None, :] // page                      # (B, T)
    page_off = tpos[None, :] % page
    phys = jnp.take_along_axis(block_tables, page_idx, axis=1)  # (B, T)
    valid = tpos[None, :] < seq_lens[:, None]
    phys = jnp.where(valid, phys, 0)

    # Pools travel FLAT (L*P, page, nkv, d) in the scan CARRY with
    # per-layer page-id offsets l*P. Scanning them as xs->ys (the old
    # structure) forced XLA to write fresh ys pool buffers — a full copy
    # of both pools per call; carried scatters update in place. The
    # manager reserves page 0, so every layer slab's page l*P+0 is the
    # garbage page and padded block-table slots stay safe after offset.
    n_layers, pool_p = k_pages.shape[0], k_pages.shape[1]
    kp_flat = k_pages.reshape((n_layers * pool_p,) + k_pages.shape[2:])
    vp_flat = v_pages.reshape((n_layers * pool_p,) + v_pages.shape[2:])

    body = functools.partial(
        _paged_prefill_layer, config=config, b=b, t=t, cos=cos, sin=sin,
        phys=phys, page_off=page_off, pool_p=pool_p,
        # causal attention within the (padded) prompt
        attn_fn=lambda q, k, v, kp, vp, l: fa._sdpa_array(
            q, k, v, scale=1.0 / math.sqrt(config.head_dim), causal=True),
        scatter_first=False)
    layer_params = {k: params[k] for k in LAYER_KEYS}
    (x, kp_flat, vp_flat), _ = lax.scan(
        body, (x, kp_flat, vp_flat),
        (layer_params, jnp.arange(n_layers)))
    x = _rms(x, params["ln_f"], config.rms_norm_eps)
    logits = jnp.einsum("bth,hv->btv", x, _dense(params["lm_head"]))
    return (logits, kp_flat.reshape(k_pages.shape),
            vp_flat.reshape(v_pages.shape))


def prefill_paged_suffix(params, ids, seq_lens, start_pos, k_pages, v_pages,
                         block_tables, config: LlamaConfig):
    """Prefill only the UNCACHED SUFFIX of a ragged batch into paged KV.

    The prefix-cache path (paddle_tpu.kvcache): each row's leading
    ``start_pos[b]`` tokens are already resident in shared pages reachable
    through ``block_tables``, so only the suffix runs through the model.
    Suffix queries sit at absolute positions ``start_pos + t`` — rope is
    taken at those positions and attention runs over the gathered page
    span (cached prefix + just-scattered suffix) with the
    ``key_pos <= query_pos`` mask (ops.paged_attention.
    paged_prefill_attention_array), not the in-prompt causal mask.

    ids: (B, T) right-padded suffix tokens; seq_lens: (B,) true suffix
    lengths; start_pos: (B,) cached-prefix lengths (0 = cold row);
    k_pages/v_pages: (L, P, page, nkv, d); block_tables: (B, max_pages).
    Returns (logits (B, T, V), k_pages', v_pages').
    """
    from ..ops import paged_attention as pa
    b, t = ids.shape
    page = k_pages.shape[2]
    s_max = block_tables.shape[1] * page
    cos_full, sin_full = rope_ops.build_rope_cache(s_max, config.head_dim,
                                                   config.rope_theta)
    x = jnp.take(params["embed"], ids.astype(jnp.int32), axis=0)

    tpos = jnp.arange(t)
    start_pos = start_pos.astype(jnp.int32)
    # clamp: a padded suffix bucket may poke past the table span; those
    # slots are invalid (masked below) but the gathers must stay in range
    abs_pos = jnp.minimum(start_pos[:, None] + tpos[None, :], s_max - 1)
    cos = jnp.take(cos_full, abs_pos, axis=0)             # (B, T, d)
    sin = jnp.take(sin_full, abs_pos, axis=0)
    page_idx = abs_pos // page                            # (B, T)
    page_off = abs_pos % page
    phys = jnp.take_along_axis(block_tables, page_idx, axis=1)
    valid = tpos[None, :] < seq_lens[:, None]
    phys = jnp.where(valid, phys, 0)                      # pads -> page 0

    # flat-pool carry with per-layer page offsets — see prefill_paged
    n_layers, pool_p = k_pages.shape[0], k_pages.shape[1]
    kp_flat = k_pages.reshape((n_layers * pool_p,) + k_pages.shape[2:])
    vp_flat = v_pages.reshape((n_layers * pool_p,) + v_pages.shape[2:])

    body = functools.partial(
        _paged_prefill_layer, config=config, b=b, t=t, cos=cos, sin=sin,
        phys=phys, page_off=page_off, pool_p=pool_p,
        # scatter the suffix K/V FIRST (scatter_first) so attention sees
        # cached prefix + suffix through one page gather
        attn_fn=lambda q, k, v, kp, vp, l: pa.paged_prefill_attention_array(
            q, kp, vp, block_tables + l * pool_p, start_pos,
            scale=1.0 / math.sqrt(config.head_dim)),
        scatter_first=True)
    layer_params = {k: params[k] for k in LAYER_KEYS}
    (x, kp_flat, vp_flat), _ = lax.scan(
        body, (x, kp_flat, vp_flat),
        (layer_params, jnp.arange(n_layers)))
    x = _rms(x, params["ln_f"], config.rms_norm_eps)
    logits = jnp.einsum("bth,hv->btv", x, _dense(params["lm_head"]))
    return (logits, kp_flat.reshape(k_pages.shape),
            vp_flat.reshape(v_pages.shape))


def ragged_step(params, ids, token_row, positions, kv_lens, last_idx,
                k_pages, v_pages, block_tables, config: LlamaConfig,
                mesh: Optional[Mesh] = None, mp_axis: str = "mp",
                logits_epilogue=None):
    """One forward over a RAGGED packed token batch — the unified model
    step behind the engine's single-dispatch serving loop.

    Mixed prefill+decode in one program: every live row contributes a
    span of the flat token axis (a decode row its one new token, a
    prefill row the next chunk of its prompt — a warm/COW suffix row is
    just "a row whose first position > 0"). Rope is taken at each
    token's absolute position, K/V scatter into the row's pages, and
    attention is the ragged paged kernel's one mask rule
    ``key_pos <= position`` (ops.paged_attention.ragged_paged_attention),
    which subsumes the in-prompt causal mask, the suffix offset mask and
    the decode ``kv_len`` mask. The compiled shape depends only on
    (T, rows, table width) — never on the request mix.

    ids:       (T,) int32 packed tokens (pad slots: anything)
    token_row: (T,) int32 owning row per token; -1 = pad slot
    positions: (T,) int32 absolute KV position per token
    kv_lens:   (R,) int32 per-row attendable span this call (0 = idle)
    last_idx:  (C,) int32 flat token indices to take logits at. The
               unified engine passes one per row (C == R, each row's
               last token); the speculative engine passes PER-CANDIDATE
               indices (C == R * (k+1)) — every token of a drafted span
               yields its own next-token logits, which is what turns the
               single dispatch into the draft verifier. Unused entries
               may point anywhere; callers mask the resulting logits.
    k_pages/v_pages: (L, P, page, nkv, d); block_tables: (R, max_pages)
    Returns (logits (C, V), k_pages', v_pages').

    Multi-chip TP (``mesh`` given, mp degree > 1): weights are placed by
    ``shard_params_tp`` and the paged pools head-sharded over ``mp_axis``
    (``PagedKVCacheManager.shard_heads``) — on the XLA path GSPMD
    partitions every einsum/gather from those layouts alone (attention
    is head-parallel, ``wo``/``w_down`` become partial-sum all-reduces),
    so the traced program here is UNCHANGED and the mesh is only
    forwarded to the attention dispatcher for the Pallas kernel, which
    cannot be auto-partitioned and runs under ``shard_map`` with each
    chip's GQA group slice instead.
    """
    from ..ops import paged_attention as pa
    t = ids.shape[0]
    d = config.head_dim
    page = k_pages.shape[2]
    n_rows, width = block_tables.shape
    s_max = width * page
    cos_full, sin_full = rope_ops.build_rope_cache(s_max, config.head_dim,
                                                   config.rope_theta)
    # clamp: over-decoded tokens past the table span land in the last
    # slot (their outputs are trimmed by the host, same as the legacy
    # decode path's clipped take_along_axis)
    pos_c = jnp.minimum(positions.astype(jnp.int32), s_max - 1)
    cos = jnp.take(cos_full, pos_c, axis=0)[None]          # (1, T, d)
    sin = jnp.take(sin_full, pos_c, axis=0)[None]
    x = jnp.take(params["embed"], ids.astype(jnp.int32), axis=0)[None]

    valid = token_row >= 0
    row_c = jnp.clip(token_row.astype(jnp.int32), 0, n_rows - 1)
    page_idx = pos_c // page
    page_off = pos_c % page
    phys = jnp.take(block_tables.reshape(-1), row_c * width + page_idx)
    phys = jnp.where(valid, phys, 0)                       # pads -> page 0

    # flat-pool carry with per-layer page offsets — see prefill_paged's
    # structure note (pools as scan xs/ys would copy both pools per step)
    n_layers, pool_p = k_pages.shape[0], k_pages.shape[1]
    kp_flat = k_pages.reshape((n_layers * pool_p,) + k_pages.shape[2:])
    vp_flat = v_pages.reshape((n_layers * pool_p,) + v_pages.shape[2:])

    def body(carry, lp_l):
        xc, kp, vp = carry
        lp, l = lp_l
        xn = _rms(xc, lp["ln1"], config.rms_norm_eps)
        q = _mm_prefill(xn, lp["wq"]).reshape(1, t, -1, d)
        k = _mm_prefill(xn, lp["wk"]).reshape(1, t, -1, d)
        v = _mm_prefill(xn, lp["wv"]).reshape(1, t, -1, d)
        q, k = rope_ops.apply_rope_array(q, k, cos, sin)
        # scatter FIRST: every token (decode and prefill alike) attends
        # through the page gather, its own fresh K/V included
        kp = kp.at[phys + l * pool_p, page_off].set(k[0].astype(kp.dtype))
        vp = vp.at[phys + l * pool_p, page_off].set(v[0].astype(vp.dtype))
        attn = pa.ragged_paged_attention(
            q[0], kp, vp, block_tables + l * pool_p, token_row, pos_c,
            kv_lens, scale=1.0 / math.sqrt(d),
            mesh=mesh, mp_axis=mp_axis)                    # (T, nh, d)
        xo = xc + _mm_prefill(attn.reshape(1, t, -1),
                              lp["wo"]).astype(xc.dtype)
        xn2 = _rms(xo, lp["ln2"], config.rms_norm_eps)
        g = _mm_prefill(xn2, lp["w_gate"])
        u = _mm_prefill(xn2, lp["w_up"])
        xo = xo + jnp.einsum("btm,mh->bth", jax.nn.silu(g) * u,
                             _dense(lp["w_down"]))
        # int8-quantized weights dequantize to f32; keep the carry dtype
        return (xo.astype(xc.dtype), kp, vp), None

    layer_params = {k: params[k] for k in LAYER_KEYS}
    (x, kp_flat, vp_flat), _ = lax.scan(
        body, (x, kp_flat, vp_flat),
        (layer_params, jnp.arange(n_layers)))
    x = _rms(x, params["ln_f"], config.rms_norm_eps)
    # lm_head over ONLY each row's last token: (R, h) @ (h, V), not the
    # full (T, V) logits the bucketed prefill paid for
    h_last = jnp.take(x[0], last_idx.astype(jnp.int32), axis=0)
    logits = jnp.einsum("rh,hv->rv", h_last, _dense(params["lm_head"]))
    if logits_epilogue is not None:
        # in-program hook over the per-row logits (e.g. the grammar
        # mask of inference.constrain — applied BEFORE any sampling
        # epilogue so constrained rows renormalize over legal tokens)
        logits = logits_epilogue(logits)
    return (logits, kp_flat.reshape(k_pages.shape),
            vp_flat.reshape(v_pages.shape))


def decode_step_paged(params, tok, positions, k_pages, v_pages, block_tables,
                      config: LlamaConfig):
    """One ragged decode step. tok: (B,); positions: (B,) absolute position
    of each row's new token (may differ per row). Returns
    (logits (B, V), k_pages', v_pages')."""
    from ..ops import paged_attention as pa
    b = tok.shape[0]
    d = config.head_dim
    s_max = block_tables.shape[1] * k_pages.shape[2]
    cos_full, sin_full = rope_ops.build_rope_cache(s_max, config.head_dim,
                                                   config.rope_theta)
    x = jnp.take(params["embed"], tok.astype(jnp.int32), axis=0)[:, None, :]
    cos = jnp.take(cos_full, positions, axis=0)[:, None, :]  # (B, 1, d)
    sin = jnp.take(sin_full, positions, axis=0)[:, None, :]
    kv_lens = positions + 1

    # flat-pool carry with per-layer page offsets — see prefill_paged's
    # structure note (pools as scan xs/ys would copy both pools per STEP,
    # ~1.5 GB at serving scale; carried scatters are in place)
    n_layers, pool_p = k_pages.shape[0], k_pages.shape[1]
    kp_flat = k_pages.reshape((n_layers * pool_p,) + k_pages.shape[2:])
    vp_flat = v_pages.reshape((n_layers * pool_p,) + v_pages.shape[2:])

    def body(carry, lp_l):
        xc, kp, vp = carry
        lp, l = lp_l
        bt_l = block_tables + l * pool_p
        xn = _rms(xc, lp["ln1"], config.rms_norm_eps)
        q = jnp.einsum("bth,hd->btd", xn, _dense(lp["wq"])).reshape(b, 1, -1, d)
        k = jnp.einsum("bth,hd->btd", xn, _dense(lp["wk"])).reshape(b, 1, -1, d)
        v = jnp.einsum("bth,hd->btd", xn, _dense(lp["wv"])).reshape(b, 1, -1, d)
        q2, k2 = rope_ops.apply_rope_array(q, k, cos, sin)  # (B,1,d) 3-D form
        kp, vp = pa.paged_write_array(kp, vp, k2[:, 0], v[:, 0],
                                      bt_l, positions)
        attn = pa.paged_attention(q2[:, 0], kp, vp, bt_l,
                                  kv_lens, scale=1.0 / math.sqrt(d))
        xo = xc + jnp.einsum("bd,dh->bh", attn.reshape(b, -1),
                             _dense(lp["wo"]))[:, None, :]
        xn2 = _rms(xo, lp["ln2"], config.rms_norm_eps)
        g = _mm_prefill(xn2, lp["w_gate"])
        u = _mm_prefill(xn2, lp["w_up"])
        xo = xo + jnp.einsum("btm,mh->bth", jax.nn.silu(g) * u, _dense(lp["w_down"]))
        # int8-quantized weights dequantize to f32; keep the carry dtype
        return (xo.astype(xc.dtype), kp, vp), None

    layer_params = {k: params[k] for k in LAYER_KEYS}
    (x, kp_flat, vp_flat), _ = lax.scan(
        body, (x, kp_flat, vp_flat),
        (layer_params, jnp.arange(n_layers)))
    x = _rms(x, params["ln_f"], config.rms_norm_eps)
    logits = jnp.einsum("bh,hv->bv", x[:, 0], _dense(params["lm_head"]))
    return (logits, kp_flat.reshape(k_pages.shape),
            vp_flat.reshape(v_pages.shape))
