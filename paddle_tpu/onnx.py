"""``paddle_tpu.onnx`` — export surface.

The reference's ``paddle.onnx.export`` is a thin wrapper that imports
the OPTIONAL external ``paddle2onnx`` package and raises if absent
(python/paddle/onnx/export.py:§0). This environment has no onnx
runtime/converter, and the framework's native serialized program format
is StableHLO (``paddle_tpu.jit.save`` — portable, versioned, loadable
by any XLA-bearing runtime), which plays the deployment-artifact role
ONNX plays for the reference. ``export`` therefore either delegates to
a present ``paddle2onnx``-compatible converter or raises the same
actionable ImportError the reference does, pointing at the StableHLO
path.
"""

from __future__ import annotations

__all__ = ["export"]


def export(layer, path: str, input_spec=None, opset_version: int = 9,
           **configs):
    """Reference-parity paddle.onnx.export. See module docstring."""
    try:
        import paddle2onnx  # noqa: F401
    except ImportError:
        raise ImportError(
            "paddle.onnx.export needs the optional 'paddle2onnx' package "
            "(the reference has the same requirement), which is not "
            "installed here. For a portable deployment artifact use "
            "paddle_tpu.jit.save(layer, path, input_spec=...) — it emits "
            "a StableHLO program + params loadable by any XLA runtime.")
    raise NotImplementedError(
        "a paddle2onnx install was found, but the converter bridge for "
        "this framework is not implemented; use paddle_tpu.jit.save "
        "(StableHLO) for deployment")
