"""Bijective transforms + TransformedDistribution.

Reference: python/paddle/distribution/transform.py:§0 (Transform,
AffineTransform, ExpTransform, SigmoidTransform, TanhTransform,
PowerTransform, AbsTransform, ChainTransform, StackTransform,
IndependentTransform) and transformed_distribution.py:§0. Forward /
inverse / log_det_jacobian are jnp expressions, so transformed
log_probs trace and differentiate like everything else.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from ..core.tensor import Tensor
from . import Distribution, _val

__all__ = [
    "Transform", "AffineTransform", "ExpTransform", "SigmoidTransform",
    "TanhTransform", "PowerTransform", "AbsTransform", "ChainTransform",
    "IndependentTransform", "StackTransform", "TransformedDistribution",
]


class Transform:
    """Bijection y = f(x). Subclasses implement ``_forward``,
    ``_inverse`` and ``_forward_log_det_jacobian`` on jax arrays."""

    #: dims of a single event the jacobian is computed over (0 = scalar)
    event_dim = 0

    def forward(self, x):
        return Tensor(self._forward(_val(x)))

    def inverse(self, y):
        return Tensor(self._inverse(_val(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(self._forward_log_det_jacobian(_val(x)))

    def inverse_log_det_jacobian(self, y):
        return Tensor(-self._forward_log_det_jacobian(
            self._inverse(_val(y))))


class AffineTransform(Transform):
    """y = loc + scale * x."""

    def __init__(self, loc, scale):
        self.loc = _val(loc)
        self.scale = _val(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    """y = exp(x)."""

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class SigmoidTransform(Transform):
    """y = sigmoid(x)."""

    def _forward(self, x):
        return 1.0 / (1.0 + jnp.exp(-x))

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        # log σ'(x) = -softplus(-x) - softplus(x)
        sp = lambda v: jnp.logaddexp(v, 0.0)  # noqa: E731
        return -sp(-x) - sp(x)


class TanhTransform(Transform):
    """y = tanh(x)."""

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh²x) = 2(log2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x - jnp.logaddexp(-2.0 * x, 0.0))


class PowerTransform(Transform):
    """y = x^power (x > 0)."""

    def __init__(self, power):
        self.power = _val(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class AbsTransform(Transform):
    """y = |x| — not bijective; inverse returns the positive branch
    (reference behaviour)."""

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class ChainTransform(Transform):
    """Composition (applied left to right on forward)."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._forward_log_det_jacobian(x)
            x = t._forward(x)
        return total


class IndependentTransform(Transform):
    """Reinterprets ``reinterpreted_batch_rank`` trailing batch dims of a
    base transform as event dims (jacobian sums over them)."""

    def __init__(self, base, reinterpreted_batch_rank: int):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        self.event_dim = base.event_dim + self.rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ld = self.base._forward_log_det_jacobian(x)
        return ld.sum(axis=tuple(range(-self.rank, 0)))


class StackTransform(Transform):
    """Applies the i-th transform to the i-th slice along ``axis``."""

    def __init__(self, transforms, axis: int = 0):
        self.transforms = list(transforms)
        self.axis = axis

    def _map(self, x, attr):
        parts = jnp.split(x, len(self.transforms), axis=self.axis)
        outs = [getattr(t, attr)(p.squeeze(self.axis))
                for t, p in zip(self.transforms, parts)]
        return jnp.stack(outs, axis=self.axis)

    def _forward(self, x):
        return self._map(x, "_forward")

    def _inverse(self, y):
        return self._map(y, "_inverse")

    def _forward_log_det_jacobian(self, x):
        return self._map(x, "_forward_log_det_jacobian")


class TransformedDistribution(Distribution):
    """base distribution pushed through a transform chain
    (reference transformed_distribution.py): sample = f(base.sample()),
    log_prob(y) = base.log_prob(f⁻¹(y)) - log|det J_f(f⁻¹(y))|."""

    def __init__(self, base: Distribution, transforms):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transform = ChainTransform(list(transforms))

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return self.transform.forward(x)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        return self.transform.forward(x)

    def log_prob(self, value) -> Tensor:
        y = _val(value)
        x = self.transform._inverse(y)
        base_lp = _val(self.base.log_prob(Tensor(x)))
        ld = self.transform._forward_log_det_jacobian(x)
        # a base with event dims (Dirichlet, MultivariateNormal) returns
        # log_prob with those dims reduced; sum the element-wise log-det
        # over the same trailing dims so shapes agree instead of
        # silently broadcasting to a wrong per-component result
        extra = ld.ndim - jnp.ndim(base_lp)
        if extra > 0:
            ld = ld.sum(axis=tuple(range(-extra, 0)))
        return Tensor(base_lp - ld)
