"""``paddle_tpu.distribution`` — probability distributions.

Rebuild of python/paddle/distribution/ (Normal, Uniform, Categorical,
Bernoulli, kl_divergence — SURVEY.md §2.1 kernel-corpus gap list /
VERDICT round-1 "distribution ops"). Sampling uses the framework PRNG-key
stream (paddle_tpu.random), so results are reproducible under paddle.seed
and replayable inside jit traces.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from .. import random as _random


def _val(x):
    if isinstance(x, Tensor):
        v = x._value
        # int/bool parameters (e.g. Chi2(to_tensor(4))) would poison the
        # float closed forms (full_like(df, 0.5) truncates to 0)
        if not jnp.issubdtype(v.dtype, jnp.inexact):
            v = v.astype(jnp.float32)
        return v
    return jnp.asarray(x, jnp.float32)


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value) -> Tensor:
        raise NotImplementedError

    def prob(self, value) -> Tensor:
        return apply(jnp.exp, self.log_prob(value), op_name="dist_prob")

    def entropy(self) -> Tensor:
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)

    @property
    def mean(self) -> Tensor:
        return Tensor(jnp.broadcast_to(self.loc, jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape)))

    @property
    def variance(self) -> Tensor:
        return Tensor(jnp.broadcast_to(self.scale ** 2, jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape)))

    def sample(self, shape=()):
        key = _random.next_key()
        base = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        eps = jax.random.normal(key, tuple(shape) + base, jnp.float32)
        return Tensor(self.loc + self.scale * eps)

    rsample = sample  # reparameterized by construction

    def log_prob(self, value) -> Tensor:
        def fn(v):
            var = self.scale ** 2
            return (-((v - self.loc) ** 2) / (2 * var)
                    - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))
        return apply(fn, value, op_name="normal_log_prob")

    def entropy(self) -> Tensor:
        base = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        return Tensor(jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale), base))

    def kl_divergence(self, other: "Normal") -> Tensor:
        var_a, var_b = self.scale ** 2, other.scale ** 2
        return Tensor(0.5 * (var_a / var_b
                             + (self.loc - other.loc) ** 2 / var_b
                             - 1.0 + jnp.log(var_b) - jnp.log(var_a)))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _val(low)
        self.high = _val(high)

    def sample(self, shape=()):
        key = _random.next_key()
        base = jnp.broadcast_shapes(self.low.shape, self.high.shape)
        u = jax.random.uniform(key, tuple(shape) + base, jnp.float32)
        return Tensor(self.low + (self.high - self.low) * u)

    rsample = sample

    def log_prob(self, value) -> Tensor:
        def fn(v):
            inside = (v >= self.low) & (v < self.high)
            lp = -jnp.log(self.high - self.low)
            return jnp.where(inside, lp, -jnp.inf)
        return apply(fn, value, op_name="uniform_log_prob")

    def entropy(self) -> Tensor:
        return Tensor(jnp.log(self.high - self.low))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _val(probs)

    def sample(self, shape=()):
        key = _random.next_key()
        out = jax.random.bernoulli(key, self.probs,
                                   tuple(shape) + self.probs.shape)
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value) -> Tensor:
        def fn(v):
            p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return apply(fn, value, op_name="bernoulli_log_prob")

    def entropy(self) -> Tensor:
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _val(logits)

    @property
    def probs(self) -> Tensor:
        return Tensor(jax.nn.softmax(self.logits, axis=-1))

    def sample(self, shape=()):
        key = _random.next_key()
        out = jax.random.categorical(key, self.logits, axis=-1,
                                     shape=tuple(shape)
                                     + self.logits.shape[:-1])
        # int64 only exists under jax_enable_x64; int32 avoids the per-call
        # truncation warning with the same values
        return Tensor(out.astype(jnp.int32))

    def log_prob(self, value) -> Tensor:
        def fn(v):
            logp = jax.nn.log_softmax(self.logits, axis=-1)
            vi = v.astype(jnp.int32)
            if logp.ndim == 1:  # shared categories, batched values
                return jnp.take(logp, vi, axis=0)
            return jnp.take_along_axis(logp, vi[..., None], axis=-1)[..., 0]
        return apply(fn, value, op_name="categorical_log_prob")

    def entropy(self) -> Tensor:
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        p = jnp.exp(logp)
        return Tensor(-jnp.sum(p * logp, axis=-1))

    def kl_divergence(self, other: "Categorical") -> Tensor:
        la = jax.nn.log_softmax(self.logits, axis=-1)
        lb = jax.nn.log_softmax(other.logits, axis=-1)
        return Tensor(jnp.sum(jnp.exp(la) * (la - lb), axis=-1))


#: closed-form same-family KLs for the extended zoo (reference
#: python/paddle/distribution/kl.py's _REGISTER_TABLE):§0
_KL_REGISTRY = {}


def register_kl(type_p, type_q):
    def deco(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    # most-specific matching (super)class pair wins, like the reference
    # kl.py's dispatch — so Chi2 resolves to the (Gamma, Gamma) form
    for tp in type(p).__mro__:
        for tq in type(q).__mro__:
            fn = _KL_REGISTRY.get((tp, tq))
            if fn is not None:
                return fn(p, q)
    if type(p) is type(q) and hasattr(p, "kl_divergence"):
        return p.kl_divergence(q)
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})")


from .extras import (  # noqa: E402,F401
    Beta, Binomial, Cauchy, Chi2, Dirichlet, Exponential,
    ExponentialFamily, Gamma, Geometric, Gumbel, Laplace, LogNormal,
    Multinomial, MultivariateNormal, Poisson, StudentT,
)
from .transform import (  # noqa: E402,F401
    AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, SigmoidTransform,
    StackTransform, TanhTransform, Transform, TransformedDistribution,
)
from jax.scipy import special as _jsp  # noqa: E402


@register_kl(Exponential, Exponential)
def _kl_exp(p, q):
    r = q.rate / p.rate
    return Tensor(jnp.log(p.rate) - jnp.log(q.rate) + r - 1.0)


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    a1, b1, a2, b2 = p.concentration, p.rate, q.concentration, q.rate
    return Tensor((a1 - a2) * _jsp.digamma(a1)
                  - _jsp.gammaln(a1) + _jsp.gammaln(a2)
                  + a2 * (jnp.log(b1) - jnp.log(b2))
                  + a1 * (b2 - b1) / b1)


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    lb = lambda a, b: (_jsp.gammaln(a) + _jsp.gammaln(b)  # noqa: E731
                       - _jsp.gammaln(a + b))
    return Tensor(lb(a2, b2) - lb(a1, b1)
                  + (a1 - a2) * _jsp.digamma(a1)
                  + (b1 - b2) * _jsp.digamma(b1)
                  + (a2 - a1 + b2 - b1) * _jsp.digamma(a1 + b1))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    c1, c2 = p.concentration, q.concentration
    s1 = c1.sum(-1)
    return Tensor(_jsp.gammaln(s1) - _jsp.gammaln(c2.sum(-1))
                  - jnp.sum(_jsp.gammaln(c1), -1)
                  + jnp.sum(_jsp.gammaln(c2), -1)
                  + jnp.sum((c1 - c2) * (_jsp.digamma(c1)
                                         - _jsp.digamma(s1)[..., None]), -1))


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    d = jnp.abs(p.loc - q.loc)
    r = p.scale / q.scale
    return Tensor(jnp.log(q.scale) - jnp.log(p.scale) + d / q.scale
                  + r * jnp.exp(-d / p.scale) - 1.0)
