"""The reference distribution zoo beyond the round-1 four.

Reference: python/paddle/distribution/{beta,binomial,cauchy,chi2,
dirichlet,exponential,gamma,geometric,gumbel,laplace,lognormal,
multinomial,multivariate_normal,poisson,student_t}.py:§0. Sampling
draws from jax.random with the framework PRNG stream
(paddle_tpu.random), so paddle.seed reproduces and everything replays
under jit; log_prob/entropy are the closed forms.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from .. import random as _random
from . import Distribution, _val

__all__ = [
    "Exponential", "Gamma", "Beta", "Dirichlet", "Laplace", "LogNormal",
    "Gumbel", "Cauchy", "Chi2", "StudentT", "Poisson", "Geometric",
    "Binomial", "Multinomial", "MultivariateNormal", "ExponentialFamily",
]


class ExponentialFamily(Distribution):
    """Marker base mirroring the reference's ExponentialFamily (its
    Bregman-entropy shortcut is unnecessary here — every subclass has a
    closed-form entropy)."""


class Exponential(ExponentialFamily):
    def __init__(self, rate, name=None):
        self.rate = _val(rate)

    @property
    def mean(self):
        return Tensor(1.0 / self.rate)

    @property
    def variance(self):
        return Tensor(1.0 / self.rate ** 2)

    def sample(self, shape=()):
        key = _random.next_key()
        e = jax.random.exponential(key, tuple(shape) + self.rate.shape)
        return Tensor(e / self.rate)

    rsample = sample

    def log_prob(self, value):
        return apply(lambda v: jnp.log(self.rate) - self.rate * v,
                     value, op_name="exponential_log_prob")

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Gamma(ExponentialFamily):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _val(concentration)
        self.rate = _val(rate)

    @property
    def mean(self):
        return Tensor(self.concentration / self.rate)

    @property
    def variance(self):
        return Tensor(self.concentration / self.rate ** 2)

    def sample(self, shape=()):
        key = _random.next_key()
        base = jnp.broadcast_shapes(self.concentration.shape,
                                    self.rate.shape)
        g = jax.random.gamma(key, jnp.broadcast_to(self.concentration, base),
                             tuple(shape) + base)
        return Tensor(g / self.rate)

    rsample = sample

    def log_prob(self, value):
        a, b = self.concentration, self.rate
        return apply(
            lambda v: a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
            - jsp.gammaln(a), value, op_name="gamma_log_prob")

    def entropy(self):
        a = self.concentration
        return Tensor(a - jnp.log(self.rate) + jsp.gammaln(a)
                      + (1 - a) * jsp.digamma(a))


class Chi2(Gamma):
    def __init__(self, df, name=None):
        df = _val(df).astype(jnp.float32)
        super().__init__(df / 2.0, jnp.full_like(df, 0.5))
        self.df = df


class Beta(ExponentialFamily):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _val(alpha)
        self.beta = _val(beta)

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (s * s * (s + 1)))

    def sample(self, shape=()):
        key = _random.next_key()
        base = jnp.broadcast_shapes(self.alpha.shape, self.beta.shape)
        out = jax.random.beta(key, jnp.broadcast_to(self.alpha, base),
                              jnp.broadcast_to(self.beta, base),
                              tuple(shape) + base)
        return Tensor(out)

    rsample = sample

    def log_prob(self, value):
        a, b = self.alpha, self.beta
        lbeta = jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)
        return apply(
            lambda v: (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta,
            value, op_name="beta_log_prob")

    def entropy(self):
        a, b = self.alpha, self.beta
        lbeta = jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)
        return Tensor(lbeta - (a - 1) * jsp.digamma(a)
                      - (b - 1) * jsp.digamma(b)
                      + (a + b - 2) * jsp.digamma(a + b))


class Dirichlet(ExponentialFamily):
    def __init__(self, concentration, name=None):
        self.concentration = _val(concentration)

    @property
    def mean(self):
        c = self.concentration
        return Tensor(c / c.sum(-1, keepdims=True))

    def sample(self, shape=()):
        key = _random.next_key()
        out = jax.random.dirichlet(key, self.concentration, tuple(shape)
                                   + self.concentration.shape[:-1])
        return Tensor(out)

    rsample = sample

    def log_prob(self, value):
        c = self.concentration
        norm = jnp.sum(jsp.gammaln(c), -1) - jsp.gammaln(c.sum(-1))
        return apply(
            lambda v: jnp.sum((c - 1) * jnp.log(v), -1) - norm,
            value, op_name="dirichlet_log_prob")

    def entropy(self):
        c = self.concentration
        c0 = c.sum(-1)
        k = c.shape[-1]
        lnB = jnp.sum(jsp.gammaln(c), -1) - jsp.gammaln(c0)
        return Tensor(lnB + (c0 - k) * jsp.digamma(c0)
                      - jnp.sum((c - 1) * jsp.digamma(c), -1))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape)))

    @property
    def variance(self):
        return Tensor(2.0 * self.scale ** 2)

    def sample(self, shape=()):
        key = _random.next_key()
        base = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        e = jax.random.laplace(key, tuple(shape) + base)
        return Tensor(self.loc + self.scale * e)

    rsample = sample

    def log_prob(self, value):
        return apply(
            lambda v: -jnp.abs(v - self.loc) / self.scale
            - jnp.log(2 * self.scale), value, op_name="laplace_log_prob")

    def entropy(self):
        return Tensor(1.0 + jnp.log(2 * self.scale))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)

    @property
    def mean(self):
        return Tensor(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return Tensor((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def sample(self, shape=()):
        key = _random.next_key()
        base = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        eps = jax.random.normal(key, tuple(shape) + base)
        return Tensor(jnp.exp(self.loc + self.scale * eps))

    rsample = sample

    def log_prob(self, value):
        return apply(
            lambda v: -((jnp.log(v) - self.loc) ** 2) / (2 * self.scale ** 2)
            - jnp.log(v * self.scale) - 0.5 * math.log(2 * math.pi),
            value, op_name="lognormal_log_prob")

    def entropy(self):
        return Tensor(self.loc + 0.5
                      + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)

    _EULER = 0.57721566490153286

    @property
    def mean(self):
        return Tensor(self.loc + self._EULER * self.scale)

    @property
    def variance(self):
        return Tensor((math.pi ** 2 / 6) * self.scale ** 2)

    def sample(self, shape=()):
        key = _random.next_key()
        base = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        g = jax.random.gumbel(key, tuple(shape) + base)
        return Tensor(self.loc + self.scale * g)

    rsample = sample

    def log_prob(self, value):
        def fn(v):
            z = (v - self.loc) / self.scale
            return -(z + jnp.exp(-z)) - jnp.log(self.scale)
        return apply(fn, value, op_name="gumbel_log_prob")

    def entropy(self):
        return Tensor(jnp.log(self.scale) + 1.0 + self._EULER)


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)

    def sample(self, shape=()):
        key = _random.next_key()
        base = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        c = jax.random.cauchy(key, tuple(shape) + base)
        return Tensor(self.loc + self.scale * c)

    rsample = sample

    def log_prob(self, value):
        def fn(v):
            z = (v - self.loc) / self.scale
            return -jnp.log1p(z * z) - jnp.log(math.pi * self.scale)
        return apply(fn, value, op_name="cauchy_log_prob")

    def entropy(self):
        return Tensor(jnp.log(4 * math.pi * self.scale))


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _val(df)
        self.loc = _val(loc)
        self.scale = _val(scale)

    def sample(self, shape=()):
        key = _random.next_key()
        base = jnp.broadcast_shapes(self.df.shape, self.loc.shape,
                                    self.scale.shape)
        t = jax.random.t(key, jnp.broadcast_to(self.df, base),
                         tuple(shape) + base)
        return Tensor(self.loc + self.scale * t)

    rsample = sample

    def log_prob(self, value):
        df = self.df

        def fn(v):
            z = (v - self.loc) / self.scale
            return (jsp.gammaln((df + 1) / 2) - jsp.gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(self.scale)
                    - (df + 1) / 2 * jnp.log1p(z * z / df))
        return apply(fn, value, op_name="studentt_log_prob")

    def entropy(self):
        df = self.df
        return Tensor((df + 1) / 2 * (jsp.digamma((df + 1) / 2)
                                      - jsp.digamma(df / 2))
                      + 0.5 * jnp.log(df) + jnp.log(self.scale)
                      + jsp.betaln(df / 2, 0.5))


class Poisson(ExponentialFamily):
    def __init__(self, rate, name=None):
        self.rate = _val(rate)

    @property
    def mean(self):
        return Tensor(self.rate)

    variance = mean

    def sample(self, shape=()):
        key = _random.next_key()
        out = jax.random.poisson(key, self.rate,
                                 tuple(shape) + self.rate.shape)
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value):
        return apply(
            lambda v: v * jnp.log(self.rate) - self.rate
            - jsp.gammaln(v + 1), value, op_name="poisson_log_prob")

    def entropy(self):
        # exact truncated series for small rates (the Stirling surrogate
        # is wildly wrong there — review r5: -4.7 at rate 0.1 vs true
        # 0.33), Stirling expansion for large ones where the series
        # would need many terms: H = r - r·log r + e^{-r}·Σ r^k·log(k!)/k!
        r = self.rate
        k = jnp.arange(64, dtype=jnp.float32)
        log_kfact = jsp.gammaln(k + 1)
        rk = r[..., None]
        series = jnp.exp(-rk + k * jnp.log(jnp.maximum(rk, 1e-30))
                         - log_kfact) * log_kfact
        exact = r - r * jnp.log(jnp.maximum(r, 1e-30)) + series.sum(-1)
        stirling = (0.5 * jnp.log(2 * math.pi * math.e * r)
                    - 1 / (12 * r) - 1 / (24 * r * r))
        return Tensor(jnp.where(r < 16.0, exact, stirling))


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p over k = 0, 1, 2, … (failures before the first
    success — the reference's convention)."""

    def __init__(self, probs, name=None):
        self.probs = _val(probs)

    @property
    def mean(self):
        return Tensor((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return Tensor((1 - self.probs) / self.probs ** 2)

    def sample(self, shape=()):
        key = _random.next_key()
        out = jax.random.geometric(key, self.probs,
                                   tuple(shape) + self.probs.shape)
        # jax.random.geometric counts trials (1-based); shift to failures
        return Tensor(out.astype(jnp.float32) - 1.0)

    def log_prob(self, value):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return apply(lambda v: v * jnp.log1p(-p) + jnp.log(p),
                     value, op_name="geometric_log_prob")

    def entropy(self):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        q = 1 - p
        return Tensor(-(q * jnp.log(q) + p * jnp.log(p)) / p)


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = _val(total_count)
        self.probs = _val(probs)

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        key = _random.next_key()
        base = jnp.broadcast_shapes(jnp.shape(self.total_count),
                                    self.probs.shape)
        out = jax.random.binomial(key, self.total_count, self.probs,
                                  tuple(shape) + base)
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value):
        n, p = self.total_count, jnp.clip(self.probs, 1e-7, 1 - 1e-7)

        def fn(v):
            return (jsp.gammaln(n + 1) - jsp.gammaln(v + 1)
                    - jsp.gammaln(n - v + 1)
                    + v * jnp.log(p) + (n - v) * jnp.log1p(-p))
        return apply(fn, value, op_name="binomial_log_prob")


class Multinomial(Distribution):
    """Counts over k categories from ``total_count`` draws.

    ``total_count`` must be a Python int (static under jit — the sample
    is a scan of that many categorical draws folded into one_hot sums)."""

    def __init__(self, total_count: int, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _val(probs)

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    def sample(self, shape=()):
        key = _random.next_key()
        k = self.probs.shape[-1]
        logits = jnp.log(jnp.clip(self.probs, 1e-30, None))
        batch = tuple(shape) + self.probs.shape[:-1]
        keys = jax.random.split(key, self.total_count)

        def body(counts, k_i):
            draw = jax.random.categorical(k_i, logits, axis=-1,
                                          shape=batch)
            return counts + jax.nn.one_hot(draw, k, dtype=jnp.float32), None

        counts, _ = jax.lax.scan(body, jnp.zeros(batch + (k,), jnp.float32),
                                 keys)
        return Tensor(counts)

    def log_prob(self, value):
        p = jnp.clip(self.probs, 1e-30, None)

        def fn(v):
            return (jsp.gammaln(jnp.sum(v, -1) + 1)
                    - jnp.sum(jsp.gammaln(v + 1), -1)
                    + jnp.sum(v * jnp.log(p), -1))
        return apply(fn, value, op_name="multinomial_log_prob")


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, scale_tril=None,
                 name=None):
        self.loc = _val(loc)
        if (covariance_matrix is None) == (scale_tril is None):
            raise ValueError("give exactly one of covariance_matrix / "
                             "scale_tril")
        if covariance_matrix is not None:
            self.covariance_matrix = _val(covariance_matrix)
            self.scale_tril = jnp.linalg.cholesky(self.covariance_matrix)
        else:
            self.scale_tril = _val(scale_tril)
            self.covariance_matrix = self.scale_tril @ jnp.swapaxes(
                self.scale_tril, -1, -2)

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def variance(self):
        return Tensor(jnp.diagonal(self.covariance_matrix, axis1=-2,
                                   axis2=-1))

    def sample(self, shape=()):
        key = _random.next_key()
        eps = jax.random.normal(
            key, tuple(shape) + self.loc.shape)
        return Tensor(self.loc + jnp.einsum("...ij,...j->...i",
                                            self.scale_tril, eps))

    rsample = sample

    def log_prob(self, value):
        d = self.loc.shape[-1]
        L = self.scale_tril
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)),
                              -1)

        def fn(v):
            diff = v - self.loc
            sol = jax.scipy.linalg.solve_triangular(
                L, diff[..., None], lower=True)[..., 0]
            maha = jnp.sum(sol * sol, -1)
            return (-0.5 * maha - half_logdet
                    - 0.5 * d * math.log(2 * math.pi))
        return apply(fn, value, op_name="mvn_log_prob")

    def entropy(self):
        d = self.loc.shape[-1]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(
            self.scale_tril, axis1=-2, axis2=-1)), -1)
        return Tensor(0.5 * d * (1 + math.log(2 * math.pi)) + half_logdet)
