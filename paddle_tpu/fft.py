"""``paddle_tpu.fft`` — discrete Fourier transform namespace.

Rebuild of python/paddle/fft.py over phi FFT kernels
(paddle/phi/kernels/funcs/fft.* — SURVEY.md §2.1 kernel corpus; listed as a
round-1 gap in VERDICT "missing op families"). All transforms lower to XLA's
FFT HLO via jnp.fft; gradients flow through the eager tape (jax FFTs are
differentiable).
"""

from __future__ import annotations

import functools

import jax.numpy as jnp

from .core.dispatch import apply
from .core.tensor import Tensor


def _norm(norm):
    if norm in (None, "backward", "forward", "ortho"):
        return norm or "backward"
    raise ValueError(f"invalid norm {norm!r}")


def _wrap1(jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply(lambda v: jfn(v, n=n, axis=axis, norm=_norm(norm)), x,
                     op_name=jfn.__name__)
    return op


def _wrap2(jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply(lambda v: jfn(v, s=s, axes=tuple(axes),
                                   norm=_norm(norm)), x,
                     op_name=jfn.__name__)
    return op


def _wrapn(jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return apply(lambda v: jfn(v, s=s,
                                   axes=None if axes is None else tuple(axes),
                                   norm=_norm(norm)), x,
                     op_name=jfn.__name__)
    return op


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)

fft2 = _wrap2(jnp.fft.fft2)
ifft2 = _wrap2(jnp.fft.ifft2)
rfft2 = _wrap2(jnp.fft.rfft2)
irfft2 = _wrap2(jnp.fft.irfft2)

fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d=d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d=d))


def fftshift(x, axes=None, name=None):
    return apply(lambda v: jnp.fft.fftshift(
        v, axes=None if axes is None else tuple(axes)), x, op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply(lambda v: jnp.fft.ifftshift(
        v, axes=None if axes is None else tuple(axes)), x,
        op_name="ifftshift")
