"""Profiler with scheduler-windowed capture + exporters.

Reference: paddle.profiler.Profiler / make_scheduler / export_chrome_tracing
(python/paddle/profiler/profiler.py — SURVEY.md §5.1). State machine parity:
CLOSED → READY (warmup) → RECORD → RECORD_AND_RETURN on the last active
step, driven by ``Profiler.step()``. Device-side capture delegates to
``jax.profiler.start_trace/stop_trace`` (xplane/TensorBoard) when
ProfilerTarget.TPU is requested.
"""

from __future__ import annotations

import json
import os
import time
from enum import Enum
from typing import Callable, List, Optional, Sequence

from .record import HostSpan, RecordEvent, host_recorder


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3  # last record step of a window


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1      # parity alias — maps to the accelerator trace
    TPU = 2
    CUSTOM_DEVICE = 3


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0
                   ) -> Callable[[int], ProfilerState]:
    """Window scheduler (parity with paddle.profiler.make_scheduler):
    skip_first steps CLOSED, then cycles of closed/ready/record; ``repeat=0``
    cycles forever."""
    if closed < 0 or ready < 0 or record <= 0:
        raise ValueError("closed/ready must be >=0 and record >= 1")
    cycle = closed + ready + record

    def fn(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return fn


def _default_scheduler(_step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """Returns an on_trace_ready callback writing chrome://tracing JSON.

    Spans that share a trace id (one serving request / training step —
    see ``observability.trace``) carry ``args.trace_id`` (and
    ``args.request_id`` where known) so Perfetto can filter a single
    request's timeline, and are linked with flow events (``ph: s/t/f``)
    so the queue-wait → prefill → decode-chunk chain is drawn as arrows.
    """

    def handler(prof: "Profiler") -> None:
        os.makedirs(dir_name, exist_ok=True)
        worker = worker_name or f"worker_{os.getpid()}"
        path = os.path.join(
            dir_name, f"{worker}_time_{int(time.time()*1000)}.paddle_trace.json")
        events = []
        by_trace = {}
        for sp in prof.collected_spans:
            ev = {
                "name": sp.name, "cat": sp.event_type, "ph": "X",
                "ts": sp.start_ns / 1000.0,
                "dur": (sp.end_ns - sp.start_ns) / 1000.0,
                "pid": sp.pid, "tid": sp.tid,
            }
            trace_id = getattr(sp, "trace_id", "")
            args = dict(getattr(sp, "args", None) or {})
            if trace_id:
                args.setdefault("trace_id", trace_id)
                by_trace.setdefault(trace_id, []).append(ev)
            if args:
                ev["args"] = args
            events.append(ev)
        # flow events: one arrow chain per trace id, linking its spans in
        # start-time order (s = first, t = intermediate, f = last)
        for flow_id, (trace_id, chain) in enumerate(sorted(by_trace.items()),
                                                    start=1):
            if len(chain) < 2:
                continue
            chain.sort(key=lambda e: e["ts"])
            for i, ev in enumerate(chain):
                ph = "s" if i == 0 else ("f" if i == len(chain) - 1 else "t")
                flow = {"name": f"trace/{trace_id}", "cat": "flow",
                        "ph": ph, "id": flow_id, "ts": ev["ts"],
                        "pid": ev["pid"], "tid": ev["tid"]}
                if ph == "f":
                    flow["bp"] = "e"
                events.append(flow)
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        prof.last_export_path = path

    return handler


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    """Parity shim: the xplane protobuf comes from the jax profiler dump
    (``jax.profiler.start_trace(log_dir)``); host spans are exported as
    chrome tracing next to it."""
    return export_chrome_tracing(dir_name, worker_name)


class Profiler:
    """Scheduler-windowed profiler (parity: paddle.profiler.Profiler).

    ``targets`` containing TPU/GPU turns on the XLA device trace
    (jax.profiler) for the capture window; CPU host spans are always
    recorded while a window is active.
    """

    def __init__(self, *, targets: Optional[Sequence[ProfilerTarget]] = None,
                 scheduler=None, on_trace_ready=None, timer_only: bool = False,
                 log_dir: str = "./profiler_log"):
        self.targets = list(targets) if targets else [ProfilerTarget.CPU]
        if callable(scheduler):
            self.scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            # (start, end) step-range shorthand, as in the reference
            lo, hi = scheduler
            self.scheduler = make_scheduler(
                closed=max(lo, 0), ready=0, record=hi - lo, repeat=1)
        elif scheduler is None:
            self.scheduler = _default_scheduler
        else:
            raise TypeError(f"bad scheduler: {scheduler!r}")
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.log_dir = log_dir
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self.collected_spans: List[HostSpan] = []
        self.last_export_path: Optional[str] = None
        self._device_tracing = False
        self._step_event: Optional[RecordEvent] = None
        self._benchmark = _TimerStats()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self.current_state = self.scheduler(self.step_num)
        self._transition(ProfilerState.CLOSED, self.current_state)
        self._begin_step_span()

    def stop(self) -> None:
        self._end_step_span()
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._capture_off(export=True)
        self.current_state = ProfilerState.CLOSED

    def step(self, num_samples: Optional[int] = None) -> None:
        """Advance one training step; drives the window state machine."""
        self._end_step_span()
        self._benchmark.record_step(num_samples)
        prev = self.current_state
        self.step_num += 1
        self.current_state = self.scheduler(self.step_num)
        self._transition(prev, self.current_state)
        self._begin_step_span()

    def __enter__(self) -> "Profiler":
        self.start()
        return self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- internals ----------------------------------------------------------

    def _transition(self, prev: ProfilerState, new: ProfilerState) -> None:
        was_rec = prev in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        is_rec = new in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if not was_rec and is_rec:
            self._capture_on()
        elif was_rec and prev == ProfilerState.RECORD_AND_RETURN:
            self._capture_off(export=True)
            if is_rec:  # back-to-back windows
                self._capture_on()
        elif was_rec and not is_rec:
            self._capture_off(export=True)

    def _capture_on(self) -> None:
        if self.timer_only:
            return
        host_recorder.clear()
        host_recorder.enabled = True
        if any(t in (ProfilerTarget.TPU, ProfilerTarget.GPU,
                     ProfilerTarget.CUSTOM_DEVICE) for t in self.targets):
            try:
                import jax.profiler as jprof
                jprof.start_trace(self.log_dir)
                self._device_tracing = True
            except Exception:
                self._device_tracing = False

    def _capture_off(self, export: bool) -> None:
        if self.timer_only:
            return
        host_recorder.enabled = False
        self.collected_spans = host_recorder.drain()
        if self._device_tracing:
            try:
                import jax.profiler as jprof
                jprof.stop_trace()
            except Exception:
                pass
            self._device_tracing = False
        if export and self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def _begin_step_span(self) -> None:
        if host_recorder.enabled:
            self._step_event = RecordEvent(
                f"ProfileStep#{self.step_num}", "ProfileStep")
            self._step_event.begin()

    def _end_step_span(self) -> None:
        if self._step_event is not None:
            self._step_event.end()
            self._step_event = None

    # -- reporting ----------------------------------------------------------

    def summary(self, sorted_by=None, op_detail: bool = True,
                thread_sep: bool = False, time_unit: str = "ms") -> str:
        from .statistic import summary as _summary
        return _summary(self.collected_spans, sorted_by=sorted_by,
                        time_unit=time_unit)

    def step_info(self, unit: Optional[str] = None) -> str:
        return self._benchmark.info()


class _TimerStats:
    """reads/ips bookkeeping behind Profiler.step_info (reference
    benchmark() timer)."""

    def __init__(self):
        self.last_t = None
        self.durs: List[float] = []
        self.samples: List[int] = []

    def record_step(self, num_samples: Optional[int]) -> None:
        t = time.perf_counter()
        if self.last_t is not None:
            self.durs.append(t - self.last_t)
            self.samples.append(num_samples or 0)
        self.last_t = t

    def info(self) -> str:
        if not self.durs:
            return "no steps recorded"
        avg = sum(self.durs) / len(self.durs)
        total_samples = sum(self.samples)
        ips = (total_samples / sum(self.durs)) if total_samples else 0.0
        return (f"avg batch_cost: {avg*1000:.3f} ms, ips: {ips:.3f} samples/s")
