"""Host span recorder + RecordEvent annotation API.

Reference: RecordEvent (python/paddle/profiler/utils.py) backed by the C++
thread-local HostEventRecorder (paddle/fluid/platform/profiler/
host_tracer.cc — SURVEY.md §5.1). Here the recorder is a process-global,
thread-aware span list; when a capture is active each span additionally
enters a ``jax.profiler.TraceAnnotation`` so it shows up in XLA xplane
traces (TensorBoard) correlated with device activity.

Spans carry the ambient trace id (``observability.trace``) so one serving
request / training step can be followed across scheduler, engine and op
dispatch in the chrome-tracing export. Outside a capture window,
``RecordEvent.__enter__``/``__exit__`` short-circuit on a single boolean
— the zero-overhead contract the dispatcher relies on.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import List, NamedTuple, Optional

from ..observability import runtime as _obs_runtime
from ..observability.flight import flight_armed, flight_recorder
from ..observability.timeline import span_collector, timeline_armed
from ..observability.trace import current_trace


class HostSpan(NamedTuple):
    name: str
    event_type: str
    start_ns: int
    end_ns: int
    tid: int
    pid: int
    trace_id: str = ""
    args: Optional[dict] = None


class _HostRecorder:
    """HostEventRecorder equivalent: lock-guarded span sink, armed only
    while a Profiler capture window is active (zero overhead otherwise).
    Toggling ``enabled`` also re-arms the dispatcher's single-boolean
    fast-path flag (observability.runtime.dispatch_armed)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._spans: List[HostSpan] = []
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    @enabled.setter
    def enabled(self, value: bool) -> None:
        self._enabled = bool(value)
        _obs_runtime.set_capture_active(self._enabled)

    def emit(self, span: HostSpan) -> None:
        with self._lock:
            self._spans.append(span)

    def drain(self) -> List[HostSpan]:
        with self._lock:
            spans, self._spans = self._spans, []
        return spans

    def clear(self) -> None:
        self.drain()


host_recorder = _HostRecorder()

_MAIN_PID = threading.main_thread().ident or 0


def spans_armed() -> bool:
    """True when ANY span sink wants spans: a profiler capture window,
    the flight recorder's ring, or the timeline span collector. Hot
    call sites (engine step loops, scheduler admission) gate their span
    bookkeeping on this so the disarmed cost stays one boolean + two
    list indexes."""
    return host_recorder.enabled or flight_armed[0] or timeline_armed[0]


def make_span(name: str, start_ns: int, end_ns: int,
              event_type: str = "UserDefined", trace_id: str = "",
              args: Optional[dict] = None) -> HostSpan:
    """Build a HostSpan without emitting it — for hot loops that batch
    several per-request spans into one :func:`emit_spans` call (one lock
    round per sink instead of one per span)."""
    return HostSpan(name, event_type, start_ns, end_ns,
                    threading.get_ident(), _MAIN_PID, trace_id, args)


def emit_spans(spans) -> None:
    """Batch-emit pre-built spans (see :func:`make_span`). Callers gate
    on :func:`spans_armed` before building the batch."""
    if not spans:
        return
    if host_recorder.enabled:
        for sp in spans:
            host_recorder.emit(sp)
    if flight_armed[0]:
        flight_recorder.note_spans(spans)
    if timeline_armed[0]:
        span_collector.note_spans(spans)


def emit_span(name: str, start_ns: int, end_ns: int,
              event_type: str = "UserDefined",
              trace_id: Optional[str] = None,
              args: Optional[dict] = None) -> None:
    """Emit a span with explicit timestamps (for retroactive spans like a
    request's queue wait, whose start predates the emit site). No-op when
    no capture window, flight recorder or span collector is armed.
    ``trace_id=None`` picks up the ambient trace context."""
    if not spans_armed():
        return
    if trace_id is None:
        ctx = current_trace()
        trace_id = ctx.trace_id if ctx is not None else ""
    span = HostSpan(name, event_type, start_ns, end_ns,
                    threading.get_ident(), _MAIN_PID, trace_id, args)
    if host_recorder.enabled:
        host_recorder.emit(span)
    if flight_armed[0]:
        flight_recorder.note_span(span)
    if timeline_armed[0]:
        span_collector.note_span(span)


class RecordEvent:
    """User annotation span (parity: paddle.profiler.RecordEvent).

    Usable as a context manager or via explicit begin()/end(). Event types
    mirror the reference's TracerEventType names (UserDefined, Operator,
    Dataloader, Communication, Forward, Backward, Optimization...).
    ``args`` lands in the chrome-trace event's ``args`` (request ids etc);
    ``trace_id`` overrides the ambient trace context.
    """

    __slots__ = ("name", "event_type", "args", "_trace_id", "_tid0",
                 "_start_ns", "_jax_ann", "_is_request", "_light")

    def __init__(self, name: str, event_type: str = "UserDefined",
                 args: Optional[dict] = None,
                 trace_id: Optional[str] = None, light: bool = False):
        self.name = name
        self.event_type = event_type
        self.args = args
        self._trace_id = trace_id
        self._tid0 = trace_id       # constructor value, restored on end()
        # so a REUSED event re-resolves the ambient trace context per
        # begin instead of pinning the first span's id forever
        self._start_ns: Optional[int] = None
        self._jax_ann = None
        # precomputed: the timeline collector only consumes request
        # envelopes (every other categorised span arrives via emit_span)
        self._is_request = name.endswith(".request")
        # light spans record ONLY inside a profiler capture window: the
        # per-STEP scheduler span fires hundreds of times a second and
        # would otherwise pay the full HostSpan+ring cost on every armed
        # serving step just to wrap the 256-deep flight ring in under a
        # second (armed-overhead engineering, like the engine's
        # coalesced per-slot windows — bench_obs_overhead)
        self._light = light

    def begin(self) -> None:
        capture = host_recorder._enabled
        # zero-overhead fast path; the timeline term only arms request
        # envelopes — with just the collector armed, step/mark spans
        # nobody would consume never pay the span bookkeeping
        if not capture and (self._light or (
                not flight_armed[0]
                and not (timeline_armed[0] and self._is_request))):
            return
        if self._trace_id is None:
            ctx = current_trace()
            self._trace_id = ctx.trace_id if ctx is not None else ""
        self._start_ns = time.perf_counter_ns()
        if not capture:      # flight-only: skip the jax annotation (the
            return           # xplane trace belongs to capture windows)
        try:
            import jax.profiler as jprof
            self._jax_ann = jprof.TraceAnnotation(self.name)
            self._jax_ann.__enter__()
        except Exception:
            self._jax_ann = None

    def end(self) -> None:
        if self._start_ns is None:        # never began (or capture was off)
            return
        if self._jax_ann is not None:
            try:
                self._jax_ann.__exit__(None, None, None)
            finally:
                self._jax_ann = None
        # light spans feed ONLY the capture window — a light span begun
        # under capture with the flight recorder also armed must still
        # stay out of the ring (it would wrap the 256-deep postmortem
        # ring in under a second)
        light = self._light
        if host_recorder._enabled or (not light and (
                flight_armed[0]
                or (timeline_armed[0] and self._is_request))):
            span = HostSpan(
                self.name, self.event_type, self._start_ns,
                time.perf_counter_ns(),
                threading.get_ident(), _MAIN_PID,
                self._trace_id or "", self.args)
            if host_recorder._enabled:
                host_recorder.emit(span)
            if flight_armed[0] and not light:
                flight_recorder.note_span(span)
            if not light and timeline_armed[0] and self._is_request:
                # the ONLY RecordEvent the timeline consumes is the
                # request envelope — step spans and markers carry step
                # trace ids the collector would discard anyway, and the
                # per-step call into it is real armed-loop cost
                # (bench_obs_overhead)
                span_collector.note_span(span)
        self._start_ns = None
        self._trace_id = self._tid0

    def __enter__(self) -> "RecordEvent":
        self.begin()
        return self

    def __exit__(self, *exc) -> bool:
        self.end()
        return False


def record_function(name: str, event_type: str = "UserDefined"):
    """Decorator form of RecordEvent."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with RecordEvent(name, event_type):
                return fn(*args, **kwargs)
        return wrapper

    return deco
