"""Always-on, low-overhead runtime telemetry for the op dispatcher.

``core.dispatch.apply`` is the single funnel every eager op goes through;
this module gives it:

* per-op dispatch **counters** (``paddle_runtime_op_dispatch_total{op=…}``)
  and **sampled durations** (1 in ``sample_every`` dispatches per op lands
  in ``paddle_runtime_op_duration_us``) — cheap enough to leave on in
  production;
* **recompile detection**: every compile-cache miss (engine prefill /
  decode builds, ``jit.CompileGuard``) increments
  ``paddle_runtime_recompiles_total{fn=…}`` exactly once per new shape
  signature and logs a structured event carrying the shapes, so a shape
  leak that silently retraces per step becomes a counter you can alert on;
* the **single-boolean fast path**: ``dispatch_armed[0]`` is the ONE flag
  ``apply`` checks per dispatch. It is recomputed only when telemetry is
  switched or a profiler capture window opens/closes, so a fully disarmed
  dispatcher pays one list-index — the zero-overhead contract guarded by
  ``benchmarks/bench_dispatch_overhead.py``.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from .events import emit_event
from .registry import get_registry

#: the one flag core.dispatch.apply checks per call (mutable cell so the
#: dispatcher reads a stable module attribute, not a rebindable name)
dispatch_armed = [False]

_capture_active = False     # mirrors profiler.record.host_recorder.enabled


def _rearm() -> None:
    dispatch_armed[0] = _capture_active or telemetry.enabled


def set_capture_active(active: bool) -> None:
    """Called by the profiler's host recorder when a capture window opens
    or closes (keeps the fast-path flag a single check)."""
    global _capture_active
    _capture_active = bool(active)
    _rearm()


class DispatchTelemetry:
    """Per-op dispatch counters + sampled duration histogram. ON by
    default (the always-on view); ``disable()`` restores the seed-exact
    fast path."""

    def __init__(self, sample_every: int = 64):
        self.sample_every = sample_every
        self._enabled = True
        self._counts: Dict[str, int] = {}
        reg = get_registry()
        self._duration_us = reg.histogram(
            "paddle_runtime_op_duration_us",
            "sampled eager-dispatch wall time per op (µs)",
            bounds=(1, 5, 10, 50, 100, 500, 1000, 5000, 10000, 100000))

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True
        _rearm()

    def disable(self) -> None:
        self._enabled = False
        _rearm()

    def count(self, op_name: str) -> bool:
        """Hot path: bump the dispatch counter; True when this dispatch
        should have its duration sampled (1 in ``sample_every`` per op).
        GIL-serialized dict ops — a lost count under free threading is
        acceptable for telemetry."""
        c = self._counts
        n = c.get(op_name, 0)
        c[op_name] = n + 1
        return n % self.sample_every == 0

    def observe_duration(self, dur_ns: int) -> None:
        self._duration_us.observe(dur_ns / 1e3)

    @property
    def op_counts(self) -> Dict[str, int]:
        return dict(self._counts)

    def reset(self) -> None:
        self._counts.clear()

    # -- export (registered as a registry sink) -----------------------------

    def _lines(self):
        from . import format as fmt
        series = [({"op": op}, float(n))
                  for op, n in sorted(self._counts.items())]
        if not series:
            return []
        return fmt.counter_lines(
            "paddle_runtime_op_dispatch_total", series=series,
            help="eager op dispatches through core.dispatch.apply")

    def _snapshot(self):
        return {"op_dispatch_total": dict(self._counts)}


class RecompileDetector:
    """Counts compile-cache misses once per (fn, shape-signature)."""

    def __init__(self):
        self._seen: Dict[str, set] = {}
        self._lock = threading.Lock()
        reg = get_registry()
        self._counter = reg.counter(
            "paddle_runtime_recompiles_total",
            "XLA trace-cache misses (first compile included), by function",
            labels=("fn",))
        self._compile_s = reg.histogram(
            "paddle_runtime_compile_seconds",
            "wall time of XLA trace+compile per cache miss, by function",
            labels=("fn",),
            bounds=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0,
                    30.0, 60.0, 120.0))
        self._compile_sums: Dict[str, float] = {}

    def note(self, fn_name: str, shape_key) -> bool:
        """Record a compile-cache lookup for ``fn_name`` with hashable
        ``shape_key``. Returns True (and counts + logs an event) only the
        first time this (fn, key) is seen — for callers WITHOUT their own
        per-instance compile cache. Callers that already deduplicate
        (engines, CompileGuard) use :meth:`record_miss` instead, or a
        second instance's real recompiles would be swallowed here."""
        key = shape_key if isinstance(shape_key, tuple) else (shape_key,)
        with self._lock:
            seen = self._seen.setdefault(fn_name, set())
            if key in seen:
                return False
            seen.add(key)
            distinct = len(seen)
        self._fire(fn_name, shape_key, distinct)
        return True

    def record_miss(self, fn_name: str, shape_key) -> None:
        """Unconditionally count one trace-cache miss — for callers whose
        OWN compile cache already deduplicates shapes (the decoding
        engines check ``key not in self._compiled`` before calling); a
        fresh engine's first compile is a real miss even if another
        instance compiled the same shapes earlier."""
        self._fire(fn_name, shape_key, None)

    def _fire(self, fn_name: str, shape_key, distinct) -> None:
        self._counter.inc(fn=fn_name)
        extra = {} if distinct is None else {"distinct_signatures": distinct}
        emit_event("recompile", fn=fn_name, shapes=repr(shape_key), **extra)

    def observe_compile(self, fn_name: str, seconds: float) -> None:
        """Record one compile's wall time (the caller times its first
        invocation of a freshly built program, blocked to completion) so
        warmup cost shows up in ``paddle_runtime_compile_seconds{fn}``
        on /metrics and in bench JSON lines."""
        self._compile_s.observe(float(seconds), fn=fn_name)
        with self._lock:
            self._compile_sums[fn_name] = (
                self._compile_sums.get(fn_name, 0.0) + float(seconds))

    def compile_seconds_total(self, fn_name: str) -> float:
        """Summed compile wall time recorded for ``fn_name`` (local
        mirror — reading an unseen fn must NOT materialize an empty
        labeled series on /metrics)."""
        with self._lock:
            return self._compile_sums.get(fn_name, 0.0)

    def count(self, fn_name: Optional[str] = None) -> float:
        if fn_name is not None:
            return self._counter.value(fn=fn_name)
        return self._counter.total

    def reset(self) -> None:
        with self._lock:
            self._seen.clear()


telemetry = DispatchTelemetry()
recompiles = RecompileDetector()
get_registry().register_sink("paddle_runtime_ops", telemetry._lines,
                             telemetry._snapshot)
_rearm()
