"""HBM memory ledger: byte-level accounting for the paged serving stack.

The observability plane answers "where did the time go" (timelines,
sensors) but not "where did the bytes go" — and the paged KV pool is the
dominant HBM consumer in a TPU serving stack (PAPERS.md "Ragged Paged
Attention"). This module is the memory half of the sensor plane:

* :class:`MemoryLedger` — process-global, registry-integrated accounting
  of device bytes by **class**:

  ========== ==========================================================
  class      what it measures
  ========== ==========================================================
  weights    model parameter pytrees (dtype-aware; fed once per params
             object by the engine / trainer)
  kv_live    paged-pool pages pinned by in-flight sequences (admission
             reservations included, speculative tails excluded)
  kv_spec    speculative tail pages (``grow_to`` growth past each row's
             admission reservation — rolled back on rejection)
  kv_cached  resident-but-unreferenced prefix-cache pages (evictable)
  kv_free    free-list pages
  optimizer  training state (params + optimizer accumulators) via
             ``ResilientTrainer``
  ========== ==========================================================

  with per-class **peak watermarks**, ``paddle_mem_bytes{class}`` /
  ``paddle_mem_peak_bytes{class}`` gauges, and a **byte conservation
  audit** — ``free + live + spec + cached bytes == pool bytes`` — run
  alongside the pool's ``check_conservation`` after every engine step.

* :func:`plan_capacity` — the capacity planner: model geometry +
  page_size + dtype + an HBM budget → page bytes, max pages, max
  concurrent sequences, max total context tokens. ``page_nbytes`` is
  DERIVED from geometry (2 × layers × page_size × kv_heads × head_dim ×
  dtype bytes), so an int8 page pool automatically halves it — the
  measurement substrate ROADMAP items 2 and 3 gate on. Every live pool
  carries a **planner verdict**: the plan recomputed from the pool's own
  geometry and byte size must predict its page capacity exactly.

* **per-request attribution** — pages (cached-vs-fresh bytes) held per
  request, keyed by trace id, surfaced at ``/memz``, in ``/statusz``'s
  ``memory`` section and in every flight bundle's ``memory.json``.

* **OOM forensics** — :func:`note_oom` turns ``allocate``/``grow_to``
  ``MemoryError`` raises and scheduler page-admission rejections into an
  ``oom_pressure`` JSONL event plus a once-per-reason flight-recorder
  ``auto_dump`` whose ``memory.json`` names the exhausting class, the
  per-request page holders and the planner verdict — a self-explaining
  postmortem instead of a bare ``MemoryError``.

Discipline (the telemetry layer's standing contracts):

* **fed, never pulls** — this module never imports the serving stack,
  the engine or the kvcache package (tpu-lint ``layer-deps`` checks this
  file STRICTLY: even lazy function-scope imports of serving/ or
  inference/ fail). Call sites hand it manager objects / pytrees /
  numbers; everything here is duck-typed attribute reads.
* **zero-cost disarmed gate** — hot paths check the module-cell
  ``memory_armed`` (one list index, no allocation) exactly like
  ``flight.flight_armed`` / ``timeseries.history_armed``; armed overhead
  rides under ``benchmarks/bench_obs_overhead.py``'s 3% budget.
* gauges publish decimated (every ``publish_every`` observations);
  peaks, the audit and the snapshot read the host-side books directly,
  so decimation never costs accuracy — only scrape freshness.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, Optional

from .events import emit_event
from .flight import flight_recorder

#: the one cell hot paths check before feeding the ledger (mutable list
#: so callers read a stable module attribute, not a rebindable name)
memory_armed = [False]

#: every accounting class the ledger reports (fixed: dashboards and the
#: MetricHistory rings key on these)
MEM_CLASSES = ("weights", "kv_live", "kv_spec", "kv_cached", "kv_free",
               "optimizer")

#: retained pools (a pool is one engine's paged KV manager); bounded so
#: short-lived test engines cannot grow the process-global ledger forever
MAX_POOLS = 16

#: migration-timeline entries kept in memory (oldest dropped; the
#: cumulative totals are unbounded counters and never lose bytes)
MAX_MIGRATIONS = 64


# ---------------------------------------------------------------------------
# pure helpers (the ONE place these derivations live)
# ---------------------------------------------------------------------------

def page_nbytes(num_layers: int, page_size: int, num_kv_heads: int,
                head_dim: int, dtype_bytes: int) -> int:
    """Device bytes of ONE page across every layer: K and V slabs (the
    factor 2) × layers × page_size tokens × kv_heads × head_dim ×
    element size. Derived from geometry — an int8 page pool
    (``dtype_bytes=1``) halves it with no ledger change."""
    return 2 * num_layers * page_size * num_kv_heads * head_dim * dtype_bytes


def pytree_nbytes(tree: Any) -> int:
    """Total device bytes of a parameter / state pytree (dicts, lists,
    tuples, array leaves with ``.nbytes``) — dtype-aware by construction.
    Non-array leaves (ints, None) count 0."""
    if isinstance(tree, dict):
        return sum(pytree_nbytes(v) for v in tree.values())
    if isinstance(tree, (list, tuple)):
        return sum(pytree_nbytes(v) for v in tree)
    nbytes = getattr(tree, "nbytes", None)
    return int(nbytes) if nbytes is not None else 0


def pool_occupancy(mgr) -> Dict[str, float]:
    """THE page-pool occupancy derivation (one source of truth: the
    scheduler's utilization gauges and the signal bus's pool-pressure
    reader both delegate here instead of re-deriving the split by hand).
    Duck-typed over any paged manager: refcounted pools report their
    live/cached split, exclusive pools report owned pages as live."""
    usable = mgr.usable_pages
    free = mgr.num_free_pages
    live = getattr(mgr, "num_live_pages", None)
    if live is None:
        live = usable - free                  # exclusive ownership
    cached = getattr(mgr, "num_cached_pages", 0)
    inv = 1.0 / usable if usable else 0.0
    return {
        "usable": usable, "free": free, "live": live, "cached": cached,
        "pressure": 1.0 - free * inv if usable else 0.0,
        "live_utilization": live * inv,
        "cached_utilization": cached * inv,
    }


def _mgr_page_nbytes(mgr) -> int:
    """A manager's actual per-page byte cost, measured off its device
    arrays (K + V). The planner verdict cross-checks this against the
    geometry-derived :func:`page_nbytes`."""
    pb = getattr(mgr, "page_nbytes", None)
    if pb is not None:
        return int(pb)
    return (int(mgr.k_pages.nbytes) + int(mgr.v_pages.nbytes)) \
        // int(mgr.num_pages)


# ---------------------------------------------------------------------------
# capacity planner
# ---------------------------------------------------------------------------

@dataclass
class CapacityPlan:
    """Output of :func:`plan_capacity` — what a given HBM budget buys.

    ``max_pages`` counts ALLOCATABLE pages (the pool's reserved pad page
    0 is already subtracted), so it compares directly against a live
    pool's ``usable_pages``."""

    page_bytes: int            # bytes of one page (K+V, all layers)
    kv_budget_bytes: int       # HBM left for the pool after weights
    total_pages: int           # pool size including the reserved page
    max_pages: int             # allocatable pages (total - 1)
    max_context_tokens: int    # max_pages * page_size
    max_slots: Optional[int]   # concurrent max_seq_len sequences (None
                               # when no max_seq_len was given)
    pages_per_seq: Optional[int]

    def as_dict(self) -> Dict[str, Any]:
        return {
            "page_bytes": self.page_bytes,
            "kv_budget_bytes": self.kv_budget_bytes,
            "total_pages": self.total_pages,
            "max_pages": self.max_pages,
            "max_context_tokens": self.max_context_tokens,
            "max_slots": self.max_slots,
            "pages_per_seq": self.pages_per_seq,
        }


def plan_capacity(*, num_layers: int, num_kv_heads: int, head_dim: int,
                  page_size: int, dtype_bytes: int, hbm_bytes: int,
                  weight_bytes: int = 0,
                  max_seq_len: Optional[int] = None) -> CapacityPlan:
    """Model geometry + page size + dtype + HBM budget → pool capacity.

    ``hbm_bytes`` is the device budget; ``weight_bytes`` (resident model
    parameters) is carved out first and the remainder becomes the paged
    KV pool. With ``max_seq_len`` the plan also reports how many
    max-length sequences fit concurrently (the engine's ``num_slots``
    ceiling for a worst-case admission policy)."""
    if page_size <= 0 or num_layers <= 0:
        raise ValueError("geometry must be positive")
    pb = page_nbytes(num_layers, page_size, num_kv_heads, head_dim,
                     dtype_bytes)
    kv_budget = max(0, int(hbm_bytes) - int(weight_bytes))
    total = kv_budget // pb
    usable = max(0, total - 1)            # page 0 is the reserved pad page
    pages_per_seq = None
    max_slots = None
    if max_seq_len is not None:
        pages_per_seq = -(-int(max_seq_len) // page_size)   # ceil div
        max_slots = usable // pages_per_seq if pages_per_seq else 0
    return CapacityPlan(
        page_bytes=pb, kv_budget_bytes=kv_budget, total_pages=total,
        max_pages=usable, max_context_tokens=usable * page_size,
        max_slots=max_slots, pages_per_seq=pages_per_seq)


def plan_verdict(plan: CapacityPlan, mgr) -> Dict[str, Any]:
    """Validate a plan against a REAL pool: the plan's page bytes must
    match the pool's measured per-page cost and its ``max_pages`` must
    predict the pool's allocatable capacity exactly."""
    actual_pb = _mgr_page_nbytes(mgr)
    actual_pages = int(mgr.usable_pages)
    exact = (plan.page_bytes == actual_pb
             and plan.max_pages == actual_pages)
    return {
        "predicted_page_bytes": plan.page_bytes,
        "actual_page_bytes": actual_pb,
        "predicted_max_pages": plan.max_pages,
        "actual_max_pages": actual_pages,
        "exact": exact,
    }


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------

class _Pool:
    __slots__ = ("label", "page_bytes", "usable_pages", "num_pages",
                 "page_size", "pool_bytes", "verdict", "split", "held",
                 "tails", "meta", "cache_stats", "observes", "refcounted",
                 "ref", "chips")

    def __init__(self, label: str):
        self.label = label
        self.ref = None                     # weakref to the manager
        self.page_bytes = 0
        self.usable_pages = 0
        self.num_pages = 0
        self.page_size = 0
        self.pool_bytes = 0
        self.refcounted = False
        self.chips = 1                      # TP mesh degree (head-sharded)
        self.verdict: Dict[str, Any] = {}
        self.split: Dict[str, int] = {}     # class -> pages (last observe)
        self.held: Dict[Any, int] = {}      # rid -> pages (last observe)
        self.tails: Dict[Any, int] = {}     # rid -> spec tail pages
        self.meta: Dict[Any, Dict[str, Any]] = {}  # rid -> admission info
        self.cache_stats: Optional[Dict[str, Any]] = None
        self.observes = 0


class MemoryLedger:
    """See module docstring. One process-global instance
    (:data:`memory_ledger`); independent instances constructible for
    tests. Every entry point is cheap host bookkeeping; callers gate on
    ``memory_armed[0]`` so the disarmed cost is one list index."""

    def __init__(self, publish_every: int = 16):
        self._lock = threading.Lock()
        self._pools: "OrderedDict[int, _Pool]" = OrderedDict()
        self._pool_seq = 0          # monotonic: labels never collide
        self._classes: Dict[str, int] = {c: 0 for c in MEM_CLASSES}
        self._peaks: Dict[str, int] = {c: 0 for c in MEM_CLASSES}
        # params-id -> (fingerprint, nbytes); LRU-bounded like _pools
        self._weights: "OrderedDict[int, tuple]" = OrderedDict()
        self._publish_every = max(1, int(publish_every))
        self._since_publish = 0
        self._g_bytes = None
        self._g_peak = None
        self._c_rejects = None
        self._last_reject_key = None
        self.audits = 0
        self.last_oom: Optional[Dict[str, Any]] = None
        # cross-host page-migration books (fed by the multi-host router;
        # NOT a MEM_CLASS — migrated bytes land in kv_* when the
        # destination pool is observed, this tracks the TRANSFERS)
        self._migration: Dict[str, int] = {
            "bytes": 0, "pages": 0, "requests": 0}
        self._migration_log: list = []

    # -- lifecycle ----------------------------------------------------------

    @property
    def armed(self) -> bool:
        return memory_armed[0]

    def arm(self) -> "MemoryLedger":
        """Arm the memory plane (flips the ``memory_armed`` cell the
        engine/scheduler/trainer feeds gate on) and bind the registry
        families (idempotent: re-arming after a registry reset re-binds
        fresh gauge objects)."""
        from .registry import get_registry
        reg = get_registry()
        with self._lock:
            self._g_bytes = reg.gauge(
                "paddle_mem_bytes",
                "device bytes by accounting class (HBM memory ledger)",
                labels=("class",))
            self._g_peak = reg.gauge(
                "paddle_mem_peak_bytes",
                "peak device bytes by accounting class since arm/reset",
                labels=("class",))
            self._c_rejects = reg.counter(
                "paddle_mem_admission_rejects_total",
                "scheduler admissions deferred for KV pages (per blocked "
                "step; the event carries the byte shortfall)")
        memory_armed[0] = True
        return self

    def disarm(self) -> None:
        memory_armed[0] = False

    def reset(self) -> None:
        """Drop every pool, class total and peak (tests). Metric handles
        are dropped too, so a re-arm (or the next reject) re-binds into
        the CURRENT registry — a ``registry.reset()`` between tests must
        not leave the ledger incrementing orphaned families."""
        with self._lock:
            self._pools.clear()
            self._pool_seq = 0
            self._classes = {c: 0 for c in MEM_CLASSES}
            self._peaks = {c: 0 for c in MEM_CLASSES}
            self._weights.clear()
            self._last_reject_key = None
            self._g_bytes = None
            self._g_peak = None
            self._c_rejects = None
            self.audits = 0
            self.last_oom = None
            self._migration = {"bytes": 0, "pages": 0, "requests": 0}
            self._migration_log = []

    # -- cross-host migration accounting ------------------------------------

    def note_migration(self, *, nbytes: int, pages: int, requests: int = 1,
                       seconds: float = 0.0, src_host=None, dst_host=None,
                       outcome: str = "ok") -> None:
        """Account one request's KV-page transfer across a host boundary
        (the multi-host router feeds this per migrated request): bump
        the cumulative byte/page/request totals and append a bounded
        timeline entry — the byte audit's answer to "how much KV
        actually crossed DCN", next to the per-pool splits the
        destination's next :meth:`observe` re-balances."""
        with self._lock:
            self._migration["bytes"] += int(nbytes)
            self._migration["pages"] += int(pages)
            self._migration["requests"] += int(requests)
            self._migration_log.append({
                "bytes": int(nbytes), "pages": int(pages),
                "seconds": float(seconds), "src_host": src_host,
                "dst_host": dst_host, "outcome": outcome})
            del self._migration_log[:-MAX_MIGRATIONS]

    def migration_snapshot(self) -> Dict[str, Any]:
        """Cumulative migration totals + the bounded transfer timeline
        (embedded in ``memory.json`` / ``/statusz``'s memory section)."""
        with self._lock:
            return {"totals": dict(self._migration),
                    "recent": [dict(e) for e in self._migration_log]}

    # -- class accounting ---------------------------------------------------

    def _set_class_locked(self, cls: str, nbytes: int) -> None:
        self._classes[cls] = int(nbytes)
        if nbytes > self._peaks[cls]:
            self._peaks[cls] = int(nbytes)

    def note_class(self, cls: str, nbytes: int) -> None:
        """Feed one class's current byte count directly (the trainer's
        ``optimizer`` feed; pool classes go through :meth:`observe`)."""
        if cls not in self._classes:
            raise ValueError(f"unknown memory class {cls!r}; "
                             f"one of {MEM_CLASSES}")
        with self._lock:
            self._set_class_locked(cls, nbytes)
            self._publish_locked(force=True)

    def note_weights(self, params: Any) -> int:
        """Account a model parameter pytree (dtype-aware). Cached by the
        pytree object's identity plus a cheap content fingerprint (a
        recycled ``id()`` on a DIFFERENT pytree must re-walk, and the
        ledger never holds a strong reference that would pin dead
        weights on device), so feeding the same params every step costs
        a dict lookup, not a tree walk. Multiple models (fleet replicas
        sharing a process) sum; the table is LRU-bounded so dead models
        age out of the sum."""
        key = id(params)
        fp = self._params_fingerprint(params)
        with self._lock:
            entry = self._weights.get(key)
            if entry is not None and entry[0] == fp:
                self._weights.move_to_end(key)
                return entry[1]
            nb = pytree_nbytes(params)
            self._weights[key] = (fp, nb)
            self._weights.move_to_end(key)
            while len(self._weights) > MAX_POOLS:
                self._weights.popitem(last=False)
            self._set_class_locked(
                "weights", sum(e[1] for e in self._weights.values()))
            self._publish_locked(force=True)
        return nb

    @staticmethod
    def _params_fingerprint(params: Any):
        """id-reuse guard for the weights cache: the identity of the
        first leaf-ish member. A recycled dict id would also need its
        first value's id recycled to collide — and the fallout of that
        double coincidence is one stale byte count for one feed."""
        if isinstance(params, dict):
            for v in params.values():
                return id(v)
        elif isinstance(params, (list, tuple)) and params:
            return id(params[0])
        return None

    def class_bytes(self, cls: str) -> int:
        with self._lock:
            return self._classes.get(cls, 0)

    def peak_bytes(self, cls: str) -> int:
        with self._lock:
            return self._peaks.get(cls, 0)

    # -- pool accounting (the per-step feed) --------------------------------

    def _prune_dead_pools_locked(self) -> None:
        """Drop entries whose manager has been garbage-collected: a dead
        engine's last split must not keep inflating the class totals
        (and /memz) until enough new pools evict it."""
        dead = [k for k, p in self._pools.items()
                if p.ref is not None and p.ref() is None]
        for k in dead:
            del self._pools[k]

    def _pool_locked(self, mgr) -> _Pool:
        key = id(mgr)
        pool = self._pools.get(key)
        if pool is not None and (
                pool.num_pages != int(mgr.num_pages)
                or pool.page_size != int(mgr.page_size)
                or pool.page_bytes != int(mgr.page_nbytes)
                or pool.chips != (int(getattr(mgr, "mesh_chips", 1)) or 1)
                or (pool.ref is not None and pool.ref() is not mgr)):
            # recycled id(): a DIFFERENT manager landed on a dead one's
            # address — a stale entry's cached capacity would turn the
            # byte audit into a false RuntimeError inside engine.step
            del self._pools[key]
            pool = None
        if pool is not None:
            # LRU, not FIFO: the bound exists to shed short-lived test/
            # warmup pools — evicting the long-lived production pool
            # first would drop its attribution and reorder snapshots
            self._pools.move_to_end(key)
        if pool is None:
            self._prune_dead_pools_locked()
            self._pool_seq += 1
            pool = _Pool(label=f"pool{self._pool_seq}")
            try:                    # liveness probe for the prune pass
                pool.ref = weakref.ref(mgr)
            except TypeError:       # non-weakref-able manager: skip it
                pool.ref = None
            pool.refcounted = hasattr(mgr, "num_live_pages")
            # TP-sharded pools split every page's bytes evenly across
            # the mesh (head-sharded: whole GQA groups per chip), so
            # per-chip HBM cost = class bytes / chips — the capacity
            # answer an elastic resize changes
            pool.chips = int(getattr(mgr, "mesh_chips", 1)) or 1
            pool.num_pages = int(mgr.num_pages)
            pool.page_size = int(mgr.page_size)
            pool.usable_pages = int(mgr.usable_pages)
            pool.page_bytes = _mgr_page_nbytes(mgr)
            pool.pool_bytes = (int(mgr.k_pages.nbytes)
                               + int(mgr.v_pages.nbytes))
            # planner verdict: re-derive the plan from the pool's own
            # geometry + byte size; it must predict capacity exactly
            shape = mgr.k_pages.shape      # (L, P, page, kv_heads, dim)
            plan = plan_capacity(
                num_layers=int(shape[0]), num_kv_heads=int(shape[3]),
                head_dim=int(shape[4]), page_size=int(shape[2]),
                dtype_bytes=int(mgr.k_pages.dtype.itemsize),
                hbm_bytes=pool.pool_bytes)
            pool.verdict = plan_verdict(plan, mgr)
            self._pools[key] = pool
            while len(self._pools) > MAX_POOLS:
                self._pools.popitem(last=False)
        return pool

    def note_request(self, mgr, rid, *, prompt_len: int = 0,
                     cached_pages: int = 0, trace_id: str = "") -> None:
        """Record one admission's attribution metadata: how many of the
        request's pages were borrowed from the prefix cache (the rest
        are fresh). Entries for retired sequences are pruned by the next
        :meth:`observe`."""
        with self._lock:
            pool = self._pool_locked(mgr)
            pool.meta[rid] = {"prompt_len": int(prompt_len),
                              "cached_pages": int(cached_pages),
                              "trace_id": trace_id}

    def observe(self, mgr, *, reserved: Optional[Dict[Any, int]] = None,
                cache_stats: Optional[Dict[str, Any]] = None,
                audit: bool = True) -> Dict[str, int]:
        """One accounting round over a paged manager — the engine calls
        this after every step (gated on ``memory_armed``): derive the
        free/live/spec/cached page split, refresh per-request holdings,
        update class totals + peaks, publish gauges (decimated) and run
        the **byte conservation audit**. ``reserved`` maps live seq ids
        to their admission page reservation: pages held beyond it are
        the speculative tail (class ``kv_spec``). Raises ``RuntimeError``
        when the books don't balance.

        Every call is a FULL accounting round — the feeding CADENCE is
        the feeder's choice: invariant-checked engines feed every step
        (the audit is the point), engines that opted out of per-step
        invariant checking decimate their feed instead
        (``ContinuousBatchingEngine._note_memory``)."""
        with self._lock:
            pool = self._pool_locked(mgr)
            pool.observes += 1
            pb = pool.page_bytes
            tables = mgr._tables
            free = int(mgr.num_free_pages)
            # per-request page holdings (ints only on this hot path —
            # the full attribution dicts materialise on the cold
            # snapshot() read) + spec tails past each reservation
            held = {rid: len(t) for rid, t in tables.items()}
            spec_pages = 0
            if reserved:
                tails = {}
                for rid, r in reserved.items():
                    h = held.get(rid, 0)
                    if h > r:
                        tails[rid] = h - int(r)
                        spec_pages += h - int(r)
                pool.tails = tails
            elif pool.tails:
                pool.tails = {}
            pool.held = held
            if pool.refcounted:
                live = int(mgr.num_live_pages)
                cached = int(mgr.num_cached_pages)
            else:
                # exclusive ownership: live pages = block-table holdings
                # (derived INDEPENDENTLY of the free list, so the byte
                # audit below is a real cross-check, not an identity)
                live = sum(held.values())
                cached = 0
            # prune admission meta for retired sequences (meta only
            # grows at admission, so a size mismatch is the trigger)
            if len(pool.meta) != len(held):
                for rid in [r for r in pool.meta if r not in held]:
                    del pool.meta[rid]
            split = {
                "kv_free": free,
                "kv_live": live - spec_pages,
                "kv_spec": spec_pages,
                "kv_cached": cached,
            }
            pool.split = split
            if cache_stats is not None:
                pool.cache_stats = cache_stats    # live reference; the
            # snapshot copies it (small ints, mutated in place upstream)
            # class totals sum across LIVE pools (fleet replicas in-
            # process; a dead engine's last split ages out immediately)
            self._prune_dead_pools_locked()
            for cls in ("kv_free", "kv_live", "kv_spec", "kv_cached"):
                nb = 0
                for p in self._pools.values():
                    nb += p.split.get(cls, 0) * p.page_bytes
                self._set_class_locked(cls, nb)
            if audit:
                self.audits += 1
                total_b = (free + live + cached) * pb
                pool_b = pool.usable_pages * pb
                if total_b != pool_b:
                    raise RuntimeError(
                        f"byte conservation violated on {pool.label}: "
                        f"free {split['kv_free'] * pb} + live "
                        f"{split['kv_live'] * pb} + spec "
                        f"{split['kv_spec'] * pb} + cached "
                        f"{split['kv_cached'] * pb} = {total_b} != "
                        f"{pool_b} pool bytes "
                        f"({pool.usable_pages} usable pages × {pb})")
            # a pool's first observation publishes immediately (a scrape
            # right after arm must not read zeros); later rounds decimate
            self._publish_locked(force=pool.observes == 1)
            return split

    def _publish_locked(self, force: bool = False) -> None:
        """Refresh the registry gauges (decimated: every
        ``publish_every`` observations unless forced). Peaks and the
        snapshot read the host books directly, so decimation only
        affects scrape freshness."""
        if self._g_bytes is None:
            return
        if not force:
            self._since_publish += 1
            if self._since_publish < self._publish_every:
                return
        self._since_publish = 0
        for cls in MEM_CLASSES:
            self._g_bytes.set(self._classes[cls], **{"class": cls})
            self._g_peak.set(self._peaks[cls], **{"class": cls})

    # -- OOM forensics ------------------------------------------------------

    def note_oom(self, source: str, mgr=None, *, need_pages: int = 0,
                 free_pages: int = 0, request_id=None,
                 trace_id: str = "") -> None:
        """Allocation-failure hook (``allocate``/``extend``/``grow_to``
        raise sites, engine infeasibility): emit an ``oom_pressure``
        event naming the byte shortfall and the dominant (exhausting)
        class, then trigger a once-per-reason flight bundle whose
        ``memory.json`` is the full postmortem. Never raises — this sits
        in failure paths."""
        if not memory_armed[0]:
            return
        try:
            with self._lock:
                pb = 0
                if mgr is not None:
                    pool = self._pool_locked(mgr)
                    pb = pool.page_bytes
                short = max(0, int(need_pages) - int(free_pages))
                if mgr is not None:
                    # the FAILING pool's own split (a sibling replica's
                    # healthy pool must not name the exhausting class);
                    # derived live off the manager — the pool may never
                    # have been observed before its first OOM. Spec
                    # tails come from the last observe's reservation
                    # split, so a draft-dominated pool names kv_spec,
                    # not the committed sequences.
                    occ = pool_occupancy(mgr)
                    spec = sum(pool.tails.values()) if pool.tails else 0
                    kv = {"kv_live": max(0, int(occ["live"]) - spec) * pb,
                          "kv_spec": spec * pb,
                          "kv_cached": int(occ["cached"]) * pb}
                else:
                    kv = {c: self._classes[c]
                          for c in ("kv_live", "kv_spec", "kv_cached")}
                exhausting = max(kv, key=kv.get) if any(kv.values()) \
                    else "kv_live"
                self.last_oom = {
                    "source": source,
                    "need_pages": int(need_pages),
                    "free_pages": int(free_pages),
                    "pages_short": short,
                    "bytes_short": short * pb,
                    "exhausting_class": exhausting,
                    "request_id": request_id,
                }
            emit_event("oom_pressure", source=source,
                       need_pages=int(need_pages),
                       free_pages=int(free_pages),
                       bytes_short=short * pb,
                       exhausting_class=exhausting,
                       request_id=request_id, trace_id=trace_id)
            flight_recorder.auto_dump(f"oom_{source}")
        except Exception:       # forensics must never worsen the failure
            pass

    def note_admission_reject(self, mgr, *, request_id, need_pages: int,
                              free_pages: int, trace_id: str = "") -> None:
        """Scheduler page-admission rejection: count every blocked step
        (``paddle_mem_admission_rejects_total`` — the honest autoscaler
        pressure signal) and emit one ``oom_pressure`` event with the
        byte shortfall per distinct blocked request (a head-of-queue
        request is re-judged every step; one event per victim is signal,
        one per step is spam)."""
        c = self._c_rejects
        if c is None:
            # bound lazily but UNCONDITIONALLY of arming: the pressure
            # counter counts whether or not the memory plane is armed —
            # its meaning must not depend on arm history (the event and
            # dump below stay armed-gated). The local `c` is what gets
            # incremented: a concurrent reset() nulling the handle must
            # not turn this into an AttributeError inside the scheduler.
            from .registry import get_registry
            c = get_registry().counter(
                "paddle_mem_admission_rejects_total",
                "scheduler admissions deferred for KV pages (per "
                "blocked step; the event carries the byte shortfall)")
            with self._lock:
                if self._c_rejects is None:
                    self._c_rejects = c
        c.inc()
        if not memory_armed[0]:
            return
        key = (id(mgr), request_id)
        with self._lock:
            if key == self._last_reject_key:
                return
            self._last_reject_key = key
        self.note_oom("admission", mgr, need_pages=need_pages,
                      free_pages=free_pages, request_id=request_id,
                      trace_id=trace_id)

    # -- history integration ------------------------------------------------

    def attach_history(self, history) -> None:
        """Track every class's byte level into a
        :class:`~.timeseries.MetricHistory` ring (``mem.<class>_bytes``
        gauge series) — the sensor plane samples them on its own
        decimated cadence (``SignalBus.attach_scheduler`` wires this)."""
        for cls in MEM_CLASSES:
            history.track_gauge(f"mem.{cls}_bytes",
                                lambda c=cls: float(self.class_bytes(c)))

    # -- reading ------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The ``memory.json`` / ``/memz`` document: class bytes +
        peaks, per-pool geometry + planner verdict + page split +
        per-request holders + prefix-cache stats, and the last OOM."""
        with self._lock:
            self._prune_dead_pools_locked()
            pools = []
            for p in self._pools.values():
                pb = p.page_bytes
                requests = {}
                for rid, held in p.held.items():
                    meta = p.meta.get(rid)
                    cached_p = meta["cached_pages"] if meta else 0
                    requests[str(rid)] = {
                        "pages": held,
                        "bytes": held * pb,
                        "cached_bytes": cached_p * pb,
                        "fresh_bytes": (held - cached_p) * pb,
                        "spec_tail_pages": p.tails.get(rid, 0),
                        "prompt_len": meta["prompt_len"] if meta else 0,
                        "trace_id": meta["trace_id"] if meta else "",
                    }
                pools.append({
                    "label": p.label,
                    "page_bytes": pb,
                    "page_size": p.page_size,
                    "num_pages": p.num_pages,
                    "usable_pages": p.usable_pages,
                    "pool_bytes": p.pool_bytes,
                    "planner": p.verdict,
                    "pages": dict(p.split),
                    "bytes": {cls: pages * pb
                              for cls, pages in p.split.items()},
                    # the per-chip view of a head-sharded pool: every
                    # page's bytes split evenly across the TP mesh
                    "chips": p.chips,
                    "bytes_per_chip": {cls: pages * pb // p.chips
                                       for cls, pages in p.split.items()},
                    "requests": requests,
                    "cache": dict(p.cache_stats)
                    if p.cache_stats is not None else None,
                    "observes": p.observes,
                })
            return {
                "armed": memory_armed[0],
                "classes": dict(self._classes),
                "peaks": dict(self._peaks),
                "audits": self.audits,
                "pools": pools,
                "last_oom": self.last_oom,
                "migration": {"totals": dict(self._migration),
                              "recent": [dict(e)
                                         for e in self._migration_log]},
            }

    def statusz(self) -> Dict[str, Any]:
        """The /statusz ``memory`` section: the class totals + peaks and
        per-pool planner verdicts (the full per-request table lives on
        ``/memz``)."""
        with self._lock:
            self._prune_dead_pools_locked()
            return {
                "armed": memory_armed[0],
                "classes": dict(self._classes),
                "peaks": dict(self._peaks),
                "audits": self.audits,
                "pools": {p.label: {"pages": dict(p.split),
                                    "planner_exact":
                                        p.verdict.get("exact"),
                                    "requests": len(p.held)}
                          for p in self._pools.values()},
                "last_oom": self.last_oom,
                "migration": dict(self._migration),
            }


#: the process-global ledger the engine/scheduler/trainer feed
memory_ledger = MemoryLedger()


def note_oom(source: str, mgr=None, **kw) -> None:
    """Module-level convenience for the pool's raise sites (gated on
    ``memory_armed`` inside — safe to call unconditionally from rare
    failure paths)."""
    memory_ledger.note_oom(source, mgr, **kw)
