"""Deterministic postmortem replay: re-execute a debug bundle's journal
and localize the first divergence.

::

    python -m paddle_tpu.observability.replay <bundle.tar.gz>

The bundle's ``journal.jsonl`` (:mod:`.journal`) records the complete
nondeterminism frontier of a fleet run — model geometry, fleet
topology, request arrivals with resolved sampler seeds, per-step clock
samples, consumed chaos faults, health transitions and terminal
outcomes. This module rebuilds the fleet from the head frame (CPU
smoke geometry: the same ``LlamaConfig`` + ``init_stacked_params``
seed), re-drives the step loop from the journaled arrivals/clock/chaos,
and verifies:

* **frame-sequence match** — every journaled frame re-occurs, in
  order, with an identical canonical payload (this subsumes the
  event-sequence and health-transition checks);
* **byte-identical token streams** — ``outcome`` frames carry the full
  stream tokens + crc32, so a single flipped token surfaces as a
  localized divergence, not a silent pass;
* **page conservation** — every replica pool's books balance after the
  drive, and a fully drained replay leaks zero pages.

On mismatch the report names the *first divergence* — (step, replica,
component, journaled-vs-observed) — instead of a wall of diffs. A
bundle dumped mid-incident (e.g. a ``replica_ejected_*`` auto-dump)
journals a prefix of the run; replay completes the step in flight, so
observed frames extending past the journal are expected, and in-flight
requests remain ``pending`` rather than failing the replay.

Structured refusals (exit code 2) instead of wrong answers: a rotated
ring (arrivals evicted), a non-``FleetRouter`` topology, autoscale
topology changes or disagg handoffs mid-window, and grammar arrivals
without a journaled vocab all refuse with a code — replay never
guesses at inputs it does not have.

NOTE: replay drives the PROCESS-global journal recorder (the taps it
verifies write there). In-process callers must snapshot their own
journal (``journal.encode()``) before calling :func:`replay_bundle`.
"""

from __future__ import annotations

import argparse
import json
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from .journal import (DecodedJournal, Divergence, JournalError,
                      decode_journal, first_divergence, journal)


class ReplayRefused(Exception):
    """The bundle is structurally un-replayable; ``code`` says why."""

    def __init__(self, code: str, detail: str = ""):
        self.code = code
        self.detail = detail
        super().__init__(f"replay refused ({code}): {detail}")

    def as_dict(self) -> Dict[str, str]:
        return {"code": self.code, "detail": self.detail}


@dataclass
class ReplayReport:
    """The replay verdict; ``as_dict`` is the CLI's ``--json`` body."""

    bundle: str
    ok: bool
    refused: Optional[Dict[str, str]] = None
    replicas: int = 0
    steps: int = 0
    arrivals: int = 0
    outcomes: int = 0
    pending: int = 0
    leaked_pages: int = 0
    conservation: str = "ok"
    divergence: Optional[Divergence] = None

    def as_dict(self) -> Dict[str, Any]:
        return {
            "bundle": self.bundle, "ok": self.ok,
            "refused": self.refused, "replicas": self.replicas,
            "steps": self.steps, "arrivals": self.arrivals,
            "outcomes": self.outcomes, "pending": self.pending,
            "leaked_pages": self.leaked_pages,
            "conservation": self.conservation,
            "divergence": (None if self.divergence is None
                           else self.divergence.as_dict()),
        }


class ReplayClock:
    """A settable injected clock: the drive loop pins it to each
    journaled sample; intra-step sleeps advance it exactly as the
    original fake clock's did."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def set(self, t: float) -> None:
        self.t = float(t)

    def sleep(self, dt: float) -> None:
        self.t += float(dt)


# -- reconstruction ----------------------------------------------------------

def rebuild_model(head: Dict[str, Any]):
    """(cfg, params) from the head frame's ``model_spec``."""
    from ..models import llama as L
    m = head.get("model") or {}
    arch = m.get("arch")
    ctor = getattr(L, str(arch), None)
    if ctor is None:
        raise ReplayRefused("model", f"unknown model arch {arch!r}")
    kwargs = dict(m.get("config") or {})
    if "dtype" in kwargs:
        try:
            kwargs["dtype"] = np.dtype(kwargs["dtype"])
        except Exception:
            raise ReplayRefused(
                "model", f"unresolvable dtype {kwargs['dtype']!r}")
    cfg = ctor(**kwargs)
    params = L.init_stacked_params(cfg, seed=int(m.get("params_seed", 0)))
    return cfg, params


def rebuild_injector(frames: List[Dict[str, Any]]):
    """A :class:`FaultInjector` whose schedule is exactly the journaled
    consumed faults — replay re-fires what fired, nothing else."""
    from ..resilience.faults import Fault, FaultInjector
    sched = []
    for f in frames:
        if f.get("t") != "fault":
            continue
        rec = f.get("fault") or {}
        sched.append(Fault(
            event=str(rec.get("event")), step=int(rec.get("step", 0)),
            replica=rec.get("replica"), chip=rec.get("chip"),
            host=rec.get("host"), delay_s=rec.get("delay_s")))
    return FaultInjector(schedule=sched) if sched else None


def rebuild_fleet(head: Dict[str, Any], clock: ReplayClock, injector):
    """The fleet from the head frame's ``journal_topology``."""
    from ..inference.decoding import (ContinuousBatchingEngine,
                                      GenerationConfig)
    from ..serving import (FleetRouter, HealthConfig, ReplicaHandle,
                           RouterConfig, SchedulerConfig)

    fleet = head.get("fleet") or {}
    kind = fleet.get("router_kind")
    if kind != "FleetRouter":
        raise ReplayRefused(
            "topology", f"router_kind={kind!r} is not replayable yet "
                        "(only single-process FleetRouter fleets)")
    specs = fleet.get("replicas") or []
    if not specs:
        raise ReplayRefused("topology", "head frame names no replicas")
    cfg, params = rebuild_model(head)
    replicas = []
    for spec in specs:
        e = spec.get("engine") or {}
        eng = ContinuousBatchingEngine(
            cfg, GenerationConfig(**(spec.get("generation") or {})),
            num_slots=int(e["num_slots"]), page_size=int(e["page_size"]),
            max_seq_len=int(e["max_seq_len"]),
            num_pages=int(e["num_pages"]), chunk=int(e["chunk"]),
            prefix_cache=bool(e.get("prefix_cache", False)),
            speculative=bool(e.get("speculative", False)),
            spec_k=int(e.get("spec_k") or 4),
            unified=bool(e.get("unified", True)))
        replicas.append(ReplicaHandle(
            int(spec["replica_id"]), eng,
            config=SchedulerConfig(**(spec.get("scheduler") or {})),
            health_config=HealthConfig(**(spec.get("health") or {})),
            clock=clock, sleep=clock.sleep))
    router = FleetRouter(
        replicas, config=RouterConfig(**(fleet.get("config") or {})),
        clock=clock, sleep=clock.sleep, fault_injector=injector)
    return cfg, params, router, replicas


def _rebuild_sampler(payload: Optional[Dict[str, Any]]):
    if payload is None:
        return None
    from ..inference.sampling import SamplerConfig
    return SamplerConfig(**payload)


def _rebuild_grammar(payload: Optional[Dict[str, Any]],
                     head: Dict[str, Any], eos: Optional[int]):
    if payload is None:
        return None
    vocab = (head.get("model") or {}).get("vocab")
    if vocab is None:
        raise ReplayRefused(
            "grammar", "journal has grammar-constrained arrivals but "
                       "the head frame carries no vocab")
    from ..inference.constrain import compile_regex
    dfa = compile_regex(str(payload.get("pattern")), vocab,
                        eos_token_id=payload.get("eos_token_id", eos))
    want = payload.get("fingerprint")
    if want is not None and getattr(dfa, "fingerprint", None) != want:
        raise ReplayRefused(
            "grammar", f"recompiled DFA fingerprint "
                       f"{getattr(dfa, 'fingerprint', None)!r} != "
                       f"journaled {want!r}")
    return dfa


# -- the drive ---------------------------------------------------------------

def _refuse_unreplayable(decoded: DecodedJournal) -> None:
    if decoded.dropped:
        raise ReplayRefused(
            "rotated", f"journal ring evicted {decoded.dropped} leading "
                       "frames — arrivals are incomplete; re-arm with a "
                       "larger capacity")
    for f in decoded.frames:
        t = f.get("t")
        if t == "scale":
            raise ReplayRefused(
                "topology_changed",
                f"autoscale record {f.get('scale_seq')} "
                f"({f.get('action')}) changed the fleet mid-window")
        if t == "handoff":
            raise ReplayRefused(
                "disagg", "disagg KV handoffs in window — DisaggRouter "
                          "replay is not supported yet")


def replay_journal(decoded: DecodedJournal,
                   bundle: str = "<journal>") -> ReplayReport:
    """Re-execute a decoded journal; see the module docstring for the
    verification contract."""
    report = ReplayReport(bundle=bundle, ok=False)
    try:
        _refuse_unreplayable(decoded)
        clock = ReplayClock()
        injector = rebuild_injector(decoded.frames)
        cfg, params, router, replicas = rebuild_fleet(
            decoded.head, clock, injector)
    except ReplayRefused as e:
        report.refused = e.as_dict()
        return report
    report.replicas = len(replicas)
    eos = router.replicas[next(iter(router.replicas))] \
        .engine.config.eos_token_id

    # record with the very taps being verified: the process journal
    journal.arm(capacity=max(4 * len(decoded.frames) + 64, 4096))
    journal.record_head(**decoded.head)
    try:
        for f in decoded.frames:
            t = f.get("t")
            if t == "step":
                clock.set(float(f["clock"]))
                router.step(params)
                report.steps += 1
            elif t == "arrival":
                clock.set(float(f["clock"]))
                try:
                    grammar = _rebuild_grammar(f.get("grammar"),
                                               decoded.head, eos)
                except ReplayRefused as e:
                    report.refused = e.as_dict()
                    return report
                router.submit(
                    np.asarray(f["prompt"], np.int32),
                    priority=int(f.get("priority", 0)),
                    deadline_ms=f.get("deadline_ms"),
                    max_new_tokens=int(f["budget"]),
                    sampler=_rebuild_sampler(f.get("sampler")),
                    grammar=grammar)
                report.arrivals += 1
            elif t == "outcome":
                report.outcomes += 1
            # fault/health/admit/wire frames are outputs: the re-drive
            # regenerates them and the frame diff below judges them
        observed = decode_journal(journal.encode())
    finally:
        journal.disarm()

    report.divergence = first_divergence(decoded.frames, observed.frames)
    report.pending = router.pending
    leaked = 0
    conservation = "ok"
    for rid in sorted(router.replicas):
        eng = router.replicas[rid].engine
        check = getattr(eng.mgr, "check_conservation", None)
        if check is not None:
            try:
                check()
            except Exception as e:
                conservation = f"replica {rid}: {e!r}"
        if report.pending == 0 and eng.cache is None:
            # fully drained and no prefix cache holding retired pages:
            # every page must be back on the free list
            leaked += (int(eng.mgr.usable_pages)
                       - int(eng.mgr.num_free_pages))
    report.leaked_pages = leaked
    report.conservation = conservation
    report.ok = (report.divergence is None and leaked == 0
                 and conservation == "ok")
    return report


def replay_bundle(path: str) -> ReplayReport:
    """Validate + replay one debug-bundle tarball."""
    from .flight import BundleError, validate_bundle
    try:
        doc = validate_bundle(path)
    except BundleError as e:
        return ReplayReport(bundle=path, ok=False,
                            refused={"code": f"bundle:{e.code}",
                                     "detail": e.detail})
    except JournalError as e:
        return ReplayReport(bundle=path, ok=False,
                            refused={"code": f"journal:{e.code}",
                                     "detail": e.detail})
    decoded = doc.get("journal")
    if decoded is None:
        return ReplayReport(
            bundle=path, ok=False,
            refused={"code": "no_journal",
                     "detail": "bundle has no journal.jsonl — was the "
                               "journal armed when it was dumped?"})
    return replay_journal(decoded, bundle=path)


# -- CLI ---------------------------------------------------------------------

def _format_report(r: ReplayReport) -> str:
    lines = [f"replay: {r.bundle}"]
    if r.refused is not None:
        lines.append(f"  REFUSED [{r.refused['code']}] "
                     f"{r.refused['detail']}")
        return "\n".join(lines)
    lines.append(
        f"  fleet: {r.replicas} replicas; drove {r.steps} steps, "
        f"{r.arrivals} arrivals, {r.outcomes} journaled outcomes")
    lines.append(
        f"  pending at journal end: {r.pending}; leaked pages: "
        f"{r.leaked_pages}; conservation: {r.conservation}")
    if r.divergence is None:
        lines.append("  OK — byte-identical re-execution, every "
                     "journaled frame reproduced in order")
    else:
        d = r.divergence
        lines.append(
            f"  FIRST DIVERGENCE at step {d.step}, replica {d.replica}, "
            f"component {d.component} (frame {d.index}):")
        lines.append(f"    journaled: {json.dumps(d.journaled)}")
        lines.append(f"    observed:  {json.dumps(d.observed)}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.replay",
        description="Re-execute a debug bundle's black-box journal and "
                    "report the first divergence, if any.")
    ap.add_argument("bundle", help="debug bundle tarball (.tar.gz)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report")
    args = ap.parse_args(argv)
    try:
        report = replay_bundle(args.bundle)
    except JournalError as e:
        report = ReplayReport(bundle=args.bundle, ok=False,
                              refused={"code": f"journal:{e.code}",
                                       "detail": e.detail})
    if args.json:
        print(json.dumps(report.as_dict(), indent=1, default=str))
    else:
        print(_format_report(report))
    if report.ok:
        return 0
    return 2 if report.refused is not None else 1


if __name__ == "__main__":
    raise SystemExit(main())
