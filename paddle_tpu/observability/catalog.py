"""Central telemetry catalog: every registry-owned metric, every
structured event kind and every request-path span name, declared in ONE
place.

Motivation (ISSUE 8): a typo'd metric name or label, or a misspelled
``emit_event`` kind, silently mints a brand-new series — dashboards and
alerts keep watching the old name and see flatlines. This module is the
contract; ``tpu-lint``'s ``metric-contract`` / ``event-contract`` rules
(:mod:`paddle_tpu.analysis.contracts`) statically check every call site
in the tree against it, in both directions (undeclared use AND declared-
but-unused entries fail).

Scope notes:

* only *registry-owned* families appear in ``METRICS`` — subsystem sinks
  (``ServingMetrics``, ``ResilienceMetrics``) declare their own families
  in their ``__init__`` and are checked against those declarations;
* label tuples are positional contracts: every call site must pass
  exactly these label names (the registry enforces it at runtime too —
  this catches it at lint time, before the conflicting registration
  crashes a prod scrape).
"""

from __future__ import annotations

#: registry-owned families: name -> (kind, label names)
METRICS = {
    # -- runtime dispatch / compile telemetry (observability/runtime.py) --
    "paddle_runtime_op_duration_us": ("histogram", ()),
    "paddle_runtime_recompiles_total": ("counter", ("fn",)),
    "paddle_runtime_compile_seconds": ("histogram", ("fn",)),
    # -- event log (observability/events.py) ------------------------------
    "paddle_events_dropped_total": ("counter", ()),
    # -- SLO engine (observability/slo.py) ---------------------------------
    "paddle_slo_burn_rate": ("gauge", ("slo", "window")),
    "paddle_slo_budget_remaining": ("gauge", ("slo",)),
    "paddle_slo_breached": ("gauge", ("slo",)),
    "paddle_slo_breaches_total": ("counter", ("slo",)),
    # -- goodput / stragglers (observability/goodput.py) -------------------
    "paddle_goodput_ratio": ("gauge", ()),
    "paddle_stragglers_total": ("counter", ("source",)),
    # -- anomaly detection (observability/anomaly.py) -----------------------
    "paddle_anomaly_events_total": ("counter", ("series", "detector")),
    "paddle_anomaly_score": ("gauge", ("series",)),
    # -- signal bus (observability/signals.py) ------------------------------
    "paddle_signal_value": ("gauge", ("signal",)),
    # -- HBM memory ledger (observability/memory.py) ------------------------
    "paddle_mem_bytes": ("gauge", ("class",)),
    "paddle_mem_peak_bytes": ("gauge", ("class",)),
    "paddle_mem_admission_rejects_total": ("counter", ()),
    # -- profile-guided fusion pass (jit/fusion.py) -------------------------
    "paddle_fusion_admitted_total": ("counter", ("region",)),
    "paddle_fusion_skipped_total": ("counter", ("reason",)),
    "paddle_fusion_active": ("gauge", ("region",)),
    # -- elastic mesh resize (serving/elastic.py) ---------------------------
    "paddle_mesh_chips": ("gauge", ("replica",)),
    "paddle_mesh_resizes_total": ("counter", ("replica",)),
    "paddle_mesh_chip_faults_total": ("counter", ("replica", "kind")),
    # -- fleet router (serving/router.py) ----------------------------------
    "paddle_router_requests_total": ("counter", ("replica", "outcome")),
    "paddle_router_replica_state": ("gauge", ("replica",)),
    "paddle_router_failovers_total": ("counter", ()),
    "paddle_router_prefix_affinity_hits_total": ("counter", ()),
    "paddle_router_parked_age_seconds": ("histogram", ()),
    # -- disaggregated prefill/decode fleet (serving/roles.py) ---------------
    "paddle_router_replica_role": ("gauge", ("replica",)),
    "paddle_handoff_requests_total": ("counter", ("outcome",)),
    "paddle_handoff_pages_total": ("counter", ()),
    "paddle_handoff_bytes_total": ("counter", ()),
    "paddle_handoff_seconds": ("histogram", ()),
    # -- autoscaling control plane (serving/autoscale.py) --------------------
    "paddle_autoscale_decisions_total": ("counter", ("action",)),
    "paddle_autoscale_replicas": ("gauge", ()),
    # -- speculative decoding (inference/speculative.py) -------------------
    "paddle_spec_drafted_tokens_total": ("counter", ("replica",)),
    "paddle_spec_accepted_tokens_total": ("counter", ("replica",)),
    "paddle_spec_rejected_tokens_total": ("counter", ("replica",)),
    "paddle_spec_acceptance_ratio": ("gauge", ("replica",)),
    # -- sampling epilogue / constrained decoding (inference/sampling.py) --
    "paddle_sampling_requests_total": ("counter", ("mode",)),
    "paddle_sampling_tokens_total": ("counter", ("mode",)),
    "paddle_sampling_violations_total": ("counter", ()),
    "paddle_sampling_grammar_states": ("gauge", ()),
    # -- multi-host serving / DCN page migration (serving/multihost.py) -----
    "paddle_migration_bytes_total": ("counter", ()),
    "paddle_migration_pages_total": ("counter", ()),
    "paddle_migration_requests_total": ("counter", ("outcome",)),
    "paddle_migration_seconds": ("histogram", ()),
    "paddle_host_state": ("gauge", ("host",)),
    "paddle_host_statusz_errors_total": ("counter", ("host",)),
    "paddle_host_heartbeat_rtt_seconds": ("histogram", ("host",)),
    # -- telemetry federation (observability/federation.py) ------------------
    "paddle_federation_frames_total": ("counter", ("host",)),
    "paddle_federation_spans_merged_total": ("counter", ("host",)),
    "paddle_federation_clock_offset_seconds": ("gauge", ("host",)),
    "paddle_federation_clock_error_bound_seconds": ("gauge", ("host",)),
    "paddle_federation_stale_mirrors": ("gauge", ()),
    # -- black-box incident journal (observability/journal.py) --------------
    "paddle_journal_frames_total": ("counter", ("type",)),
    "paddle_journal_dropped_total": ("counter", ()),
    # -- prefix cache (kvcache/cache.py) -----------------------------------
    "paddle_kvcache_hits_total": ("counter", ()),
    "paddle_kvcache_misses_total": ("counter", ()),
    "paddle_kvcache_evictions_total": ("counter", ()),
    "paddle_kvcache_cow_copies_total": ("counter", ()),
    "paddle_kvcache_cached_tokens_total": ("counter", ()),
    "paddle_kvcache_pages": ("gauge", ("state",)),
}

#: every structured-event kind the tree may emit (observability/events.py)
EVENT_KINDS = {
    # serving scheduler
    "shed", "cancel", "step_retry", "degraded", "slo_degrade_shed",
    # SLO engine
    "slo_breach", "slo_recovered",
    # anomaly detection (sensor plane)
    "anomaly",
    # HBM memory ledger (allocation failure / page-admission shortfall)
    "oom_pressure",
    # resilience trainer
    "save_failure", "preempt_flush", "rollback", "step_skipped",
    "straggler",
    # runtime compile telemetry
    "recompile",
    # flight recorder
    "debug_dump",
    # incident journal: decode hit a torn/empty tail (power-loss
    # analogue) — the readable prefix is still served, but flagged
    "journal_truncated",
    # fleet router
    "replica_ejected", "replica_recovered", "replica_draining",
    "replica_drained", "failover",
    # elastic mesh resize (chip-level fault -> re-shard -> rejoin)
    "chip_lost", "mesh_resized",
    # multi-host serving: an engine PROCESS died / a live request's KV
    # pages crossed a host boundary (graceful drain or loss recovery)
    "host_lost", "page_migration",
    # fleet router: an unroutable parked request's deadline lapsed
    # before any replica healed (the all-down shed scale-up watches)
    "parked_expired",
    # disaggregated fleet: a replica changed phase role / a finished
    # prefill's KV pages handed off to a decode replica
    "role_changed", "kv_handoff",
    # autoscaling control plane: the fleet changed shape
    "scale_up", "scale_down",
    # prefix cache
    "cache_hit", "cache_evict",
    # speculative decoding (draft rejection -> per-row paged rollback)
    "spec_rollback",
    # constrained decoding: the host-side audit of the in-program grammar
    # mask caught an illegal token (a bug tripwire — the device mask
    # should make this impossible)
    "constraint_violation",
    # profile-guided fusion pass (jit/fusion.py): a hot chain installed
    # as a fused megaregion / skipped with a structured reason (stale
    # artifact symbol-missing, schema-mismatch, no-region, ...)
    "fusion_applied", "fusion_skipped",
}

#: every request-path span the tree may emit (``profiler.record.
#: emit_span`` / ``ServingMetrics.span``): canonical name -> allowed
#: ``args`` fields. Namespaced spans (``<metrics namespace>.<name>``)
#: are declared by their suffix — call sites build the prefix with an
#: f-string whose trailing literal is checked. The timeline collector's
#: critical-path attribution (observability/timeline.py) keys on these
#: names, so a typo'd span silently drops a segment from every request
#: breakdown; tpu-lint's ``span-contract`` rule checks both directions.
SPANS = {
    # scheduler request lifecycle (serving/scheduler.py); the request
    # envelope and admission spans carry the memory ledger's per-request
    # attribution (pages held, cached-vs-fresh bytes) so /tracez answers
    # "what did this request cost in HBM" next to "where did its time go"
    "request": ("request_id", "kv_pages", "cached_bytes", "fresh_bytes"),
    "step": (),
    "queue_wait": ("request_id",),
    "admission": ("request_id", "kv_pages", "cached_bytes",
                  "fresh_bytes"),
    # engine phases (inference/decoding.py)
    "engine.prefill": ("request_id", "slot", "prefill_tokens", "bucket",
                       "prompt_len", "cached_tokens"),
    "engine.decode_chunk": ("request_id", "slot", "chunk"),
    "engine.spec_draft": ("request_id", "slot", "drafted"),
    "engine.spec_round": ("request_id", "slot", "drafted"),
    # fleet router envelope + failover attribution (serving/router.py)
    "router.request": ("request_id", "outcome", "failovers"),
    "router.failover_gap": ("request_id", "to_replica", "attempt"),
    # multi-host page migration (serving/multihost.py): the whole
    # per-request drain and its nested DCN wire window (export ->
    # import) — the timeline sweep's `migration` / `dcn_transfer`
    # segments in cross-host trace trees
    "router.migration": ("request_id", "src", "dst", "pages", "bytes"),
    "router.dcn_transfer": ("request_id", "bytes", "pages"),
    # disaggregated fleet: one prefill->decode KV handoff (export ->
    # wire round-trip -> import -> redispatch), serving/roles.py
    "router.kv_handoff": ("request_id", "src", "dst", "pages", "bytes"),
}


def declared_metric(name: str):
    """(kind, labels) or None — runtime helper mirror of the lint rule."""
    return METRICS.get(name)


def declared_event(kind: str) -> bool:
    return kind in EVENT_KINDS


def declared_span(name: str):
    """Allowed args fields for a span name (suffix-resolved like the
    lint rule) or None — runtime helper mirror of ``span-contract``."""
    if name in SPANS:
        return SPANS[name]
    return SPANS.get(name.rsplit(".", 1)[-1])
