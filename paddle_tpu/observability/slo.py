"""Declarative SLOs evaluated as multi-window burn rates.

An :class:`SLObjective` names a target ("95% of TTFTs under 200 ms",
"99% of submissions not shed") and a *sampler* that reads the CUMULATIVE
(bad, total) event counts from the metrics registry — histograms via
:func:`latency_objective` (bucket counts above a threshold), counters via
:func:`ratio_objective`. The :class:`SLOMonitor` snapshots every
objective once per ``tick()`` and judges health with the classic
multi-window burn-rate rule (Google SRE workbook ch. 5):

* ``burn = bad_fraction / error_budget`` over a window — burn 1.0 spends
  the budget exactly at the end of the SLO period, 10.0 spends it 10x
  too fast;
* a **breach** requires the FAST window (5m-equivalent by default) AND
  the SLOW window (1h-equivalent) both past the threshold, so a single
  slow request cannot page but a sustained regression pages quickly;
* **recovery** is when the fast window drops back under the threshold —
  the slow window is deliberately ignored there, or a recovered system
  would stay "breached" for the rest of the hour.

Time is an injected ``clock`` (seconds, monotonic). The scheduler passes
its OWN clock when it attaches a monitor, so tests driving a fake clock
get byte-deterministic breach/recover transitions — this module must
never read the wall clock itself (lint-enforced by
``tests/test_observability_lint.py``).

On every transition the monitor emits ``slo_breach``/``slo_recovered``
JSONL events and keeps ``paddle_slo_burn_rate{slo,window}`` and
``paddle_slo_budget_remaining{slo}`` gauges fresh; an ``on_breach``
callback lets the serving scheduler shed load the moment an objective
burns (see ``ServingScheduler.attach_slo_monitor``).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ..core.histogram import Histogram
from .events import emit_event
from .flight import flight_armed, flight_recorder
from .registry import get_registry


@dataclass
class SLObjective:
    """One objective: ``target`` fraction of events must be good.

    ``sample()`` returns cumulative ``(bad, total)`` counts since process
    start; the monitor differentiates them over its windows. ``target``
    is the good-ratio promise (0.95 = "95% good"); the error budget is
    ``1 - target``.
    """

    name: str
    sample: Callable[[], Tuple[float, float]]
    target: float = 0.95
    description: str = ""

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"SLO {self.name!r}: target must be in (0, 1) — a target "
                f"of 1.0 has zero error budget and every bad event would "
                f"be an infinite burn rate (got {self.target})")

    @property
    def budget(self) -> float:
        return 1.0 - self.target


def hist_count_le(h: Histogram, threshold: float) -> float:
    """Samples at or below ``threshold`` from a fixed-bucket histogram.
    Exact when ``threshold`` equals a bucket bound; otherwise the count
    through the last bound <= threshold (conservative: the straddling
    bucket counts as bad)."""
    good = 0
    for bound, n in zip(h.bounds, h.bucket_counts):
        if bound > threshold:
            break
        good += n
    return float(good)


def latency_objective(name: str, hist_fn: Callable[[], Histogram],
                      threshold_ms: float, target: float = 0.95,
                      description: str = "") -> SLObjective:
    """"``target`` of latencies under ``threshold_ms``" over a live
    histogram (e.g. the serving sink's ``ttft_ms``). Pick a threshold on
    a bucket bound of the histogram for exact accounting."""

    def sample() -> Tuple[float, float]:
        h = hist_fn()
        total = float(h.count)
        return total - hist_count_le(h, threshold_ms), total

    return SLObjective(name, sample, target=target,
                       description=description
                       or f"p{target * 100:g} {name} < {threshold_ms:g} ms")


def ratio_objective(name: str, bad_fn: Callable[[], float],
                    total_fn: Callable[[], float], target: float = 0.99,
                    description: str = "") -> SLObjective:
    """"At most ``1 - target`` of events bad" over two cumulative
    counters (e.g. shed+failed over submitted)."""
    return SLObjective(
        name, lambda: (float(bad_fn()), float(total_fn())), target=target,
        description=description or f"bad ratio of {name} < {1 - target:g}")


class _ObjectiveState:
    """Rolling (t, bad, total) samples + breach latch for one objective."""

    __slots__ = ("objective", "samples", "breached", "fast_burn",
                 "slow_burn", "budget_remaining", "breach_count",
                 "fast_events")

    def __init__(self, objective: SLObjective):
        self.objective = objective
        self.samples: Deque[Tuple[float, float, float]] = deque()
        self.breached = False
        self.fast_burn = 0.0
        self.slow_burn = 0.0
        self.budget_remaining = 1.0
        self.breach_count = 0
        self.fast_events = 0.0

    def to_dict(self) -> Dict[str, object]:
        return {
            "slo": self.objective.name,
            "description": self.objective.description,
            "target": self.objective.target,
            "breached": self.breached,
            "breach_count": self.breach_count,
            "fast_burn": round(self.fast_burn, 4),
            "slow_burn": round(self.slow_burn, 4),
            "budget_remaining": round(self.budget_remaining, 4),
        }


class SLOMonitor:
    """Evaluates a set of objectives each ``tick()`` (see module
    docstring). Drive it from the serving/training step loop; health is
    derived state, never a side channel:

    * ``breached`` — some objective's fast AND slow burns exceed the
      threshold (latched until the fast window recovers);
    * ``degraded`` — some fast window is burning but the slow window has
      not confirmed yet (early warning, no page);
    * ``ok`` — otherwise.

    ``min_events`` is the traffic floor: an objective cannot breach (or
    report degraded) until its fast window holds at least that many
    events, so a handful of cold-start compile latencies or one stray
    error in near-zero traffic never pages.
    """

    def __init__(self, objectives: List[SLObjective],
                 clock: Optional[Callable[[], float]] = None,
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0,
                 burn_threshold: float = 10.0,
                 min_events: int = 5,
                 eval_interval_s: Optional[float] = None,
                 on_breach: Optional[Callable[[str, dict], None]] = None,
                 on_recover: Optional[Callable[[str, dict], None]] = None):
        if fast_window_s >= slow_window_s:
            raise ValueError("fast_window_s must be < slow_window_s")
        self.objectives = list(objectives)
        self._clock = clock if clock is not None else time.monotonic
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        # evaluation granularity: burn windows span minutes, so judging
        # them more than ~120x per fast window adds nothing — a tick
        # arriving earlier than this after the last evaluation returns
        # after ONE clock read + compare. This is what keeps a kHz step
        # loop's per-step cost flat (bench_obs_overhead.py) and bounds
        # sample retention to ~120 per fast window.
        self._min_gap = (self.fast_window_s / 120.0
                         if eval_interval_s is None
                         else float(eval_interval_s))
        self._last_eval: Optional[float] = None
        self.burn_threshold = float(burn_threshold)
        self.min_events = int(min_events)
        self.on_breach = on_breach
        self.on_recover = on_recover
        self._states: Dict[str, _ObjectiveState] = {
            o.name: _ObjectiveState(o) for o in self.objectives}
        if len(self._states) != len(self.objectives):
            raise ValueError("duplicate SLO names")
        # O(1) mirror of "any objective breached": the serving loop asks
        # every step (level-triggered queue trimming), so the answer must
        # not cost a pass over the states dict per step
        self._breached_count = 0
        # seed a baseline sample per objective at construction, so events
        # between now and the first tick are counted (window deltas are
        # sample-to-sample; without a baseline the first tick's state
        # would silently become the zero point)
        t0 = self._clock()
        for st in self._states.values():
            try:
                bad, total = st.objective.sample()
                st.samples.append((t0, float(bad), float(total)))
            except Exception:
                pass
        reg = get_registry()
        self._g_burn = reg.gauge(
            "paddle_slo_burn_rate",
            "error-budget burn rate per objective and window",
            labels=("slo", "window"))
        self._g_budget = reg.gauge(
            "paddle_slo_budget_remaining",
            "fraction of the slow-window error budget left (1 = untouched)",
            labels=("slo",))
        self._g_breached = reg.gauge(
            "paddle_slo_breached",
            "1 while the objective is in breach", labels=("slo",))
        self._c_breaches = reg.counter(
            "paddle_slo_breaches_total",
            "breach transitions per objective", labels=("slo",))

    # -- evaluation ---------------------------------------------------------

    def tick(self) -> None:
        """Sample every objective at the injected clock's now and update
        burn rates, gauges, breach latches and callbacks. Called once per
        scheduler/training step; evaluations are decimated to the
        ``eval_interval_s`` granularity (ticks in between are one clock
        read + compare — the per-step overhead budgeted by
        ``benchmarks/bench_obs_overhead.py``), and the window math is ONE
        bounded reversed pass over the retained samples per objective."""
        now = self._clock()
        if self._last_eval is not None \
                and now - self._last_eval < self._min_gap:
            return
        self._last_eval = now
        fast_cut = now - self.fast_window_s
        slow_cut = now - self.slow_window_s
        for st in self._states.values():
            obj = st.objective
            try:
                bad, total = obj.sample()
            except Exception:       # a torn sampler must not kill the loop
                continue
            newest = (now, float(bad), float(total))
            st.samples.append(newest)   # appends are >= _min_gap apart
            # by the decimation above, so retention is bounded
            # keep one sample older than the slow window as its baseline
            while len(st.samples) > 2 and st.samples[1][0] < slow_cut:
                st.samples.popleft()
            # window baselines: the slow one is samples[1] by the pruning
            # invariant (O(1)); the fast one is a bounded backward scan
            # (<= ~120 coalesced samples per fast window)
            if st.samples[0][0] >= slow_cut:     # run shorter than window
                slow_old = st.samples[0]
            elif len(st.samples) > 1:
                slow_old = st.samples[1]
            else:
                slow_old = newest
            fast_old = newest
            for s in reversed(st.samples):
                if s[0] < fast_cut:
                    break
                fast_old = s
            budget = obj.budget
            d_total = newest[2] - fast_old[2]
            st.fast_events = d_total
            st.fast_burn = ((newest[1] - fast_old[1]) / d_total / budget
                            if d_total > 0 else 0.0)
            d_total = newest[2] - slow_old[2]
            if d_total > 0:
                st.slow_burn = (newest[1] - slow_old[1]) / d_total / budget
                st.budget_remaining = max(0.0, min(1.0, 1.0 - (
                    (newest[1] - slow_old[1]) / (d_total * budget))))
            else:
                st.slow_burn = 0.0
                st.budget_remaining = 1.0
            self._g_burn.set(st.fast_burn, slo=obj.name, window="fast")
            self._g_burn.set(st.slow_burn, slo=obj.name, window="slow")
            self._g_budget.set(st.budget_remaining, slo=obj.name)
            if flight_armed[0]:
                flight_recorder.note_metrics(obj.name, {
                    "t": now, "fast_burn": st.fast_burn,
                    "slow_burn": st.slow_burn, "bad": bad, "total": total})
            self._transition(st)
        # resync the O(1) mirror from the states (covers tests/tools
        # that latch st.breached directly, bypassing _transition); this
        # runs once per EVALUATED tick, so the per-step cost of
        # breached() stays one integer compare
        self._breached_count = sum(
            1 for st in self._states.values() if st.breached)

    def _transition(self, st: _ObjectiveState) -> None:
        thr = self.burn_threshold
        obj = st.objective
        if not st.breached and st.fast_events < self.min_events:
            # traffic floor: a couple of cold-start or stray events must
            # not page (standard low-traffic burn-rate suppression)
            return
        if not st.breached and st.fast_burn > thr and st.slow_burn > thr:
            st.breached = True
            self._breached_count += 1
            st.breach_count += 1
            self._g_breached.set(1.0, slo=obj.name)
            self._c_breaches.inc(slo=obj.name)
            emit_event("slo_breach", slo=obj.name,
                       fast_burn=round(st.fast_burn, 3),
                       slow_burn=round(st.slow_burn, 3),
                       budget_remaining=round(st.budget_remaining, 4),
                       target=obj.target)
            if self.on_breach is not None:
                self.on_breach(obj.name, st.to_dict())
        elif st.breached and st.fast_burn <= thr:
            st.breached = False
            self._breached_count -= 1
            self._g_breached.set(0.0, slo=obj.name)
            emit_event("slo_recovered", slo=obj.name,
                       fast_burn=round(st.fast_burn, 3),
                       slow_burn=round(st.slow_burn, 3))
            if self.on_recover is not None:
                self.on_recover(obj.name, st.to_dict())

    # -- derived state ------------------------------------------------------

    def health(self) -> str:
        """``breached`` | ``degraded`` | ``ok`` (see class docstring)."""
        states = self._states.values()
        if self._breached_count > 0:
            return "breached"
        if any(st.fast_burn > self.burn_threshold
               and st.fast_events >= self.min_events for st in states):
            return "degraded"
        return "ok"

    def breached(self, name: Optional[str] = None) -> bool:
        if name is not None:
            return self._states[name].breached
        return self._breached_count > 0      # O(1): per-step hot path

    def states(self) -> List[Dict[str, object]]:
        """JSON-able per-objective state (statusz / debug bundles)."""
        return [st.to_dict() for st in self._states.values()]
