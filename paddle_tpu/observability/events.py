"""Structured JSON-lines event log with size-capped rotation.

One sink shared by serving and resilience (and the recompile detector):
shed / retry / rollback / preempt / recompile events land here as one
JSON object per line, so an operator can ``jq`` a production incident
without correlating three ad-hoc log formats.

Disabled (no-op, one ``is None`` check per emit) until
:func:`configure_event_log` points it at a path. Rotation keeps
``backups`` closed generations (``events.jsonl.1`` newest … ``.N``
oldest) and never lets the live file exceed ``max_bytes``.

``emit`` is called from scheduler/trainer hot paths, so it is
exception-safe by contract: an I/O failure (full disk, a path turned
into a directory, a racing rotation) increments
``paddle_events_dropped_total`` and drops the event instead of
propagating into the step loop. While the flight recorder is armed,
every record also lands in its ring — even when the file sink is
disabled.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from .flight import flight_armed, flight_recorder
from .trace import current_trace

_dropped_counter = None       # lazy: created on first drop, then cached


def _count_dropped() -> None:
    global _dropped_counter
    try:
        if _dropped_counter is None:
            from .registry import get_registry
            _dropped_counter = get_registry().counter(
                "paddle_events_dropped_total",
                "events lost to event-log I/O failures")
        _dropped_counter.inc()
    except Exception:         # even the accounting must never propagate
        pass


class EventLog:
    def __init__(self, path: Optional[str] = None,
                 max_bytes: int = 1 << 20, backups: int = 2):
        self._lock = threading.Lock()
        self._path: Optional[str] = None
        self._max_bytes = max_bytes
        self._backups = backups
        self._size = 0
        if path is not None:
            self.configure(path, max_bytes=max_bytes, backups=backups)

    @property
    def enabled(self) -> bool:
        return self._path is not None

    @property
    def path(self) -> Optional[str]:
        return self._path

    def configure(self, path: Optional[str], max_bytes: int = 1 << 20,
                  backups: int = 2) -> "EventLog":
        """Point the sink at ``path`` (None disables it again)."""
        with self._lock:
            self._path = path
            self._max_bytes = max_bytes
            self._backups = backups
            if path is not None:
                d = os.path.dirname(path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._size = (os.path.getsize(path)
                              if os.path.exists(path) else 0)
        return self

    def emit(self, kind: str, **fields) -> None:
        """Append one event (see module docstring: exception-safe, taps
        the armed flight recorder). The current trace context's ids are
        attached automatically (explicit kwargs win)."""
        if self._path is None and not flight_armed[0]:
            return
        ctx = current_trace()
        record = {"ts": round(time.time(), 6), "kind": kind}
        if ctx is not None:
            record["trace_id"] = ctx.trace_id
            if ctx.request_id is not None:
                record.setdefault("request_id", ctx.request_id)
            if ctx.step is not None:
                record.setdefault("step", ctx.step)
        record.update(fields)
        if flight_armed[0]:
            flight_recorder.note_event(record)
        if self._path is None:
            return
        try:
            line = json.dumps(record, default=str,
                              separators=(",", ":")) + "\n"
            data = line.encode()
            with self._lock:
                if self._path is None:
                    return
                if self._size and self._size + len(data) > self._max_bytes:
                    self._rotate_locked()
                with open(self._path, "ab") as f:
                    f.write(data)
                self._size += len(data)
        except Exception:
            # full disk / rotation race / unserialisable field: the hot
            # path (scheduler, trainer) must never see event-log errors
            _count_dropped()

    def _rotate_locked(self) -> None:
        """path -> path.1 -> … -> path.backups (oldest dropped). Caller
        holds ``self._lock`` (the ``_locked`` suffix is the repo-wide
        lock-discipline convention, see tpu-lint lock-unguarded-write)."""
        if self._backups <= 0:
            try:
                os.remove(self._path)
            except OSError:
                pass
        else:
            oldest = f"{self._path}.{self._backups}"
            if os.path.exists(oldest):
                os.remove(oldest)
            for i in range(self._backups - 1, 0, -1):
                src = f"{self._path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self._path}.{i + 1}")
            if os.path.exists(self._path):
                os.replace(self._path, f"{self._path}.1")
        self._size = 0


#: the process-global sink serving/resilience/runtime emit into
event_log = EventLog()


def configure_event_log(path: Optional[str], max_bytes: int = 1 << 20,
                        backups: int = 2) -> EventLog:
    return event_log.configure(path, max_bytes=max_bytes, backups=backups)


def emit_event(kind: str, **fields) -> None:
    event_log.emit(kind, **fields)
