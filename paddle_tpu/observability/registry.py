"""Process-global metrics registry — ONE scrape surface for the tree.

Reference shape: prometheus_client's CollectorRegistry, trimmed to what the
runtime needs. Two ways in:

* **owned metrics** — :meth:`MetricsRegistry.counter` / :meth:`gauge` /
  :meth:`histogram` create (or return the already-registered) named metric,
  optionally labeled. Name uniqueness is enforced: re-asking for the same
  name with the same type/labels returns the SAME object (so call sites
  don't need import-order coordination); a conflicting re-registration
  raises.
* **sinks** — subsystems that keep their own storage (``ServingMetrics``,
  ``ResilienceMetrics``) register a namespace + exposition/snapshot
  callbacks. Re-registering a namespace REPLACES the previous sink (a
  fresh ``ServingMetrics()`` per server/test is the normal lifecycle; the
  registry always scrapes the newest).

``prometheus_text()`` is a single valid exposition document (owned
families then sinks); ``snapshot()`` is the JSON-able equivalent.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..core.histogram import (DEFAULT_BOUNDS_MS, DEFAULT_QUANTILES,
                              Histogram)
from . import format as fmt


class _Labeled:
    """Shared labeled-series storage: label-value tuple -> slot."""

    def __init__(self, name: str, help: str, label_names: Sequence[str]):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._series: Dict[Tuple, object] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, object]) -> Tuple:
        if tuple(sorted(labels)) != tuple(sorted(self.label_names)):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(sorted(labels))}")
        return tuple(labels[k] for k in self.label_names)

    def _iter_series(self):
        with self._lock:
            items = list(self._series.items())
        for key, slot in items:
            yield dict(zip(self.label_names, key)), slot


class Counter(_Labeled):
    """Monotonic counter, optionally labeled."""

    def inc(self, by: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + by

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(self._key(labels), 0.0)

    @property
    def total(self) -> float:
        with self._lock:
            return sum(self._series.values())

    def lines(self) -> List[str]:
        if self.label_names:
            series = sorted(self._iter_series(),
                            key=lambda kv: tuple(kv[0].items()))
            return fmt.counter_lines(self.name, series=series,
                                     help=self.help or None)
        return fmt.counter_lines(self.name, value=self.value(),
                                 help=self.help or None)

    def snapshot(self):
        if not self.label_names:
            return self.value()
        return {",".join(f"{k}={v}" for k, v in labels.items()): v2
                for labels, v2 in self._iter_series()}


class Gauge(_Labeled):
    """Last-value gauge, optionally labeled."""

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, by: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + by

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(self._key(labels), 0.0)

    def lines(self) -> List[str]:
        if self.label_names:
            series = sorted(self._iter_series(),
                            key=lambda kv: tuple(kv[0].items()))
            return fmt.gauge_lines(self.name, series=series,
                                   help=self.help or None)
        return fmt.gauge_lines(self.name, value=self.value(),
                               help=self.help or None)

    def snapshot(self):
        if not self.label_names:
            return self.value()
        return {",".join(f"{k}={v}" for k, v in labels.items()): v2
                for labels, v2 in self._iter_series()}


class HistogramMetric(_Labeled):
    """Registry-owned histogram (one ``core.histogram.Histogram`` per
    label combination)."""

    def __init__(self, name, help, label_names=(),
                 bounds: Sequence[float] = DEFAULT_BOUNDS_MS,
                 quantiles: Optional[Sequence[float]] = DEFAULT_QUANTILES):
        super().__init__(name, help, label_names)
        self.bounds = tuple(bounds)
        self.quantiles = tuple(quantiles) if quantiles else None

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:   # record under the lock: lines() formats (and
            h = self._series.get(key)      # sorts percentiles) concurrently
            if h is None:
                h = self._series[key] = Histogram(bounds=self.bounds)
            h.record(value)

    def hist(self, **labels) -> Histogram:
        key = self._key(labels)
        with self._lock:
            h = self._series.get(key)
            if h is None:
                h = self._series[key] = Histogram(bounds=self.bounds)
            return h

    def lines(self) -> List[str]:
        out: List[str] = []
        with self._lock:   # freeze records while formatting (percentile
            if not self.label_names:       # sorts would race otherwise)
                h = self._series.get(())
                if h is None:
                    h = self._series[()] = Histogram(bounds=self.bounds)
                out.extend(fmt.histogram_lines(
                    self.name, h, help=self.help or None,
                    quantiles=self.quantiles))
                return out
            series = sorted(self._series.items())
            if not series:   # no label-set yet: still emit an empty family
                out.extend(fmt.histogram_lines(
                    self.name, Histogram(bounds=self.bounds),
                    help=self.help or None, quantiles=self.quantiles))
                return out
            for i, (key, h) in enumerate(series):
                # one HELP/TYPE per FAMILY, then every label-set's samples
                # (quantile siblings omitted for labeled histograms: they
                # would need their own once-per-family TYPE handling)
                out.extend(fmt.histogram_lines(
                    self.name, h,
                    help=(self.help or None) if i == 0 else None,
                    quantiles=None,
                    labels=dict(zip(self.label_names, key)),
                    include_type=i == 0))
            return out

    def snapshot(self):
        with self._lock:
            if not self.label_names:
                h = self._series.get(())
                h = h if h is not None else Histogram(bounds=self.bounds)
                return h.summary(self.quantiles or ())
            return {",".join(f"{k}={v}" for k, v in
                             zip(self.label_names, key)):
                    h.summary(self.quantiles or ())
                    for key, h in sorted(self._series.items())}


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": HistogramMetric}


class MetricsRegistry:
    """See module docstring. Thread-safe; one process-global instance via
    :func:`get_registry`, independent instances constructible for tests."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}          # name -> metric
        self._kinds: Dict[str, str] = {}               # name -> type
        self._sinks: Dict[str, Tuple[Callable, Optional[Callable]]] = {}

    # -- owned metrics ------------------------------------------------------

    def _get_or_make(self, kind: str, name: str, help: str,
                     labels: Sequence[str], **kw):
        if isinstance(labels, str):
            # labels="op" silently iterates into ('o', 'p'); catch the
            # footgun before it registers an unusable family
            raise TypeError(
                f"metric {name!r}: labels must be a SEQUENCE of label "
                f"names, got the bare string {labels!r} — use "
                f"labels=({labels!r},)")
        labels = tuple(labels)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if self._kinds[name] != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as a "
                        f"{self._kinds[name]}; cannot re-register as a "
                        f"{kind}")
                if existing.label_names != labels:
                    # returning the existing family here would make later
                    # inc(**labels) calls key inconsistently between the
                    # two call sites — fail loudly at registration instead
                    raise ValueError(
                        f"metric {name!r} already registered with label "
                        f"names {existing.label_names}; cannot "
                        f"re-register with label names {labels} — every "
                        f"call site of one family must declare the same "
                        f"labels (order included)")
                if kind == "histogram":
                    bounds = tuple(kw.get("bounds", existing.bounds))
                    if bounds != existing.bounds:
                        raise ValueError(
                            f"histogram {name!r} already registered with "
                            f"bounds {existing.bounds}; cannot "
                            f"re-register with bounds {bounds}")
                    q = kw.get("quantiles", existing.quantiles)
                    q = tuple(q) if q else None
                    if q != existing.quantiles:
                        raise ValueError(
                            f"histogram {name!r} already registered with "
                            f"quantiles {existing.quantiles}; cannot "
                            f"re-register with quantiles {q}")
                return existing
            metric = _TYPES[kind](name, help, labels, **kw)
            self._metrics[name] = metric
            self._kinds[name] = kind
            return metric

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_make("counter", name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_make("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  bounds: Sequence[float] = DEFAULT_BOUNDS_MS,
                  quantiles: Optional[Sequence[float]] = DEFAULT_QUANTILES
                  ) -> HistogramMetric:
        return self._get_or_make("histogram", name, help, labels,
                                 bounds=bounds, quantiles=quantiles)

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    # -- sinks --------------------------------------------------------------

    def register_sink(self, namespace: str,
                      text_fn: Callable[[], List[str]],
                      snapshot_fn: Optional[Callable[[], dict]] = None,
                      replace: bool = True) -> None:
        """Register a subsystem sink. ``text_fn`` returns exposition LINES
        (no trailing newline) built via :mod:`.format`; ``snapshot_fn``
        returns a JSON-able dict. A namespace re-registration replaces the
        previous sink unless ``replace=False`` (then it raises)."""
        with self._lock:
            if namespace in self._sinks and not replace:
                raise ValueError(f"sink {namespace!r} already registered")
            self._sinks[namespace] = (text_fn, snapshot_fn)

    def unregister_sink(self, namespace: str) -> None:
        with self._lock:
            self._sinks.pop(namespace, None)

    # -- export -------------------------------------------------------------

    def prometheus_text(self) -> str:
        """One valid exposition document covering owned metrics + sinks."""
        with self._lock:
            metrics = list(self._metrics.values())
            sinks = list(self._sinks.items())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.lines())
        for _, (text_fn, _snap) in sinks:
            try:
                lines.extend(text_fn())
            except Exception:       # a torn sink must not kill the scrape
                continue
        return "\n".join(lines) + "\n" if lines else ""

    def snapshot(self) -> dict:
        with self._lock:
            metrics = list(self._metrics.items())
            sinks = list(self._sinks.items())
        out: dict = {}
        for name, m in metrics:
            out[name] = m.snapshot()
        for ns, (_text, snap) in sinks:
            if snap is None:
                continue
            try:
                out[ns] = snap()
            except Exception:
                continue
        return out

    def reset(self) -> None:
        """Drop every owned metric and sink (tests)."""
        with self._lock:
            self._metrics.clear()
            self._kinds.clear()
            self._sinks.clear()


#: the process-global registry every subsystem re-registers into
_global = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _global
