"""Bounded metric time series: the memory the instantaneous stack lacks.

The registry, SLO monitor and ``/statusz`` answer "what is true now";
the autoscaler policy (ROADMAP item 4) and the fusion-pass regression
gate (item 1) need "what has been true lately and which way is it
moving". :class:`MetricHistory` is that memory: a per-family ring-buffer
sampler over the existing :class:`~.registry.MetricsRegistry` (and any
sink-owned ``core.histogram.Histogram``), with windowed derivations —
counters materialise as **rates**, gauges as **levels + slopes**,
histograms as **windowed quantile estimates** (bucket-count deltas
between the window's endpoints, interpolated) — so cumulative families
become the trend signals a controller can act on.

Discipline (same contracts as the rest of the telemetry layer):

* **injected step-driven clocks only** — the constructor takes a
  ``clock`` and never reads the wall clock itself (tpu-lint
  ``layer-wall-clock``, the ``slo.py``/``goodput.py`` rule, covers this
  module too), so history windows are byte-deterministic in fake-clock
  tests and chaos replays;
* **one lock round per sample** — ``sample()`` reads every tracked
  reader and appends every ring inside a single ``with self._lock``;
* **zero-cost disarmed gate** — hot paths check the module-cell
  ``history_armed`` (one list index, no allocation) exactly like
  ``flight.flight_armed`` / ``runtime.dispatch_armed``; armed overhead
  rides under ``benchmarks/bench_obs_overhead.py``'s 3% budget;
* **decimation** — ``sample()`` returns after one clock compare when
  called again within ``min_interval_s``, so a kHz step loop costs a
  comparison, not a scrape.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

#: the one cell step loops check before ticking a sampler/bus (mutable
#: list so callers read a stable module attribute, not a rebindable name)
history_armed = [False]

#: ``history.json`` / snapshot schema version (bump on breaking changes)
HISTORY_SCHEMA_VERSION = 1


class _Series:
    __slots__ = ("name", "kind", "reader", "ring", "errors")

    def __init__(self, name: str, kind: str,
                 reader: Optional[Callable[[], Any]], capacity: int):
        self.name = name
        self.kind = kind                    # counter | gauge | histogram
        self.reader = reader                # None: push-only (note())
        self.ring: Deque[tuple] = deque(maxlen=capacity)
        self.errors = 0


def _hist_state(h) -> Tuple[float, float, Tuple[int, ...],
                            Tuple[float, ...]]:
    """(count, sum, bucket_counts, bounds) of a ``core.histogram.
    Histogram`` — the cumulative state windowed quantiles difference."""
    return (float(h.count), float(h.sum), tuple(h.bucket_counts),
            tuple(h.bounds))


class MetricHistory:
    """See module docstring. ``track_*`` registers readers; ``sample()``
    is the one hot-path entry; everything else is the cold read side."""

    def __init__(self, clock: Optional[Callable[[], float]] = None,
                 capacity: int = 512, min_interval_s: float = 1.0):
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._capacity = int(capacity)
        self._min_interval = float(min_interval_s)
        self._series: Dict[str, _Series] = {}
        self._last_sample: Optional[float] = None
        self.samples = 0

    # -- lifecycle ----------------------------------------------------------

    @property
    def armed(self) -> bool:
        return history_armed[0]

    def arm(self) -> "MetricHistory":
        history_armed[0] = True
        return self

    def disarm(self) -> None:
        history_armed[0] = False

    # -- registration -------------------------------------------------------

    def _track(self, name: str, kind: str,
               reader: Optional[Callable[[], Any]]) -> None:
        with self._lock:
            if name not in self._series:
                self._series[name] = _Series(name, kind, reader,
                                             self._capacity)

    def track_counter(self, name: str, reader: Callable[[], float]
                      ) -> None:
        """Track a CUMULATIVE count (``reader`` returns the running
        total — e.g. ``lambda: counter.total``); windows read as rates
        via :meth:`rate`."""
        self._track(name, "counter", reader)

    def track_gauge(self, name: str, reader: Callable[[], float]) -> None:
        """Track a level (``reader`` returns the current value); windows
        read as :meth:`latest` / :meth:`mean` / :meth:`slope`."""
        self._track(name, "gauge", reader)

    def track_histogram(self, name: str, hist_fn: Callable[[], Any]
                        ) -> None:
        """Track a live ``core.histogram.Histogram`` (e.g. a
        ``ServingMetrics`` family); windows read as
        :meth:`window_quantile` / :meth:`window_mean`."""
        self._track(name, "histogram", hist_fn)

    def note(self, name: str, value: float,
             now: Optional[float] = None) -> None:
        """Push one gauge-kind point directly (the SignalBus feeds its
        smoothed signals this way — no reader round-trip)."""
        t = self._clock() if now is None else now
        with self._lock:
            s = self._series.get(name)
            if s is None:
                s = self._series[name] = _Series(name, "gauge", None,
                                                 self._capacity)
            s.ring.append((t, float(value)))

    # -- sampling (the hot-path entry; callers gate on history_armed) --------

    def sample(self, now: Optional[float] = None) -> bool:
        """Read every tracked reader and append each ring — ONE lock
        round. Decimated: a call within ``min_interval_s`` of the last
        sample returns after one clock read + compare. Returns whether a
        sample was taken."""
        t = self._clock() if now is None else now
        if self._last_sample is not None \
                and t - self._last_sample < self._min_interval:
            return False
        with self._lock:
            if self._last_sample is not None \
                    and t - self._last_sample < self._min_interval:
                return False
            self._last_sample = t
            self.samples += 1
            for s in self._series.values():
                if s.reader is None:
                    continue
                try:
                    if s.kind == "histogram":
                        s.ring.append((t,) + _hist_state(s.reader()))
                    else:
                        s.ring.append((t, float(s.reader())))
                except Exception:   # a torn reader must not kill the loop
                    s.errors += 1
        return True

    # -- window access ------------------------------------------------------

    def _window_locked(self, name: str, window_s: Optional[float]
                       ) -> List[tuple]:
        s = self._series.get(name)
        if s is None or not s.ring:
            return []
        if window_s is None:
            return list(s.ring)
        cut = s.ring[-1][0] - float(window_s)
        return [p for p in s.ring if p[0] >= cut]

    def series(self, name: str, window_s: Optional[float] = None
               ) -> List[tuple]:
        """Raw retained points for ``name`` (newest-last), optionally
        restricted to the trailing window."""
        with self._lock:
            return self._window_locked(name, window_s)

    def latest(self, name: str) -> Optional[float]:
        with self._lock:
            s = self._series.get(name)
            if s is None or not s.ring:
                return None
            return s.ring[-1][1]

    def delta(self, name: str, window_s: Optional[float] = None) -> float:
        """newest - oldest value over the window (counters: events seen)."""
        with self._lock:
            pts = self._window_locked(name, window_s)
        if len(pts) < 2:
            return 0.0
        return pts[-1][1] - pts[0][1]

    def rate(self, name: str, window_s: Optional[float] = None) -> float:
        """Windowed events/second for a cumulative counter series."""
        with self._lock:
            pts = self._window_locked(name, window_s)
        if len(pts) < 2:
            return 0.0
        dt = pts[-1][0] - pts[0][0]
        return (pts[-1][1] - pts[0][1]) / dt if dt > 0 else 0.0

    def mean(self, name: str, window_s: Optional[float] = None) -> float:
        with self._lock:
            pts = self._window_locked(name, window_s)
        if not pts:
            return 0.0
        return sum(p[1] for p in pts) / len(pts)

    def slope(self, name: str, window_s: Optional[float] = None) -> float:
        """Least-squares slope (units/second) of a gauge series over the
        window — the queue-depth/burn-rate TREND the autoscaler keys on."""
        with self._lock:
            pts = self._window_locked(name, window_s)
        n = len(pts)
        if n < 2:
            return 0.0
        t0 = pts[0][0]
        mt = sum(p[0] - t0 for p in pts) / n
        mv = sum(p[1] for p in pts) / n
        num = sum((p[0] - t0 - mt) * (p[1] - mv) for p in pts)
        den = sum((p[0] - t0 - mt) ** 2 for p in pts)
        return num / den if den > 0 else 0.0

    def window_quantile(self, name: str, q: float,
                        window_s: Optional[float] = None) -> float:
        """Quantile estimate of the observations RECORDED INSIDE the
        window, from the bucket-count delta between the window's
        endpoint samples (linear interpolation within the straddling
        bucket; the +inf bucket clamps to the last finite bound). This
        is what "p95 TTFT over the last 5 minutes" means against a
        cumulative histogram."""
        with self._lock:
            pts = self._window_locked(name, window_s)
        if len(pts) < 2:
            return 0.0
        _, c0, _, b0, bounds = pts[0]
        _, c1, _, b1, _ = pts[-1]
        if c1 <= c0 or len(b0) != len(b1):
            return 0.0
        dcounts = [n1 - n0 for n0, n1 in zip(b0, b1)]
        total = sum(dcounts)
        if total <= 0:
            return 0.0
        target = max(0.0, min(1.0, q)) * total
        seen = 0.0
        lo = 0.0
        for i, n in enumerate(dcounts):
            hi = bounds[i] if i < len(bounds) else bounds[-1]
            if n > 0 and seen + n >= target:
                if i >= len(bounds):        # +inf bucket: clamp
                    return float(bounds[-1])
                frac = (target - seen) / n
                return float(lo + (hi - lo) * frac)
            seen += n
            lo = hi
        return float(bounds[-1])

    def window_mean(self, name: str,
                    window_s: Optional[float] = None) -> float:
        """Mean of the observations recorded inside the window (sum/count
        deltas of a histogram series)."""
        with self._lock:
            pts = self._window_locked(name, window_s)
        if len(pts) < 2:
            return 0.0
        dc = pts[-1][1] - pts[0][1]
        ds = pts[-1][2] - pts[0][2]
        return ds / dc if dc > 0 else 0.0

    # -- export -------------------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def snapshot_status(self) -> Dict[str, Any]:
        with self._lock:
            return {"armed": history_armed[0], "samples": self.samples,
                    "capacity": self._capacity,
                    "min_interval_s": self._min_interval,
                    "series": {s.name: len(s.ring)
                               for s in self._series.values()}}

    def snapshot(self, window_s: Optional[float] = None
                 ) -> Dict[str, Any]:
        """The ``history.json`` series block: every retained point per
        series (bounded by construction — ring capacity × family count),
        histograms reduced to (t, count, sum) triples."""
        with self._lock:
            out: Dict[str, Any] = {}
            for name in sorted(self._series):
                s = self._series[name]
                pts = self._window_locked(name, window_s)
                if s.kind == "histogram":
                    points = [[round(p[0], 6), p[1], round(p[2], 6)]
                              for p in pts]
                else:
                    points = [[round(p[0], 6), round(p[1], 6)]
                              for p in pts]
                out[name] = {"kind": s.kind, "errors": s.errors,
                             "points": points}
            return out
