"""Fleet-wide observability federation: per-host telemetry mirrors,
clock-offset estimation and merged cross-host surfaces.

PR 17 made serving multi-process (``serving/multihost.py``), but every
observability plane built before it — :class:`~.registry.MetricsRegistry`,
:class:`~.timeline.SpanCollector`, :class:`~.signals.SignalBus`, the
event log, flight bundles — is process-local: the parent could only peek
at a child through a lossy ``statusz`` RPC, a cross-host request had no
single trace tree, and a dead host took its telemetry to the grave. This
module is the parent half of the federation:

* each heartbeat the host ships a **versioned telemetry frame**
  (:func:`collect_telemetry`, marshalled by ``serving.wire``): its
  registry exposition text, serving gauges, SignalBus values + trends,
  the span collector's *new* completed spans since the last frame
  (per-trace watermarks — :meth:`.timeline.SpanCollector.export_new`),
  the flight ring's event tail and the memory ledger's class bytes;
* the parent keeps a :class:`HostTelemetryMirror` per host inside a
  :class:`FederationHub`, with **clock-offset estimation** from RPC
  request/reply midpoints (:class:`ClockSync`): ``offset = t_remote -
  (t_send + t_recv) / 2``, EWMA-smoothed, with ``rtt / 2`` as the error
  bound — the remote clock is *corrected, never trusted*. Remote span
  timestamps are skew-corrected into the parent's clock domain and
  injected into the parent's span collector, so spans from different
  hosts merge into ONE trace tree at ``/tracez`` and the PR 10
  exclusive-sweep attribution grows ``migration`` / ``dcn_transfer``
  segments that tile the root envelope exactly;
* federated surfaces: :meth:`FederationHub.federated_metrics_text`
  merges every mirror's exposition doc with the parent's into one
  validator-clean document under a ``host`` label
  (:func:`merge_expositions`); :meth:`attach_fleet_signals` registers
  per-host + fleet-aggregate EWMA signals (queue depth, pool pressure,
  burn rate, ``host_rtt_p90``) on a :class:`~.signals.SignalBus` for
  ``/varz`` — the ROADMAP-2 autoscaler input; and
  :meth:`FederationHub.snapshot` is the ``host_telemetry.json`` member
  flight bundles embed, so a ``host_lost`` postmortem shows the dead
  host's last-known telemetry, not just the moment of death.

Hot-path contract: the heartbeat path checks the module-level
``federation_armed`` cell (one list index disarmed) — the same
zero-overhead discipline as the flight recorder / timeline planes,
guarded by ``benchmarks/bench_obs_overhead.py``.

Layering: this module never imports ``serving`` — frame *marshalling*
(versioning, wire rejection) lives in ``serving/wire.py``; this module
only builds and consumes plain frame dicts.
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .format import HELP_PREFIX, TYPE_PREFIX, help_line, type_line
from .registry import get_registry
from .timeline import span_collector, timeline_armed

#: the one cell heartbeat call sites check before doing federation work
#: (mutable list so callers read a stable module attribute)
federation_armed = [False]

#: telemetry frame fields every well-formed frame must carry
FRAME_REQUIRED_KEYS = ("host_id", "pid", "seq", "t_ns")


def _utcnow_label() -> float:
    """Monotonic seconds for mirror freshness bookkeeping (overridable
    per-hub via the injected clock)."""
    return time.monotonic()


# ---------------------------------------------------------------------------
# clock-offset estimation
# ---------------------------------------------------------------------------

class ClockSync:
    """Peer clock-offset estimator over RPC round-trips.

    For one request/reply pair, ``t_send``/``t_recv`` are the local
    clock at send and receive and ``t_remote`` is the peer's clock when
    it built the reply. Assuming the reply was stamped near the midpoint
    of the round-trip, the offset sample is ``t_remote - (t_send +
    t_recv) / 2`` and its worst-case error is ``rtt / 2`` (the stamp
    could sit anywhere in the window). Both are EWMA-smoothed; a bounded
    deque of raw RTTs feeds quantile reads (``host_rtt_p90``). Units are
    whatever the caller feeds (the serving heartbeat uses
    ``perf_counter_ns`` on both sides).
    """

    def __init__(self, alpha: float = 0.3, window: int = 64):
        self.alpha = float(alpha)
        self.offset_ns: Optional[float] = None
        self.error_bound_ns: Optional[float] = None
        self.samples = 0
        self._rtts: deque = deque(maxlen=window)

    def observe(self, t_send_ns: float, t_recv_ns: float,
                t_remote_ns: float) -> None:
        rtt = t_recv_ns - t_send_ns
        if rtt < 0:                      # clock went backwards: discard
            return
        offset = t_remote_ns - (t_send_ns + t_recv_ns) / 2.0
        half = rtt / 2.0
        self._rtts.append(rtt)
        self.samples += 1
        if self.offset_ns is None:
            self.offset_ns = offset
            self.error_bound_ns = half
        else:
            a = self.alpha
            self.offset_ns = a * offset + (1.0 - a) * self.offset_ns
            self.error_bound_ns = a * half + (1.0 - a) * self.error_bound_ns

    def correct(self, t_remote_ns: float) -> int:
        """Map a remote timestamp into the local clock domain."""
        return int(round(t_remote_ns - (self.offset_ns or 0.0)))

    def rtt_quantile(self, q: float) -> float:
        """Empirical RTT quantile over the retained window (0 when no
        samples yet)."""
        if not self._rtts:
            return 0.0
        ordered = sorted(self._rtts)
        idx = min(len(ordered) - 1, int(q * len(ordered)))
        return float(ordered[idx])

    def snapshot(self) -> Dict[str, Any]:
        return {
            "samples": self.samples,
            "offset_ms": None if self.offset_ns is None
            else round(self.offset_ns / 1e6, 6),
            "error_bound_ms": None if self.error_bound_ns is None
            else round(self.error_bound_ns / 1e6, 6),
            "rtt_p50_ms": round(self.rtt_quantile(0.5) / 1e6, 6),
            "rtt_p90_ms": round(self.rtt_quantile(0.9) / 1e6, 6),
        }


# ---------------------------------------------------------------------------
# host-side frame building
# ---------------------------------------------------------------------------

def _span_as_dict(sp) -> Dict[str, Any]:
    return {"name": sp.name, "event_type": sp.event_type,
            "start_ns": int(sp.start_ns), "end_ns": int(sp.end_ns),
            "trace_id": sp.trace_id,
            "args": dict(sp.args) if sp.args else None}


def collect_telemetry(host_id: int, span_marks: Dict[str, int], seq: int,
                      registry=None, collector=None, signal_bus=None,
                      gauges: Optional[Dict[str, float]] = None,
                      event_tail: int = 32) -> Dict[str, Any]:
    """Build one telemetry frame on the HOST side (the ``telemetry``
    wire command's reply body). ``span_marks`` is the caller-owned
    per-trace watermark dict — each call exports only spans recorded
    since the previous call, so frames stay heartbeat-sized."""
    from .flight import flight_armed, flight_recorder
    from .memory import MEM_CLASSES, memory_ledger
    reg = registry if registry is not None else get_registry()
    coll = collector if collector is not None else span_collector
    return {
        "host_id": int(host_id),
        "pid": os.getpid(),
        "seq": int(seq),
        "t_ns": time.perf_counter_ns(),
        "metrics_text": reg.prometheus_text(),
        "gauges": {k: float(v) for k, v in (gauges or {}).items()},
        "signals": signal_bus.values() if signal_bus is not None else {},
        "events": (flight_recorder.recent_events(event_tail)
                   if flight_armed[0] else []),
        "memory": {c: memory_ledger.class_bytes(c) for c in MEM_CLASSES},
        "spans": [_span_as_dict(sp) for sp in coll.export_new(span_marks)],
    }


# ---------------------------------------------------------------------------
# exposition merging
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?( .*)$")


def _add_host_label(line: str, host: str) -> str:
    """Insert ``host="<host>"`` as the FIRST label of a sample line.
    First position keeps per-host histogram buckets accumulating
    independently under the validator (its key is the prefix before
    ``le=``). Samples that already carry a host label (the parent's own
    ``paddle_host_state{host=...}``) pass through unchanged."""
    m = _SAMPLE_RE.match(line)
    if m is None:
        return line
    name, labels, rest = m.group(1), m.group(2), m.group(3)
    if labels:
        if 'host="' in labels:
            return line
        return f'{name}{{host="{host}",{labels[1:]}{rest}'
    return f'{name}{{host="{host}"}}{rest}'


def merge_expositions(docs: Dict[str, str]) -> str:
    """Merge per-host exposition documents into ONE valid document:
    every family TYPE'd once, every sample labeled with its ``host``,
    families emitted in sorted name order, hosts in deterministic order
    (``parent`` first, then sorted) — same inputs, byte-identical
    output. Each input doc is parsed sequentially (samples after a TYPE
    line belong to that family, the shape ``observability.format``
    always emits)."""
    order = sorted(docs)
    if "parent" in docs:
        order.remove("parent")
        order.insert(0, "parent")
    fam_type: Dict[str, str] = {}
    fam_help: Dict[str, str] = {}
    fam_order: List[str] = []
    fam_samples: Dict[str, List[str]] = {}
    loose: List[str] = []                # samples before any TYPE line
    for host in order:
        text = docs.get(host) or ""
        fam = None
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith(HELP_PREFIX):
                parts = line.split(" ", 3)
                if len(parts) == 4:
                    fam_help.setdefault(parts[2], parts[3])
                continue
            if line.startswith(TYPE_PREFIX):
                parts = line.split(" ")
                if len(parts) != 4:
                    continue
                fam = parts[2]
                if fam not in fam_type:
                    fam_type[fam] = parts[3]
                    fam_order.append(fam)
                    fam_samples[fam] = []
                continue
            if line.startswith("#"):
                continue
            stamped = _add_host_label(line, host)
            if fam is None:
                loose.append(stamped)
            else:
                fam_samples[fam].append(stamped)
    lines: List[str] = []
    for fam in sorted(fam_order):
        if fam in fam_help:
            lines.append(help_line(fam, fam_help[fam]))
        lines.append(type_line(fam, fam_type[fam]))
        lines.extend(fam_samples[fam])
    lines.extend(loose)
    return "\n".join(lines) + "\n" if lines else ""


# ---------------------------------------------------------------------------
# parent-side mirrors
# ---------------------------------------------------------------------------

class HostTelemetryMirror:
    """The parent's last-known view of one host's telemetry plane."""

    __slots__ = ("host_id", "clock", "frame", "seq", "frames",
                 "spans_merged", "stale", "stale_error", "lost",
                 "last_ingest_t")

    def __init__(self, host_id: int):
        self.host_id = int(host_id)
        self.clock = ClockSync()
        self.frame: Optional[Dict[str, Any]] = None
        self.seq = -1
        self.frames = 0
        self.spans_merged = 0
        self.stale = True                # no frame yet
        self.stale_error: Optional[str] = None
        self.lost = False
        self.last_ingest_t: Optional[float] = None

    def as_dict(self) -> Dict[str, Any]:
        return {
            "host_id": self.host_id,
            "stale": self.stale,
            "stale_error": self.stale_error,
            "lost": self.lost,
            "seq": self.seq,
            "frames": self.frames,
            "spans_merged": self.spans_merged,
            "last_ingest_t": None if self.last_ingest_t is None
            else round(self.last_ingest_t, 6),
            "clock": self.clock.snapshot(),
            "frame": self.frame,
        }


class FederationHub:
    """Parent-side federation state: one :class:`HostTelemetryMirror`
    per host, span re-injection into the parent collector, federated
    ``/metrics`` / ``/varz`` / bundle surfaces. See module docstring."""

    def __init__(self, collector=None, registry=None,
                 clock: Optional[Callable[[], float]] = None):
        self._lock = threading.Lock()
        self._mirrors: Dict[int, HostTelemetryMirror] = {}
        self._collector = collector if collector is not None \
            else span_collector
        self._clock = clock if clock is not None else _utcnow_label
        reg = registry if registry is not None else get_registry()
        self._registry = reg
        self._c_frames = reg.counter(
            "paddle_federation_frames_total",
            "telemetry frames ingested per host", labels=("host",))
        self._c_spans = reg.counter(
            "paddle_federation_spans_merged_total",
            "remote spans skew-corrected into the parent trace trees",
            labels=("host",))
        self._g_offset = reg.gauge(
            "paddle_federation_clock_offset_seconds",
            "EWMA clock offset (remote - local midpoint) per host",
            labels=("host",))
        self._g_bound = reg.gauge(
            "paddle_federation_clock_error_bound_seconds",
            "EWMA offset error bound (RTT/2) per host", labels=("host",))
        self._g_stale = reg.gauge(
            "paddle_federation_stale_mirrors",
            "host mirrors currently stale or lost")

    # -- lifecycle ----------------------------------------------------------

    @property
    def armed(self) -> bool:
        return federation_armed[0]

    def arm(self) -> "FederationHub":
        federation_armed[0] = True
        return self

    def disarm(self) -> None:
        federation_armed[0] = False

    # -- mirror bookkeeping -------------------------------------------------

    def _mirror_locked(self, host_id: int) -> HostTelemetryMirror:
        m = self._mirrors.get(int(host_id))
        if m is None:
            m = self._mirrors[int(host_id)] = HostTelemetryMirror(host_id)
        return m

    def mirror(self, host_id: int) -> HostTelemetryMirror:
        with self._lock:
            return self._mirror_locked(host_id)

    def hosts(self) -> List[int]:
        with self._lock:
            return sorted(self._mirrors)

    def _publish_stale_locked(self) -> None:
        self._g_stale.set(float(sum(
            1 for m in self._mirrors.values() if m.stale or m.lost)))

    # -- ingestion ----------------------------------------------------------

    def observe_rtt(self, host_id: int, t_send_ns: float,
                    t_recv_ns: float, t_remote_ns: float) -> None:
        """Feed one heartbeat round-trip into the host's clock estimator
        and publish the offset/bound gauges."""
        with self._lock:
            m = self._mirror_locked(host_id)
            m.clock.observe(t_send_ns, t_recv_ns, t_remote_ns)
            offset, bound = m.clock.offset_ns, m.clock.error_bound_ns
        label = f"h{int(host_id)}"
        if offset is not None:
            self._g_offset.set(offset / 1e9, host=label)
        if bound is not None:
            self._g_bound.set(bound / 1e9, host=label)

    def ingest(self, host_id: int, frame: Dict[str, Any],
               t_send_ns: Optional[float] = None,
               t_recv_ns: Optional[float] = None) -> int:
        """Fold one telemetry frame into the host's mirror. When the
        round-trip timestamps are given they feed the clock estimator
        (the frame's ``t_ns`` is the remote reply stamp). Remote spans
        are skew-corrected and re-injected into the parent collector —
        skipped when the frame came from THIS process (LocalTransport:
        the spans already live in the shared collector). Returns the
        number of spans merged."""
        spans_in = frame.get("spans") or []
        with self._lock:
            m = self._mirror_locked(host_id)
            if m.lost:
                return 0                 # a dead host's mirror is frozen
            seq = int(frame.get("seq", 0))
            if m.frame is not None and seq <= m.seq:
                return 0                 # stale / duplicate frame
            if t_send_ns is not None and t_recv_ns is not None \
                    and "t_ns" in frame:
                m.clock.observe(t_send_ns, t_recv_ns, frame["t_ns"])
            m.frame = frame
            m.seq = seq
            m.frames += 1
            m.stale = False
            m.stale_error = None
            m.last_ingest_t = self._clock()
            offset = m.clock.offset_ns or 0.0
            bound = m.clock.error_bound_ns
            self._publish_stale_locked()
        label = f"h{int(host_id)}"
        self._c_frames.inc(host=label)
        self._g_offset.set(offset / 1e9, host=label)
        if bound is not None:
            self._g_bound.set(bound / 1e9, host=label)
        merged = 0
        if spans_in and timeline_armed[0] \
                and int(frame.get("pid", -1)) != os.getpid():
            from ..profiler.record import HostSpan
            spans = []
            for d in spans_in:
                args = dict(d.get("args") or {})
                args["host"] = int(host_id)
                spans.append(HostSpan(
                    d["name"], d.get("event_type", "UserDefined"),
                    int(round(d["start_ns"] - offset)),
                    int(round(d["end_ns"] - offset)),
                    0, int(frame.get("pid", 0)),
                    d.get("trace_id", ""), args))
            self._collector.note_spans(spans)
            merged = len(spans)
            with self._lock:
                m.spans_merged += merged
            self._c_spans.inc(merged, host=label)
        return merged

    def mark_stale(self, host_id: int, detail: str = "") -> None:
        """A telemetry round-trip failed: the mirror keeps its last
        frame but is flagged stale (federated surfaces say so)."""
        with self._lock:
            m = self._mirror_locked(host_id)
            m.stale = True
            m.stale_error = detail or m.stale_error
            self._publish_stale_locked()

    def mark_lost(self, host_id: int) -> None:
        """The host died: freeze its mirror as the last-known telemetry
        (the ``host_lost`` bundle embeds it via :meth:`snapshot`)."""
        with self._lock:
            m = self._mirror_locked(host_id)
            m.lost = True
            m.stale = True
            self._publish_stale_locked()

    # -- federated surfaces -------------------------------------------------

    def federated_metrics_text(self) -> str:
        """ONE exposition document covering the parent and every mirror
        under a ``host`` label (``host="parent"`` for this process)."""
        docs = {"parent": self._registry.prometheus_text()}
        with self._lock:
            mirrors = [(m.host_id, m.frame) for m in self._mirrors.values()
                       if m.frame is not None]
        for hid, frame in mirrors:
            text = frame.get("metrics_text")
            if text and int(frame.get("pid", -1)) != os.getpid():
                # LocalTransport mirrors share this process registry —
                # their families are already in the parent doc
                docs[f"h{hid}"] = text
        return merge_expositions(docs)

    def attach_fleet_signals(self, bus) -> "FederationHub":
        """Register per-host + fleet-aggregate signals on a
        :class:`~.signals.SignalBus` (the /varz fleet view and the
        ROADMAP-2 autoscaler input). Per-host signals cover the hosts
        known at attach time; fleet aggregates read the live mirror set."""
        with self._lock:
            hids = sorted(self._mirrors)
        for hid in hids:
            m = self.mirror(hid)
            bus.signal(f"h{hid}.queue_depth",
                       lambda m=m: _mirror_gauge(m, "queue_depth"))
            bus.signal(f"h{hid}.rtt_ms",
                       lambda m=m: m.clock.rtt_quantile(0.5) / 1e6)
            bus.signal(f"h{hid}.offset_ms",
                       lambda m=m: (m.clock.offset_ns or 0.0) / 1e6,
                       detect=False)
        bus.signal("fleet.queue_depth", self._fleet_queue_depth)
        bus.signal("fleet.pool_pressure", self._fleet_pool_pressure)
        bus.signal("fleet.burn_rate", self._fleet_burn_rate)
        bus.signal("host_rtt_p90", self._host_rtt_p90, detect=False)
        return self

    def _live_mirrors(self) -> List[HostTelemetryMirror]:
        with self._lock:
            return [m for m in self._mirrors.values() if not m.lost]

    def _fleet_queue_depth(self) -> float:
        return sum(_mirror_gauge(m, "queue_depth")
                   for m in self._live_mirrors())

    def _fleet_pool_pressure(self) -> float:
        return max((_mirror_gauge(m, "page_utilization")
                    for m in self._live_mirrors()), default=0.0)

    def _fleet_burn_rate(self) -> float:
        out = 0.0
        for m in self._live_mirrors():
            sig = (m.frame or {}).get("signals") or {}
            for name, st in sig.items():
                if name.endswith("slo_burn") and st.get("value"):
                    out = max(out, float(st["value"]))
        return out

    def _host_rtt_p90(self) -> float:
        """Worst p90 heartbeat RTT across live hosts, in seconds."""
        return max((m.clock.rtt_quantile(0.9) / 1e9
                    for m in self._live_mirrors()), default=0.0)

    def reconcile_error_s(self) -> float:
        """Worst clock-offset error bound across live mirrors, seconds —
        the federation's cross-host timestamp reconciliation error."""
        return max(((m.clock.error_bound_ns or 0.0) / 1e9
                    for m in self._live_mirrors()), default=0.0)

    def fleet_varz(self) -> Dict[str, Any]:
        """Compact fleet view for /varz and statusz."""
        with self._lock:
            hosts = {f"h{hid}": {
                "stale": m.stale, "lost": m.lost, "seq": m.seq,
                "frames": m.frames, "spans_merged": m.spans_merged,
                "clock": m.clock.snapshot(),
            } for hid, m in sorted(self._mirrors.items())}
        return {"armed": federation_armed[0],
                "reconcile_error_ms": round(
                    self.reconcile_error_s() * 1e3, 6),
                "hosts": hosts}

    def snapshot(self) -> Dict[str, Any]:
        """The ``host_telemetry.json`` bundle member: every mirror's
        full last-known frame + clock state."""
        with self._lock:
            hosts = {f"h{hid}": m.as_dict()
                     for hid, m in sorted(self._mirrors.items())}
        return {"schema_version": 1,
                "kind": "paddle_tpu.host_telemetry",
                "armed": federation_armed[0],
                "hosts": hosts}


def _mirror_gauge(m: HostTelemetryMirror, name: str) -> float:
    frame = m.frame
    if not frame:
        return 0.0
    return float((frame.get("gauges") or {}).get(name, 0.0))
