"""SignalBus: named, smoothed, autoscaler-ready operational signals.

The autoscaler policy (ROADMAP item 4) wants a handful of scalar
decision inputs, not a metrics scrape: SLO burn trend per replica,
queue-depth slope, queue_wait's share of end-to-end latency, paged-pool
pressure, speculation acceptance drift. The :class:`SignalBus` is the
one place those are computed: each registered signal has a *reader*
(any callable returning a float over the live objects — scheduler,
router, registry gauges, the span collector), an EWMA-smoothed value,
and a windowed **trend** (units/second slope) from the bus's
:class:`~.timeseries.MetricHistory`. Every tick also feeds each
smoothed signal to the bus's :class:`~.anomaly.AnomalyMonitor`, so a
level shift or slow drift in any signal pages (once, per-series
cooldown) without a human staring at /metrics.

Surfaces:

* ``DiagServer /varz`` — the live signal document
  (:meth:`SignalBus.varz`);
* every flight-recorder bundle embeds :meth:`history_snapshot` as
  ``history.json`` (the bus attaches itself on construction, like the
  fleet router), so an ejection postmortem shows the minutes BEFORE the
  ejection, not just the moment of it;
* ``paddle_signal_value{signal=…}`` gauges keep the newest smoothed
  values on /metrics.

Driving: the serving scheduler / fleet router tick the bus once per
step — gated on ``timeseries.history_armed`` (one list index disarmed)
and decimated inside :meth:`tick` to ``interval_s`` — the same
zero-overhead contract as the flight recorder, measured by
``benchmarks/bench_obs_overhead.py``. Time is the injected ``clock``
only (tpu-lint ``layer-wall-clock`` covers this module).
"""

from __future__ import annotations

import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .anomaly import AnomalyMonitor
from .memory import memory_ledger, pool_occupancy
from .registry import get_registry
from .timeline import span_collector, timeline_armed
from .timeseries import (HISTORY_SCHEMA_VERSION, MetricHistory,
                         history_armed)


class _Signal:
    __slots__ = ("name", "reader", "alpha", "raw", "smoothed", "detect",
                 "every", "errors")

    def __init__(self, name: str, reader: Callable[[], float],
                 alpha: float, detect: bool, every: int):
        self.name = name
        self.reader = reader
        self.alpha = float(alpha)
        self.raw: Optional[float] = None
        self.smoothed: Optional[float] = None
        self.detect = detect
        self.every = max(1, int(every))     # read every Nth bus tick
        self.errors = 0


def _max_fast_burn(monitor) -> float:
    """Worst fast-window burn across a monitor's objectives (0 when no
    monitor is attached yet — the signal exists from the start so its
    history has no gap to explain)."""
    if monitor is None:
        return 0.0
    return max((st["fast_burn"] for st in monitor.states()), default=0.0)


def _queue_wait_share(metrics) -> float:
    """queue_wait's share of end-to-end latency. Primary source: the
    span collector's critical-path attribution (exclusive segments of
    the slowest-request exemplars — already materialised on the cold
    read path, cached after first computation). Fallback when the
    timeline plane is disarmed: cumulative histogram sums from the
    serving sink."""
    if timeline_armed[0]:
        rows = span_collector.slowest(5)
        e2e = sum(r.get("e2e_ms", 0.0) for r in rows)
        if e2e > 0:
            qw = sum(r.get("segments", {}).get("queue_wait", 0.0)
                     for r in rows)
            return qw / e2e
    h_q = metrics.histograms.get("queue_wait_ms")
    h_e = metrics.histograms.get("e2e_ms")
    if h_q is None or h_e is None or h_e.sum <= 0:
        return 0.0
    return h_q.sum / h_e.sum


def _pool_pressure(engine) -> float:
    """Paged-pool pressure in [0, 1] off the engine's pool, via the
    memory ledger's ONE occupancy derivation
    (:func:`~.memory.pool_occupancy` — the scheduler's utilization
    gauges read the same split, so the autoscaler signal and /metrics
    can never disagree about what "full" means)."""
    return pool_occupancy(engine.mgr)["pressure"]


def _spec_acceptance(engine) -> float:
    spec = getattr(engine, "spec", None)
    if spec is None:
        return 1.0
    return float(spec.snapshot().get("acceptance_ratio", 1.0))


#: bump when :class:`SignalSnapshot` gains/renames a field — the
#: autoscaler refuses a mismatched document instead of mis-reading it
SIGNAL_SNAPSHOT_VERSION = 1


@dataclass(frozen=True)
class SignalSnapshot:
    """The ROADMAP-4 autoscaler input contract, promoted from prose to
    code: ONE versioned document shared by the :class:`SignalBus`
    (:meth:`SignalBus.snapshot_contract`), every flight bundle's
    ``history.json`` (embedded as ``contract``) and
    ``AutoscalePolicy.decide`` — the three can no longer silently drift.

    Fleet-level fields aggregate whatever signal set is registered:
    a scheduler-attached bus reports its own ``queue_depth`` /
    ``page_pressure`` readers directly; a router-attached bus
    aggregates the per-replica ``r<id>.*`` signals (sum for depths,
    max for burn/pressure, min for acceptance). ``per_replica`` keeps
    the unaggregated per-replica values (keyed ``"r<id>"``) for
    policies that pick WHICH replica to act on."""

    schema_version: int
    t: float
    queue_depth: float          # fleet-total queued admissions
    queue_depth_trend: float    # units/second slope over the bus window
    queue_wait_share: float     # queue_wait's share of e2e latency
    page_pressure: float        # worst paged-pool occupancy in [0, 1]
    slo_fast_burn: float        # worst fast-window burn across objectives
    spec_acceptance: float      # worst speculation acceptance (1 = off)
    pending: float              # router pending (routed + parked)
    parked: float               # requests with NO routable replica
    per_replica: Dict[str, Dict[str, float]] = field(default_factory=dict)

    def as_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "SignalSnapshot":
        ver = doc.get("schema_version")
        if ver != SIGNAL_SNAPSHOT_VERSION:
            raise ValueError(
                f"SignalSnapshot schema_version {ver!r} != "
                f"{SIGNAL_SNAPSHOT_VERSION} — refusing to mis-read a "
                "drifted contract")
        fields = {k: doc[k] for k in (
            "schema_version", "t", "queue_depth", "queue_depth_trend",
            "queue_wait_share", "page_pressure", "slo_fast_burn",
            "spec_acceptance", "pending", "parked")}
        per = {str(k): {str(s): float(x) for s, x in v.items()}
               for k, v in doc.get("per_replica", {}).items()}
        return cls(per_replica=per, **fields)

    @classmethod
    def from_bus(cls, bus: "SignalBus") -> "SignalSnapshot":
        vals = bus.values()

        def val(name: str, default: float = 0.0) -> float:
            e = vals.get(name)
            return default if e is None or e["value"] is None \
                else float(e["value"])

        def trend(name: str) -> float:
            e = vals.get(name)
            return 0.0 if e is None else float(e["trend_per_s"])

        per: Dict[str, Dict[str, float]] = {}
        for name, e in vals.items():
            head, dot, sig = name.partition(".")
            if (dot and head.startswith("r") and head[1:].isdigit()
                    and e["value"] is not None):
                per.setdefault(head, {})[sig] = float(e["value"])
        if "queue_depth" in vals:
            qd = val("queue_depth")
            qd_trend = trend("queue_depth")
        else:
            qd = sum(d.get("queue_depth", 0.0) for d in per.values())
            qd_trend = sum(trend(f"{r}.queue_depth") for r in per)
        if "page_pressure" in vals:
            pressure = val("page_pressure")
        else:
            pressure = max((d.get("page_pressure", 0.0)
                            for d in per.values()), default=0.0)
        burn = max([val("slo_burn")]
                   + [d.get("slo_burn", 0.0) for d in per.values()])
        acc = min([val("spec_acceptance", 1.0)]
                  + [d.get("spec_acceptance", 1.0)
                     for d in per.values()])
        return cls(
            schema_version=SIGNAL_SNAPSHOT_VERSION,
            t=round(bus._clock(), 6),
            queue_depth=qd, queue_depth_trend=qd_trend,
            queue_wait_share=val("queue_wait_share"),
            page_pressure=pressure, slo_fast_burn=burn,
            spec_acceptance=acc,
            pending=val("fleet.pending"), parked=val("fleet.parked"),
            per_replica=per)


class SignalBus:
    """See module docstring. One bus per serving process; construct with
    the SAME clock as the scheduler/router that ticks it so fake-clock
    tests stay deterministic end to end."""

    def __init__(self, clock: Optional[Callable[[], float]] = None,
                 interval_s: float = 1.0, window_s: float = 300.0,
                 history: Optional[MetricHistory] = None,
                 monitor: Optional[AnomalyMonitor] = None,
                 capacity: int = 512,
                 anomaly_cooldown_s: float = 60.0):
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._interval = float(interval_s)
        self.window_s = float(window_s)
        self.history = history if history is not None else MetricHistory(
            clock=self._clock, capacity=capacity,
            min_interval_s=interval_s)
        self.monitor = monitor if monitor is not None else AnomalyMonitor(
            cooldown_s=anomaly_cooldown_s)
        self._signals: Dict[str, _Signal] = {}
        self._last_tick: Optional[float] = None
        self.ticks = 0
        self._g_value = get_registry().gauge(
            "paddle_signal_value",
            "newest smoothed value per SignalBus signal",
            labels=("signal",))
        # history.json in every postmortem bundle (a later bus replaces
        # an earlier one, same lifecycle as attach_router)
        from .flight import flight_recorder
        flight_recorder.attach_signals(self)

    # -- lifecycle ----------------------------------------------------------

    @property
    def armed(self) -> bool:
        return history_armed[0]

    def arm(self) -> "SignalBus":
        """Arm the sensor plane (flips ``timeseries.history_armed`` —
        the cell the scheduler/router step loops gate their tick on)."""
        self.history.arm()
        return self

    def disarm(self) -> None:
        self.history.disarm()

    # -- registration -------------------------------------------------------

    def signal(self, name: str, reader: Callable[[], float],
               smooth: float = 0.3, detect: bool = True,
               cooldown_s: Optional[float] = None,
               every: int = 1) -> None:
        """Register signal ``name``. ``smooth`` is the EWMA alpha (1 =
        raw); ``detect=False`` keeps a signal out of the anomaly
        monitor (e.g. a value that legitimately jumps); ``every=N``
        evaluates an expensive reader on every Nth bus tick only (the
        smoothed value holds in between). Re-registering replaces the
        reader but keeps the history ring."""
        with self._lock:
            self._signals[name] = _Signal(name, reader, smooth,
                                          bool(detect), every)
        if detect and cooldown_s is not None:
            self.monitor.watch(name, cooldown_s=cooldown_s)

    def attach_scheduler(self, sched, prefix: str = "") -> "SignalBus":
        """Wire the standard single-replica signal set over a
        ``ServingScheduler``: queue depth (slope = the autoscaler's
        pressure trend), queue_wait share of e2e, paged-pool pressure,
        SLO fast burn, speculation acceptance. Also tracks the sink's
        TTFT histogram so ``/varz`` can answer "p95 TTFT over the last
        window"."""
        p = prefix
        m = sched.metrics
        self.signal(f"{p}queue_depth",
                    lambda: float(sched.queue_depth))
        # attribution share moves slowly and its reader walks the span
        # collector's slowest table — evaluate at 1/4 the bus rate
        self.signal(f"{p}queue_wait_share",
                    lambda: _queue_wait_share(m), every=4)
        self.signal(f"{p}page_pressure",
                    lambda: _pool_pressure(sched.engine))
        self.signal(f"{p}slo_burn",
                    lambda: _max_fast_burn(sched.slo_monitor))
        self.signal(f"{p}spec_acceptance",
                    lambda: _spec_acceptance(sched.engine))
        self.history.track_histogram(
            f"{p}ttft_ms", lambda: m.histograms["ttft_ms"])
        self.history.track_counter(
            f"{p}tokens_total",
            lambda: float(m.counters.get("tokens_generated_total", 0)))
        # the memory ledger's per-class byte levels ride the same rings
        # (mem.<class>_bytes series — "where did the bytes go, lately")
        memory_ledger.attach_history(self.history)
        return self

    def attach_router(self, router) -> "SignalBus":
        """Fleet signal set over a ``FleetRouter``: fleet pending /
        parked plus per-replica queue depth and SLO burn (the "burn
        trend per replica" ROADMAP item 4's policy scales on). Re-attach
        after ``replace_replica`` so signals follow the new handle."""
        self.signal("fleet.pending", lambda: float(router.pending))
        self.signal("fleet.parked", lambda: float(router.parked))
        for rid in sorted(router.replicas):
            r = router.replicas[rid]
            self.signal(f"r{rid}.queue_depth",
                        lambda r=r: float(r.queue_depth))
            self.signal(f"r{rid}.slo_burn",
                        lambda r=r: _max_fast_burn(r.slo_monitor))
            self.signal(f"r{rid}.spec_acceptance",
                        lambda r=r: _spec_acceptance(r.engine))
            self.signal(f"r{rid}.page_pressure",
                        lambda r=r: _pool_pressure(r.engine))
            # unsmoothed 0/1: can this replica take traffic NOW? The
            # autoscaler's role-balance math weighs only routable
            # replicas (a dead prefill replica must not read as "idle
            # prefill capacity" and mask the backlog)
            self.signal(f"r{rid}.routable",
                        lambda r=r: float(r.health.accepting
                                          and not r.draining
                                          and not r.degraded),
                        smooth=1.0, detect=False)
        return self

    # -- the hot-path entry (callers gate on history_armed[0]) --------------

    def tick(self, now: Optional[float] = None) -> bool:
        """One sensor round: read every signal, smooth, append to
        history, publish gauges, run anomaly detection. Decimated to
        ``interval_s`` — a call inside the interval is one clock read +
        compare. Returns whether a round ran."""
        t = self._clock() if now is None else now
        if self._last_tick is not None \
                and t - self._last_tick < self._interval:
            return False
        with self._lock:
            if self._last_tick is not None \
                    and t - self._last_tick < self._interval:
                return False
            self._last_tick = t
            self.ticks += 1
            sigs = list(self._signals.values())
        # registry families first (one lock round inside the history)
        self.history.sample(now=t)
        tick_n = self.ticks
        updates: List[tuple] = []
        for s in sigs:
            if tick_n % s.every:
                if s.smoothed is not None:      # hold between reads
                    updates.append((s.name, s.smoothed, False))
                continue
            try:
                raw = float(s.reader())
            except Exception:   # a torn reader must not kill the loop
                s.errors += 1
                continue
            s.raw = raw
            s.smoothed = raw if s.smoothed is None \
                else s.alpha * raw + (1.0 - s.alpha) * s.smoothed
            updates.append((s.name, s.smoothed, s.detect))
        for name, value, detect in updates:
            self.history.note(name, value, now=t)
            self._g_value.set(value, signal=name)
            if detect:
                self.monitor.observe(name, value, t)
        return True

    # -- reading ------------------------------------------------------------

    def values(self) -> Dict[str, Dict[str, Any]]:
        """{signal: {value, raw, trend_per_s}} — the autoscaler input."""
        with self._lock:
            sigs = list(self._signals.values())
        out: Dict[str, Dict[str, Any]] = {}
        for s in sorted(sigs, key=lambda s: s.name):
            out[s.name] = {
                "value": None if s.smoothed is None
                else round(s.smoothed, 6),
                "raw": None if s.raw is None else round(s.raw, 6),
                "trend_per_s": round(
                    self.history.slope(s.name, self.window_s), 8),
                "errors": s.errors,
            }
        return out

    def varz(self) -> Dict[str, Any]:
        """The /varz document: signal values + trends, anomaly state,
        history status."""
        return {
            "armed": history_armed[0],
            "ticks": self.ticks,
            "interval_s": self._interval,
            "window_s": self.window_s,
            "signals": self.values(),
            "anomalies": {"recent": self.monitor.recent(),
                          "series": self.monitor.snapshot()},
            "history": self.history.snapshot_status(),
        }

    def snapshot_contract(self) -> SignalSnapshot:
        """The versioned autoscaler input document
        (:class:`SignalSnapshot`) over this bus's current values — what
        ``AutoscalePolicy.decide`` consumes and ``history.json``
        embeds."""
        return SignalSnapshot.from_bus(self)

    def history_snapshot(self) -> Dict[str, Any]:
        """The ``history.json`` bundle member: the trailing window of
        every series plus signal values and emitted anomalies — the
        "5 minutes before the ejection" an autoscaler postmortem (or a
        human) replays. Bounded by the history rings by construction."""
        return {
            "schema_version": HISTORY_SCHEMA_VERSION,
            "kind": "paddle_tpu.history",
            "generated_t": round(self._clock(), 6),
            "window_s": self.window_s,
            "signals": self.values(),
            "contract": self.snapshot_contract().as_dict(),
            "series": self.history.snapshot(self.window_s),
            "anomalies": self.monitor.recent(),
        }
