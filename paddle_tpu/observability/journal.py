"""Black-box journal: the fleet's nondeterminism frontier as a bounded,
versioned JSONL ring — every debug bundle becomes a runnable incident.

The repo's signature discipline is byte-identical behavior under chaos
(failover, migration, disagg handoff, autoscale), but a flight bundle
was read-only: metrics, events and traces you can *look at*. This
module captures the complete set of inputs that make a fleet step loop
deterministic, so :mod:`.replay` can re-execute any bundle offline and
localize the first divergence to a (step, replica, component):

=========  ================================================================
frame      records
=========  ================================================================
``head``   schema version, model geometry (``model_spec``), fleet
           topology (``FleetRouter.journal_topology``: router kind +
           config, per-replica engine/scheduler/health knobs)
``step``   one router step: its 1-based counter and the injected-clock
           sample at step entry
``arrival`` one ``FleetRouter.submit``: router rid, prompt tokens +
           crc32, priority/deadline/budget, the RESOLVED sampler seed
           (pinned at the fleet boundary) and grammar fingerprint
``fault``  one consumed :class:`~paddle_tpu.resilience.faults.Fault`
           (stable id + resolved scope) at the moment it fired
``health`` one replica breaker transition (healthy → suspect →
           ejected → half_open …), diffed at end of router step
``scale``  one autoscale ``ScaleRecord`` ref (seq/action/reason)
``wire``   one serialized wire message's digest (kind, crc32, nbytes)
           — disagg handoffs and multihost transfers
``handoff`` one prefill→decode KV handoff (src/dst/pages/outcome)
``admit``  one scheduler admission (scheduler rid → engine rid, per
           replica namespace)
``outcome`` one terminal request outcome: state/outcome/replica/
           failovers, the full stream tokens + crc32, and the engine's
           own terminal checksum
=========  ================================================================

Armed-gating follows ``flight``/``dispatch``: hot paths check the
module cell ``journal_armed`` (one list index, zero overhead disarmed
— guarded by ``benchmarks/bench_obs_overhead.py``). Every frame line
carries a crc32 of its canonical JSON; :func:`decode_journal` rejects
truncation, version skew, per-line corruption and sequence gaps with
structured :class:`JournalError` codes exactly like ``serving/wire.py``
rejects torn wire frames. Only stdlib + numpy here: ``serving/wire.py``
and ``resilience/faults.py`` tap into this module and must stay
importable without JAX.
"""

from __future__ import annotations

import json
import threading
import zlib
from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, List, Optional

import numpy as np

#: the current journal wire format; decode rejects anything else
JOURNAL_VERSION = 1

#: the one cell hot paths check before building a frame (mutable list so
#: callers read a stable module attribute, not a rebindable name)
journal_armed = [False]

#: structured decode-rejection codes (mirrors ``serving.wire.WireError``)
JOURNAL_ERROR_CODES = ("truncated", "version_skew", "checksum_mismatch",
                       "schema", "gap")


class JournalError(Exception):
    """Structured journal decode failure; ``code`` is one of
    :data:`JOURNAL_ERROR_CODES`."""

    def __init__(self, code: str, detail: str = ""):
        assert code in JOURNAL_ERROR_CODES, code
        self.code = code
        self.detail = detail
        super().__init__(f"journal {code}: {detail}")

    def as_dict(self) -> Dict[str, str]:
        return {"error": "journal", "code": self.code,
                "detail": self.detail}


def token_checksum(tokens) -> int:
    """crc32 over the int32 little-endian bytes of a token sequence —
    the stream/terminal checksum every ``outcome`` frame carries and
    the engine stamps at ``_retire``."""
    a = np.asarray(list(tokens) if not isinstance(tokens, np.ndarray)
                   else tokens, np.int32)
    return zlib.crc32(a.astype("<i4").tobytes()) & 0xFFFFFFFF


def canonical_frame(frame: Dict[str, Any]) -> Dict[str, Any]:
    """A frame minus its transport fields (``seq``, ``crc``) — the
    payload two journals are compared on."""
    return {k: v for k, v in frame.items() if k not in ("seq", "crc")}


def _sign(frame: Dict[str, Any]) -> str:
    """One JSONL line: the frame plus a crc32 of its canonical JSON
    (sorted keys, no crc) — per-line corruption is detectable without
    trusting any other line."""
    body = {k: v for k, v in frame.items() if k != "crc"}
    canon = json.dumps(body, sort_keys=True, separators=(",", ":"))
    crc = zlib.crc32(canon.encode("utf-8")) & 0xFFFFFFFF
    return json.dumps({**body, "crc": crc}, sort_keys=True,
                      separators=(",", ":"))


def encode_frames(head: Dict[str, Any],
                  frames: List[Dict[str, Any]]) -> bytes:
    """Serialize a head payload + frame list to journal JSONL. Public
    so tests can re-sign a doctored journal (planted divergences)."""
    lines = [_sign({"t": "head", "seq": 0,
                    "journal_version": JOURNAL_VERSION, **head})]
    lines.extend(_sign(f) for f in frames)
    return ("\n".join(lines) + "\n").encode("utf-8")


@dataclass
class DecodedJournal:
    """A structurally verified journal: the head payload, the frame
    list (transport fields still attached) and how many leading frames
    the bounded ring dropped before the dump."""

    head: Dict[str, Any]
    frames: List[Dict[str, Any]]
    dropped: int


def decode_journal(data: bytes) -> DecodedJournal:
    """Verify + parse journal JSONL. Raises :class:`JournalError`:

    * ``truncated`` — empty input, missing trailing newline, or an
      unparseable LAST line (a torn write); also emits a
      ``journal_truncated`` event
    * ``version_skew`` — head ``journal_version`` != ours
    * ``checksum_mismatch`` — a line's crc32 does not match its body
    * ``schema`` — unparseable interior line / missing required fields
    * ``gap`` — non-contiguous ``seq`` after the first frame (a ring
      drop may only appear between head and first frame; it is
      reported as ``dropped``, not an error)
    """
    try:
        return _decode_inner(data)
    except JournalError as e:
        if e.code == "truncated":
            try:
                from .events import emit_event
                emit_event("journal_truncated", detail=e.detail,
                           nbytes=len(data))
            except Exception:
                pass
        raise


def _decode_inner(data: bytes) -> DecodedJournal:
    if not data:
        raise JournalError("truncated", "empty journal")
    text = data.decode("utf-8", errors="replace")
    if not text.endswith("\n"):
        raise JournalError("truncated",
                           "no trailing newline (torn final write)")
    lines = text.splitlines()
    frames: List[Dict[str, Any]] = []
    for i, line in enumerate(lines):
        last = i == len(lines) - 1
        try:
            obj = json.loads(line)
        except Exception:
            if last:
                raise JournalError("truncated",
                                   f"line {i} unparseable (torn write)")
            raise JournalError("schema", f"line {i} is not JSON")
        if not isinstance(obj, dict) or "crc" not in obj:
            raise JournalError("schema", f"line {i} has no crc")
        crc = obj["crc"]
        body = {k: v for k, v in obj.items() if k != "crc"}
        canon = json.dumps(body, sort_keys=True, separators=(",", ":"))
        if (zlib.crc32(canon.encode("utf-8")) & 0xFFFFFFFF) != crc:
            raise JournalError("checksum_mismatch",
                               f"line {i} crc mismatch")
        if "t" not in obj or "seq" not in obj:
            raise JournalError("schema", f"line {i} missing t/seq")
        frames.append(obj)
    head = frames[0]
    if head.get("t") != "head":
        raise JournalError("schema", "first frame is not a head frame")
    ver = head.get("journal_version")
    if ver != JOURNAL_VERSION:
        raise JournalError(
            "version_skew",
            f"journal_version={ver!r}, decoder speaks {JOURNAL_VERSION}")
    body_frames = frames[1:]
    dropped = 0
    if body_frames:
        first = int(body_frames[0]["seq"])
        if first < 1:
            raise JournalError("schema", f"first frame seq={first}")
        dropped = first - 1     # ring rotation before the dump
        prev = first
        for f in body_frames[1:]:
            s = int(f["seq"])
            if s != prev + 1:
                raise JournalError(
                    "gap", f"seq jumps {prev} -> {s} mid-journal")
            prev = s
    head_payload = {k: v for k, v in head.items()
                    if k not in ("t", "seq", "crc", "journal_version")}
    return DecodedJournal(head=head_payload, frames=body_frames,
                          dropped=dropped)


# -- divergence localization -------------------------------------------------

@dataclass
class Divergence:
    """The first point where a journaled run and its re-execution
    disagree — the replay report's one actionable line."""

    index: int                      # frame position (post-head)
    step: Optional[int]             # router step the frame belongs to
    replica: Optional[int]          # replica scope, when the frame has one
    component: str                  # frame type: outcome/health/fault/...
    journaled: Optional[Dict[str, Any]]
    observed: Optional[Dict[str, Any]]

    def as_dict(self) -> Dict[str, Any]:
        return {"index": self.index, "step": self.step,
                "replica": self.replica, "component": self.component,
                "journaled": self.journaled, "observed": self.observed}


def _frame_scope(frame: Optional[Dict[str, Any]]):
    if frame is None:
        return None, None, "missing"
    return (frame.get("step"), frame.get("replica"),
            str(frame.get("t", "unknown")))


def first_divergence(journaled: List[Dict[str, Any]],
                     observed: List[Dict[str, Any]],
                     ) -> Optional[Divergence]:
    """Compare two frame sequences canonically (transport fields
    ignored) and return the FIRST mismatch, or None. ``observed`` being
    a strict extension of ``journaled`` is NOT a divergence: a bundle
    dumped mid-incident (e.g. at ejection) journals a prefix of the
    run, and replay completes the step that was in flight."""
    for i, jf in enumerate(journaled):
        of = observed[i] if i < len(observed) else None
        if of is None or canonical_frame(jf) != canonical_frame(of):
            step, replica, component = _frame_scope(jf)
            if of is not None and (replica is None
                                   or jf.get("t") != of.get("t")):
                # scope off the observed side when it names one and the
                # journaled frame doesn't (e.g. a dropped chaos frame
                # shifts the whole tail)
                if replica is None:
                    replica = of.get("replica")
            return Divergence(
                index=i, step=step, replica=replica, component=component,
                journaled=canonical_frame(jf),
                observed=None if of is None else canonical_frame(of))
    return None


# -- the recorder ------------------------------------------------------------

class JournalRecorder:
    """Bounded, lock-guarded frame ring. Hot paths gate on
    ``journal_armed[0]`` before calling any ``note_*``; the recorder
    itself never raises into a caller (frame payloads are plain JSON
    scalars/lists built by the call sites)."""

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._capacity = int(capacity)
        self._frames: Deque[Dict[str, Any]] = deque(maxlen=self._capacity)
        self._head: Optional[Dict[str, Any]] = None
        self._seq = 0
        self._dropped = 0
        self._step = 0
        self.frames_total = 0
        self._c_frames = None
        self._c_dropped = None

    # -- lifecycle ----------------------------------------------------------

    @property
    def armed(self) -> bool:
        return journal_armed[0]

    def arm(self, capacity: Optional[int] = None) -> "JournalRecorder":
        with self._lock:
            if capacity is not None and int(capacity) != self._capacity:
                self._capacity = int(capacity)
                self._frames = deque(self._frames, maxlen=self._capacity)
            journal_armed[0] = True
        return self

    def disarm(self) -> None:
        journal_armed[0] = False

    def clear(self) -> None:
        with self._lock:
            self._frames.clear()
            self._head = None
            self._seq = 0
            self._dropped = 0
            self._step = 0

    def _counters(self):
        if self._c_frames is None:
            from .registry import get_registry
            reg = get_registry()
            self._c_frames = reg.counter(
                "paddle_journal_frames_total",
                "black-box journal frames recorded, by frame type",
                labels=("type",))
            self._c_dropped = reg.counter(
                "paddle_journal_dropped_total",
                "journal frames evicted by the bounded ring before a "
                "dump — a replay of this window will refuse (rotated)")
        return self._c_frames, self._c_dropped

    # -- recording ----------------------------------------------------------

    def record_head(self, **payload) -> None:
        """Start a capture: the head frame (model geometry + fleet
        topology) resets the ring — one journal is ONE incident
        window."""
        with self._lock:
            self._frames.clear()
            self._seq = 0
            self._dropped = 0
            self._step = 0
            self._head = dict(payload)

    @property
    def head(self) -> Optional[Dict[str, Any]]:
        return self._head

    def note(self, type_: str, **payload) -> None:
        """Append one frame. ``step`` is stamped from the last
        :meth:`note_step`, so every frame is addressable as (step,
        replica, component)."""
        c_frames, c_dropped = self._counters()
        with self._lock:
            self._seq += 1
            frame = {"t": type_, "seq": self._seq, "step": self._step,
                     **payload}
            if len(self._frames) == self._capacity:
                self._dropped += 1
                c_dropped.inc()
            self._frames.append(frame)
            self.frames_total += 1
        c_frames.inc(type=type_)

    # typed conveniences — call sites stay one line and payload shapes
    # stay uniform across the tree

    def note_step(self, step: int, clock: float) -> None:
        with self._lock:
            self._step = int(step)
        self.note("step", clock=float(clock))

    def note_arrival(self, rid: int, clock: float, prompt: List[int],
                     prompt_crc: int, priority: int,
                     deadline_ms: Optional[float], budget: int,
                     sampler: Optional[Dict[str, Any]] = None,
                     grammar: Optional[Dict[str, Any]] = None) -> None:
        self.note("arrival", rid=int(rid), clock=float(clock),
                  prompt=prompt, prompt_crc=int(prompt_crc),
                  priority=int(priority),
                  deadline_ms=(None if deadline_ms is None
                               else float(deadline_ms)),
                  budget=int(budget), sampler=sampler, grammar=grammar)

    def note_fault(self, record: Dict[str, Any]) -> None:
        # nested under "fault": the record's own "step" is the fault's
        # SCHEDULED step, distinct from the frame's journal step stamp
        self.note("fault", fault=dict(record))

    def note_health(self, replica: int, prev: Optional[str],
                    state: str) -> None:
        self.note("health", replica=int(replica), prev=prev,
                  state=str(state))

    def note_scale(self, seq: int, action: str, reason: str,
                   replica: Optional[int], role: Optional[str]) -> None:
        self.note("scale", scale_seq=int(seq), action=str(action),
                  reason=str(reason), replica=replica, role=role)

    def note_wire(self, kind: str, crc: int, nbytes: int) -> None:
        self.note("wire", kind=str(kind), wire_crc=int(crc),
                  nbytes=int(nbytes))

    def note_handoff(self, rid: int, src: int, dst: int, pages: int,
                     outcome: str) -> None:
        self.note("handoff", rid=int(rid), src=int(src), dst=int(dst),
                  pages=int(pages), outcome=str(outcome))

    def note_admit(self, srid: int, engine_rid: int, ns: str) -> None:
        self.note("admit", srid=int(srid), engine_rid=int(engine_rid),
                  ns=str(ns))

    def note_outcome(self, rid: int, state: str, outcome: str,
                     replica: Optional[int], failovers: int,
                     tokens: List[int], stream_crc: int,
                     engine_crc: Optional[int]) -> None:
        self.note("outcome", rid=int(rid), state=str(state),
                  outcome=str(outcome), replica=replica,
                  failovers=int(failovers), tokens=tokens,
                  stream_crc=int(stream_crc), engine_crc=engine_crc)

    # -- reading ------------------------------------------------------------

    def frames(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._frames)

    def tail(self, n: int = 64) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._frames)[-int(n):]

    @property
    def dropped(self) -> int:
        return self._dropped

    def snapshot_status(self) -> Dict[str, Any]:
        with self._lock:
            return {"armed": journal_armed[0],
                    "journal_version": JOURNAL_VERSION,
                    "capacity": self._capacity,
                    "frames": len(self._frames),
                    "frames_total": self.frames_total,
                    "dropped": self._dropped,
                    "step": self._step,
                    "head": self._head is not None}

    def encode(self) -> bytes:
        """The journal as versioned, crc-per-line JSONL — the
        ``journal.jsonl`` member of every flight bundle."""
        with self._lock:
            head = dict(self._head or {})
            frames = list(self._frames)
        return encode_frames(head, frames)


#: the process-global journal every tap writes into
journal = JournalRecorder()


# -- head-frame helpers ------------------------------------------------------

def model_spec(cfg, params_seed: int,
               vocab: Optional[List[str]] = None) -> Dict[str, Any]:
    """Serialize a model config dataclass for the head frame. ``dtype``
    is stored by numpy name (this module stays JAX-free); replay
    resolves it back. ``vocab`` is required only when grammar-
    constrained arrivals must be re-compiled at replay."""
    import dataclasses
    d = dataclasses.asdict(cfg)
    if "dtype" in d:
        try:
            d["dtype"] = np.dtype(d["dtype"]).name
        except Exception:
            d["dtype"] = str(d["dtype"])
    return {"arch": type(cfg).__name__, "config": d,
            "params_seed": int(params_seed), "vocab": vocab}
