"""Request timelines: span-tree collector + critical-path attribution.

The stack already emits rich per-process spans (``profiler.record.
emit_span`` / ``RecordEvent``) stamped with per-request trace ids, but
nothing assembles them: "where did this request's p99 go?" means
grepping a chrome trace by hand. This module closes that loop:

* :class:`SpanCollector` — a bounded in-process sink (same tap
  discipline as the flight recorder: hot paths check the module-level
  ``timeline_armed`` cell, one list index when disarmed) that groups
  every span by ``trace_id`` into per-request records. One trace id is
  minted at the OUTERMOST submit (``FleetRouter.submit`` when a fleet
  fronts the engines, else ``ServingScheduler.submit``) and propagated
  through replica dispatch, scheduler admission, engine
  prefill/decode/speculation rounds and failover resubmission on a
  sibling replica — so a request that dies mid-stream and resumes
  elsewhere is still ONE tree.
* **critical-path attribution** — when a trace's root span arrives
  (``router.request``, or the scheduler's ``*.request``), the
  collector attributes the request's end-to-end latency to *exclusive*
  segments: ``queue_wait``, ``admission``, ``prefill``, ``decode``,
  ``spec_draft`` / ``spec_verify``, ``failover`` (the gap between a
  replica ejection and the sibling resubmission), ``deliver`` (the
  tail between the last engine span and stream close) and ``host``
  (uncovered scheduler/plan time). Attribution is a sweep over the
  root interval where the innermost covering span wins each slice, so
  the segments tile the root exactly: their sum reconciles with the
  measured e2e by construction.
* **slowest-request exemplars** — the worst ``slow_k`` completed
  requests are auto-captured (tree + segments, materialised so ring
  eviction cannot tear them) and served at ``DiagServer /tracez``; the
  scheduler's ``statusz()`` renders the table, and armed flight-
  recorder bundles embed the whole document (``timelines.json``).

Span-name → segment mapping is declared in ``observability/catalog.py``
(``SPANS``) and lint-checked both directions by tpu-lint's
``span-contract`` rule.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

#: the one cell span emitters check before touching the collector
#: (mutable list so callers read a stable module attribute)
timeline_armed = [False]

#: exact span name -> segment category
_EXACT_CATEGORY = {
    "engine.prefill": "prefill",
    "engine.decode_chunk": "decode",
    "engine.spec_draft": "spec_draft",
    "engine.spec_round": "spec_verify",
    "router.failover_gap": "failover",
    "router.migration": "migration",
    "router.dcn_transfer": "dcn_transfer",
}

#: namespaced span suffix (``<metrics namespace>.<suffix>``) -> category
_SUFFIX_CATEGORY = {
    "queue_wait": "queue_wait",
    "admission": "admission",
}

#: every segment key attribution may produce (documented README order)
SEGMENT_KEYS = ("queue_wait", "admission", "prefill", "decode",
                "spec_draft", "spec_verify", "failover", "migration",
                "dcn_transfer", "deliver", "host")


def span_category(name: str) -> Optional[str]:
    """Segment category for a span name, None for container/other spans."""
    cat = _EXACT_CATEGORY.get(name)
    if cat is not None:
        return cat
    return _SUFFIX_CATEGORY.get(name.rsplit(".", 1)[-1])


def is_root_span(name: str) -> bool:
    """Request-envelope spans: the fleet root ``router.request`` or a
    scheduler-level ``<namespace>.request``."""
    return name == "router.request" or name.endswith(".request")


def _span_dict(sp) -> Dict[str, Any]:
    d: Dict[str, Any] = {
        "name": sp.name,
        "category": span_category(sp.name),
        "start_us": round(sp.start_ns / 1e3, 1),
        "dur_ms": round((sp.end_ns - sp.start_ns) / 1e6, 4),
    }
    if sp.args:
        d["args"] = dict(sp.args)
    return d


def build_tree(spans) -> List[Dict[str, Any]]:
    """Nest spans by interval containment (outermost first). Returns the
    forest's roots as nested dicts — normally one ``router.request`` /
    ``*.request`` envelope with phase spans inside."""
    nodes = [(sp.start_ns, -sp.end_ns, i, sp) for i, sp in enumerate(spans)]
    nodes.sort(key=lambda t: t[:3])
    roots: List[Dict[str, Any]] = []
    stack: List[tuple] = []          # (end_ns, dict)
    for start, neg_end, _i, sp in nodes:
        end = -neg_end
        node = _span_dict(sp)
        while stack and not (stack[-1][0] >= end
                             and stack[-1][1]["_start"] <= start):
            stack.pop()
        node["_start"] = start
        if stack:
            stack[-1][1].setdefault("children", []).append(node)
        else:
            roots.append(node)
        stack.append((end, node))
    for r in roots:
        _strip_internal(r)
    return roots


def _strip_internal(node: Dict[str, Any]) -> None:
    node.pop("_start", None)
    for c in node.get("children", ()):
        _strip_internal(c)


def attribute_spans(spans, trace_id: str = "") -> Dict[str, Any]:
    """Critical-path attribution for one trace's spans (see module
    docstring). The returned ``segments`` (ms) tile the root interval,
    so ``sum(segments.values()) == e2e_ms`` exactly."""
    roots = [sp for sp in spans if is_root_span(sp.name)]
    fleet = [sp for sp in roots if sp.name == "router.request"]
    pool = fleet or roots or list(spans)
    t0 = min(sp.start_ns for sp in pool)
    t1 = max(sp.end_ns for sp in pool)
    root_name = (fleet or roots or [None])[0]
    intervals = []                   # (start, end, category)
    for sp in spans:
        cat = span_category(sp.name)
        if cat is None:
            continue
        a, b = max(sp.start_ns, t0), min(sp.end_ns, t1)
        if b > a:
            intervals.append((a, b, cat))
    segments = {}
    covered_until = max((b for _, b, _ in intervals), default=t0)
    points = sorted({t0, t1, *(p for a, b, _ in intervals for p in (a, b))})
    for p, q in zip(points, points[1:]):
        if q <= t0 or p >= t1:
            continue
        covering = [iv for iv in intervals if iv[0] <= p and iv[1] >= q]
        if covering:
            # innermost wins: the covering span that started last (ties:
            # the one ending first) owns the slice exclusively
            cat = max(covering, key=lambda iv: (iv[0], -iv[1]))[2]
        elif intervals and p >= covered_until:
            cat = "deliver"          # tail: tokens done, stream closing
        else:
            cat = "host"             # scheduler/plan time between spans
        segments[cat] = segments.get(cat, 0.0) + (q - p)
    e2e_ms = (t1 - t0) / 1e6
    return {
        "trace_id": trace_id,
        "root": getattr(root_name, "name", None),
        "e2e_ms": round(e2e_ms, 4),
        "segments": {k: round(v / 1e6, 4)
                     for k, v in sorted(segments.items())},
        "spans": len(spans),
        "complete": bool(roots),
    }


class _Trace:
    __slots__ = ("spans", "complete", "dropped")

    def __init__(self):
        self.spans: List[Any] = []
        self.complete = False
        self.dropped = 0


class SpanCollector:
    """Bounded per-trace span sink (see module docstring). Hot-path
    callers (``profiler.record``) gate on ``timeline_armed[0]`` before
    calling :meth:`note_span`, so the disarmed cost is one list index —
    the same zero-overhead contract as the flight recorder, guarded by
    ``benchmarks/bench_obs_overhead.py``."""

    def __init__(self, max_traces: int = 512,
                 max_spans_per_trace: int = 1024, slow_k: int = 8):
        self._lock = threading.Lock()
        self._max_traces = max_traces
        self._max_spans = max_spans_per_trace
        self._slow_k = slow_k
        self._traces: "OrderedDict[str, _Trace]" = OrderedDict()
        self._completed_fifo: deque = deque()      # eviction order hints
        self._slowest: List[Dict[str, Any]] = []   # desc by e2e_ms
        self._slowest_raw: List[tuple] = []        # unranked (e2e, tid)
        self._raw_tids: set = set()                # O(1) membership twin
        self.dropped_spans = 0
        self.completed = 0

    # -- lifecycle ----------------------------------------------------------

    @property
    def armed(self) -> bool:
        return timeline_armed[0]

    def arm(self, max_traces: Optional[int] = None,
            max_spans_per_trace: Optional[int] = None,
            slow_k: Optional[int] = None) -> "SpanCollector":
        with self._lock:
            if max_traces is not None:
                self._max_traces = max_traces
            if max_spans_per_trace is not None:
                self._max_spans = max_spans_per_trace
            if slow_k is not None:
                self._slow_k = slow_k
            timeline_armed[0] = True
        return self

    def disarm(self) -> None:
        timeline_armed[0] = False

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._completed_fifo.clear()
            self._slowest = []
            self._slowest_raw = []
            self._raw_tids = set()
            self.dropped_spans = 0
            self.completed = 0

    # -- recording (armed-only; callers gate on timeline_armed[0]) ----------

    def note_span(self, span) -> None:
        """Called by ``profiler.record`` with a ``HostSpan``. Spans with
        no trace id are not per-request and are ignored; a span that is
        neither categorised nor a request root never STARTS a trace
        (scheduler step / dispatch-op spans carry step trace ids and
        would otherwise churn the ring)."""
        if not span.trace_id:
            return
        with self._lock:
            self._note_locked(span)

    def note_spans(self, spans) -> None:
        """Batch variant (``record.emit_spans``): one lock round for an
        engine step's whole span set, with the common case — a
        categorised span landing in a known, unfilled trace — appended
        inline (the serving loop's armed cost, bench_obs_overhead)."""
        with self._lock:
            traces = self._traces
            max_spans = self._max_spans
            for span in spans:
                tid = span.trace_id
                if not tid:
                    continue
                tr = traces.get(tid)
                if (tr is not None and len(tr.spans) < max_spans
                        and not span.name.endswith(".request")):
                    tr.spans.append(span)
                else:
                    self._note_locked(span)

    def _note_locked(self, span) -> None:
        tid = span.trace_id
        root = is_root_span(span.name)
        tr = self._traces.get(tid)
        if tr is None:
            if not root and span_category(span.name) is None:
                return
            tr = self._traces[tid] = _Trace()
            self._evict_locked()
        if len(tr.spans) >= self._max_spans and not root:
            tr.dropped += 1
            self.dropped_spans += 1
            return
        tr.spans.append(span)
        if root:
            # completion: ONE list append on the hot path — ranking,
            # trace-id dedupe, tree + segment attribution all happen
            # lazily at read time (or at ring eviction), never per
            # request in the serving loop (bench_obs_overhead budget)
            if not tr.complete:
                tr.complete = True
                self.completed += 1
                self._completed_fifo.append(tid)
            self._slowest_raw.append(
                ((span.end_ns - span.start_ns) / 1e6, tid))
            self._raw_tids.add(tid)
            if len(self._slowest_raw) >= 256:   # amortised bound
                self._prune_slowest_locked()

    def _prune_slowest_locked(self) -> None:
        """Fold the raw completion feed into the ranked slowest table:
        worst e2e per trace id wins, table trimmed to ``slow_k``.
        Already-materialised entries keep their segments/tree."""
        if not self._slowest_raw:
            return
        raw, self._slowest_raw = self._slowest_raw, []
        self._raw_tids = set()
        by_tid = {e["trace_id"]: e for e in self._slowest}
        for e2e_ms, tid in raw:
            cur = by_tid.get(tid)
            if cur is None or e2e_ms >= cur["e2e_ms"]:
                # a later root (the fleet envelope after replica-level
                # ones) re-ranks the trace; drop stale materialisation
                by_tid[tid] = {"trace_id": tid,
                               "e2e_ms": round(e2e_ms, 4)}
        ranked = sorted(by_tid.values(),
                        key=lambda e: (-e["e2e_ms"], e["trace_id"]))
        self._slowest = ranked[:self._slow_k]

    def _evict_locked(self) -> None:
        while len(self._traces) > self._max_traces:
            victim = None
            while self._completed_fifo:              # oldest complete first
                k = self._completed_fifo.popleft()   # (O(1): lazy hints,
                if k in self._traces:                # stale ids skipped)
                    victim = k
                    break
            if victim is None:
                victim = next(iter(self._traces))    # else plain oldest
            if victim in self._raw_tids:
                # the victim has an unranked completion: fold the raw
                # feed so its exemplar can rank before the spans go.
                # Skipping the prune-sort for the common churn victim
                # (neither raw nor ranked) is real armed-loop savings —
                # steady serving evicts one trace per admission.
                self._prune_slowest_locked()
            for e in self._slowest:
                # about to lose the victim's raw spans: materialise its
                # slowest-table entry first so the exemplar survives
                if e["trace_id"] == victim:
                    self._materialise_locked(e)
            del self._traces[victim]

    def _materialise_locked(self, entry: Dict[str, Any]) -> None:
        """Fill a slowest-table entry's segments + tree from the ring
        (no-op when already materialised or the spans are gone)."""
        if "segments" in entry:
            return
        tr = self._traces.get(entry["trace_id"])
        if tr is None:
            entry["segments"] = {}
            entry["tree"] = []
            return
        timeline = attribute_spans(tr.spans, trace_id=entry["trace_id"])
        timeline["tree"] = build_tree(tr.spans)
        # the lazily-computed e2e (root envelope) supersedes the ranking
        # estimate taken from whichever root span completed last
        entry.update(timeline)

    # -- reading ------------------------------------------------------------

    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def spans(self, trace_id: str) -> List[Any]:
        with self._lock:
            tr = self._traces.get(trace_id)
            return list(tr.spans) if tr is not None else []

    def export_new(self, marks: Dict[str, int]) -> List[Any]:
        """Incremental span export for telemetry federation: return
        every span recorded since the caller's last call, where ``marks``
        is the CALLER-owned per-trace watermark dict this method
        advances in place (watermarks of evicted traces are pruned so
        the dict stays bounded by the ring). Spans a trace dropped past
        ``max_spans_per_trace`` — or whole traces evicted between calls
        — are simply absent, the same losses a local reader sees."""
        with self._lock:
            out: List[Any] = []
            for tid, tr in self._traces.items():
                n = marks.get(tid, 0)
                if len(tr.spans) > n:
                    out.extend(tr.spans[n:])
                    marks[tid] = len(tr.spans)
            for tid in list(marks):
                if tid not in self._traces:
                    del marks[tid]
            return out

    def tree(self, trace_id: str) -> List[Dict[str, Any]]:
        """The trace's span forest as nested dicts (normally one root)."""
        return build_tree(self.spans(trace_id))

    def attribute(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Critical-path segments for one trace (None when unknown)."""
        spans = self.spans(trace_id)
        if not spans:
            return None
        return attribute_spans(spans, trace_id=trace_id)

    def slowest(self, n: int = 5, trees: bool = False
                ) -> List[Dict[str, Any]]:
        """Worst completed requests, slowest first: trace id, e2e and
        exclusive segments (plus the span tree when ``trees=True`` —
        the /tracez document). Attribution materialises here, on the
        cold read path, not per completion on the serving hot path."""
        with self._lock:
            self._prune_slowest_locked()
            out = []
            for e in self._slowest[:n]:
                self._materialise_locked(e)
                row = {k: v for k, v in e.items() if k != "tree"}
                if trees:
                    row["tree"] = e.get("tree", [])
                out.append(row)
            return out

    def snapshot_status(self) -> Dict[str, Any]:
        with self._lock:
            self._prune_slowest_locked()
            for e in self._slowest[:5]:
                self._materialise_locked(e)
            return {"armed": timeline_armed[0],
                    "traces": len(self._traces),
                    "completed": self.completed,
                    "dropped_spans": self.dropped_spans,
                    "slowest": [
                        {k: v for k, v in e.items() if k != "tree"}
                        for e in self._slowest[:5]]}

    def tracez(self) -> Dict[str, Any]:
        """The /tracez document: collector status, the slowest-request
        exemplars WITH their span trees, and the span trees of every
        still-active (incomplete) trace — what a postmortem bundle needs
        to be self-contained."""
        with self._lock:
            active = {tid: build_tree(tr.spans)
                      for tid, tr in self._traces.items()
                      if not tr.complete}
        doc = self.snapshot_status()
        doc["slowest"] = self.slowest(self._slow_k, trees=True)
        doc["active"] = active
        return doc


#: the process-global collector the span emitters tap while armed
span_collector = SpanCollector()
