"""Trace-context propagation: one id per serving request / training step.

A :class:`TraceContext` rides a ``contextvars.ContextVar``, so it flows
scheduler → engine step → ``core.dispatch.apply`` RecordEvent spans
without threading ids through every call signature, and survives the
serving watchdog thread (``contextvars`` copy into ``threading.Thread``
targets started inside the context... they do NOT automatically — the
scheduler passes the context explicitly where it matters).

Ids are deterministic (pid + monotonic counter, no wall clock / RNG) so
chaos-replay runs produce identical traces.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Iterator, Optional

_ctx: contextvars.ContextVar[Optional["TraceContext"]] = \
    contextvars.ContextVar("paddle_tpu_trace", default=None)
_counter = itertools.count(1)
_counter_lock = threading.Lock()


@dataclass(frozen=True)
class TraceContext:
    """Immutable trace identity: ``trace_id`` correlates spans, the
    optional ``request_id``/``step`` say what the trace is about."""

    trace_id: str
    request_id: Optional[int] = None
    step: Optional[int] = None


def new_trace_id(prefix: str = "t") -> str:
    with _counter_lock:
        n = next(_counter)
    return f"{prefix}-{os.getpid():x}-{n:x}"


def current_trace() -> Optional[TraceContext]:
    return _ctx.get()


def current_trace_id() -> str:
    ctx = _ctx.get()
    return ctx.trace_id if ctx is not None else ""


@contextmanager
def trace_context(trace_id: Optional[str] = None,
                  request_id: Optional[int] = None,
                  step: Optional[int] = None) -> Iterator[TraceContext]:
    """Enter a trace context (minting an id when none is given); restores
    the previous context on exit, so nesting works."""
    ctx = TraceContext(trace_id or new_trace_id(),
                       request_id=request_id, step=step)
    token = _ctx.set(ctx)
    try:
        yield ctx
    finally:
        _ctx.reset(token)
