"""Live diagnostics server — the process's operable surface.

A stdlib-only (``http.server`` on a daemon thread) debug endpoint; THE
one place in the tree allowed to open a listening socket for
diagnostics (``tests/test_observability_lint.py`` enforces it):

========== ==============================================================
route      serves
========== ==============================================================
/metrics   the registry's Prometheus exposition, byte-identical to
           ``registry.prometheus_text()`` (scrape target)
/healthz   SLO-aware health: ``ok`` | ``degraded`` | ``breached`` as
           JSON; HTTP 200 while serving is viable, 503 on breach
           (load-balancer ready-check semantics)
/statusz   one JSON document from every registered provider (scheduler
           queues, kvcache pages, goodput breakdown, SLO states,
           flight-recorder status)
/debugz    flight-recorder status; ``?dump=1`` writes a postmortem
           bundle (``dump_debug_bundle``) and returns its path
/tracez    request timelines from the span collector: the slowest
           requests (span tree + exclusive critical-path segments) and
           every still-active trace tree (``?trace=<id>`` narrows to
           one trace's tree + attribution)
/varz      the sensor plane's live signal document (``SignalBus.varz``
           via :meth:`DiagServer.attach_signals`): smoothed signal
           values + windowed trends, per-series anomaly state, history
           ring status — the autoscaler's decision inputs
/memz      the HBM memory ledger (``observability.memory``): device
           bytes by class + peak watermarks, per-pool planner verdicts,
           per-request page holders, prefix-cache stats and the last
           OOM — the same document every flight bundle embeds as
           ``memory.json``
/journalz  the black-box incident journal (``observability.journal``):
           armed state, ring occupancy, drop count; ``?tail=N`` adds
           the last N frames — the live view of what a postmortem
           replay would re-execute
/scalez    the autoscaling control plane (``AutoscaleController.
           timeline_snapshot`` via :meth:`DiagServer.attach_autoscale`):
           fleet roles, in-flight drain operations and the versioned
           ScaleRecord decision ring — each record carries the exact
           signal snapshot it decided on
========== ==============================================================

Providers are callables returning JSON-able data, registered with
:meth:`DiagServer.add_statusz` or via the ``attach_*`` conveniences.
Handlers never let a torn provider kill the scrape: a provider raising
turns into an ``{"error": …}`` entry, the rest of the page still
renders.

Usage::

    srv = DiagServer(monitor=slo_monitor)       # port=0: ephemeral
    srv.attach_scheduler(sched)
    port = srv.start()
    ...
    curl http://127.0.0.1:{port}/healthz
    srv.stop()
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse

from .flight import flight_recorder
from .journal import journal
from .memory import memory_ledger
from .registry import get_registry
from .timeline import span_collector

#: health states, ordered by severity (max wins when composing sources)
_HEALTH_ORDER = {"ok": 0, "degraded": 1, "breached": 2}


class DiagServer:
    """See module docstring. ``port=0`` binds an ephemeral port (tests);
    ``registry=None`` uses the process-global one."""

    def __init__(self, registry=None, monitor=None,
                 host: str = "127.0.0.1", port: int = 0,
                 flight=None):
        self.registry = registry if registry is not None else get_registry()
        self.monitor = monitor
        self.flight = flight if flight is not None else flight_recorder
        self._host = host
        self._port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._statusz: Dict[str, Callable[[], object]] = {}
        self._health_fns: Dict[str, Callable[[], str]] = {}
        self._signals = None
        self._federation = None
        self._autoscale = None
        if monitor is not None:
            self.add_health_source("slo", monitor.health)
            self.add_statusz("slo", monitor.states)
        self.add_statusz("flight_recorder", self.flight.snapshot_status)
        # request-timeline summary (slowest-requests table) rides along
        # whenever the span collector is armed; /tracez serves the trees
        self.add_statusz("timelines", span_collector.snapshot_status)
        # HBM ledger summary (class bytes + planner verdicts); the full
        # per-request document is /memz
        self.add_statusz("memory", memory_ledger.statusz)
        # incident-journal ring occupancy; the frame tail is /journalz
        self.add_statusz("journal", journal.snapshot_status)

    # -- wiring -------------------------------------------------------------

    def add_statusz(self, name: str, fn: Callable[[], object]) -> None:
        """Register a /statusz section; ``fn`` returns JSON-able data."""
        self._statusz[name] = fn

    def add_health_source(self, name: str,
                          fn: Callable[[], str]) -> None:
        """Register a health contributor returning ``ok`` | ``degraded``
        | ``breached``; /healthz reports the worst across sources."""
        self._health_fns[name] = fn

    def attach_scheduler(self, sched) -> None:
        """Serving scheduler: queue/slot/page state on /statusz, its
        degraded latch as a health source."""
        self.add_statusz("serving", sched.statusz)
        self.add_health_source(
            "serving", lambda: "breached" if sched.degraded else "ok")

    def attach_router(self, router) -> None:
        """Fleet router: the whole-fleet /statusz view (per-replica
        scheduler + breaker state) and fleet health — 503 only once NO
        replica can take traffic."""
        self.add_statusz("router", router.statusz)
        self.add_health_source("router", router.fleet_health)

    def attach_goodput(self, tracker) -> None:
        self.add_statusz("goodput", tracker.breakdown)

    def attach_signals(self, bus) -> None:
        """Sensor plane: mounts the SignalBus at ``/varz`` and a signal
        summary on /statusz."""
        self._signals = bus
        self.add_statusz("signals", bus.values)

    def attach_kvcache(self, cache) -> None:
        self.add_statusz("kvcache", cache.statusz)

    def attach_autoscale(self, controller) -> None:
        """Autoscaling control plane: mounts the controller's
        ``timeline_snapshot()`` (fleet roles, in-flight operations, the
        versioned ScaleRecord decision ring with the signal snapshots
        each decision saw) at ``/scalez`` and a summary on /statusz."""
        self._autoscale = controller
        self.add_statusz("autoscale", controller.timeline_snapshot)

    def attach_federation(self, hub) -> None:
        """Telemetry federation (:class:`~.federation.FederationHub`):
        /metrics becomes ONE merged exposition doc covering the parent
        and every host mirror under a ``host`` label, and the fleet view
        (mirror freshness, clock offsets, reconcile error) joins
        /statusz. The per-host + fleet-aggregate signals reach /varz by
        also calling ``hub.attach_fleet_signals(bus)`` on the attached
        SignalBus."""
        self._federation = hub
        self.add_statusz("federation", hub.fleet_varz)

    # -- derived health -----------------------------------------------------

    def health(self) -> str:
        worst = "ok"
        for fn in self._health_fns.values():
            try:
                state = fn()
            except Exception:
                state = "degraded"          # a torn source is suspicious
            if _HEALTH_ORDER.get(state, 1) > _HEALTH_ORDER[worst]:
                worst = state
        return worst

    def statusz(self) -> Dict[str, object]:
        out: Dict[str, object] = {"health": self.health()}
        for name, fn in self._statusz.items():
            try:
                out[name] = fn()
            except Exception as e:          # page still renders
                out[name] = {"error": repr(e)}
        return out

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def start(self) -> int:
        """Bind + serve on a daemon thread; returns the bound port."""
        if self._httpd is not None:
            return self.port
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):        # noqa: ARG002 - quiet
                pass

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):                    # noqa: N802 - stdlib API
                try:
                    url = urlparse(self.path)
                    route = url.path.rstrip("/") or "/"
                    if route == "/metrics":
                        # byte-identical to registry.prometheus_text();
                        # with a federation attached, one merged doc
                        # covering every host under a `host` label
                        if server._federation is not None:
                            text = (server._federation
                                    .federated_metrics_text())
                        else:
                            text = server.registry.prometheus_text()
                        self._send(200, text.encode("utf-8"),
                                   ctype="text/plain; version=0.0.4; "
                                         "charset=utf-8")
                    elif route == "/healthz":
                        state = server.health()
                        self._send(503 if state == "breached" else 200,
                                   json.dumps({"status": state}).encode())
                    elif route == "/statusz":
                        self._send(200, json.dumps(
                            server.statusz(), default=str,
                            indent=1).encode())
                    elif route == "/tracez":
                        q = parse_qs(url.query)
                        tid = q.get("trace", [None])[0]
                        if tid:
                            body = {"trace_id": tid,
                                    "timeline":
                                        span_collector.attribute(tid),
                                    "tree": span_collector.tree(tid)}
                        else:
                            body = span_collector.tracez()
                        self._send(200, json.dumps(
                            body, default=str, indent=1).encode())
                    elif route == "/varz":
                        if server._signals is None:
                            self._send(404, json.dumps(
                                {"error": "no signal bus attached"}
                            ).encode())
                        else:
                            self._send(200, json.dumps(
                                server._signals.varz(), default=str,
                                indent=1).encode())
                    elif route == "/scalez":
                        if server._autoscale is None:
                            self._send(404, json.dumps(
                                {"error": "no autoscaler attached"}
                            ).encode())
                        else:
                            self._send(200, json.dumps(
                                server._autoscale.timeline_snapshot(),
                                default=str, indent=1).encode())
                    elif route == "/journalz":
                        q = parse_qs(url.query)
                        body = journal.snapshot_status()
                        tail = q.get("tail", [None])[0]
                        if tail:
                            body["tail"] = journal.tail(int(tail))
                        self._send(200, json.dumps(
                            body, default=str, indent=1).encode())
                    elif route == "/memz":
                        self._send(200, json.dumps(
                            memory_ledger.snapshot(), default=str,
                            indent=1).encode())
                    elif route == "/debugz":
                        q = parse_qs(url.query)
                        if q.get("dump", ["0"])[0] == "1":
                            path = server.flight.dump_debug_bundle(
                                reason="debugz")
                            body = {"dumped": path}
                        else:
                            body = server.flight.snapshot_status()
                        self._send(200, json.dumps(
                            body, default=str).encode())
                    elif route == "/":
                        self._send(200, json.dumps({
                            "endpoints": ["/metrics", "/healthz",
                                          "/statusz", "/debugz",
                                          "/tracez", "/varz", "/memz",
                                          "/journalz", "/scalez"],
                        }).encode())
                    else:
                        self._send(404, b'{"error":"not found"}')
                except BrokenPipeError:          # client went away
                    pass
                except Exception as e:           # never kill the server
                    try:
                        self._send(500, json.dumps(
                            {"error": repr(e)}).encode())
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="paddle-diagserver",
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._httpd = None
        self._thread = None

    def __enter__(self) -> "DiagServer":
        self.start()
        return self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
