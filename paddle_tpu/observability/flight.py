"""Flight recorder: last-N telemetry in memory, tarball on demand.

A production incident needs the moments BEFORE the failure — the events,
spans and metric deltas that a rotating log or a sampled scrape already
dropped. The :class:`FlightRecorder` keeps bounded rings of the most
recent activity and can serialise a *postmortem bundle* at any time:

* ``events`` — every structured event (``emit_event``) while armed, even
  when the JSONL file sink is disabled;
* ``spans`` — profiler ``RecordEvent``/``emit_span`` spans while armed
  (no Profiler capture window required: ``profiler.record`` taps spans
  into the ring whenever ``flight_armed[0]`` is set);
* ``metrics`` — periodic deltas pushed by the SLO monitor (burn rates
  per tick).

Disarmed cost is the zero-overhead contract of the telemetry layer: call
sites check the module-level ``flight_armed`` cell (one list index, no
allocation) exactly like ``runtime.dispatch_armed`` — guarded by
``benchmarks/bench_obs_overhead.py``.

:meth:`FlightRecorder.dump_debug_bundle` writes a tar.gz containing
``metrics.prom`` (the full registry exposition), ``metrics.json`` (its
snapshot), ``events.jsonl`` (ring), ``trace.json`` (ring spans as a
chrome trace that loads in Perfetto), ``slo.json`` (objective states, if
a monitor was attached), ``fleet.json`` (the router's /statusz fleet
view, when a :meth:`attach_router` fleet fronts the engines),
``timelines.json`` (slowest-request span trees + segment attributions
and every active trace, when the timeline collector is armed or a
router is attached), ``history.json`` (the sensor plane's metric
time-series window, smoothed signals and emitted anomalies, when a
:meth:`attach_signals` SignalBus exists), ``memory.json`` (the HBM
memory ledger's class bytes + peaks, per-pool planner verdicts,
per-request page holders and last OOM, when ``observability.memory`` is
armed — an ``oom_<source>`` auto-dump IS the allocation-failure
postmortem) and ``manifest.json`` (reason, counts, config).
:meth:`auto_dump` is the hook the runtime calls on watchdog timeouts,
NaN rollbacks and scheduler degradation — it rate-limits to one bundle
per reason so a crash loop cannot fill the disk.

Bundle schema hygiene (ISSUE 20): every JSON-object member carries a
top-level ``schema_version`` and the manifest maps EVERY member to its
declared version (``schema_versions``) — list/JSONL members
(``slo.json``, ``events.jsonl``, ``journal.jsonl``) are versioned
through the manifest alone, since injecting keys/header lines would
break their consumers. :func:`validate_bundle` is the one shared
structural validator (postmortem replay refuses through it); when the
black-box journal is armed, its versioned frame ring is embedded as
``journal.jsonl`` and ``python -m paddle_tpu.observability.replay``
can re-execute the bundle.
"""

from __future__ import annotations

import io
import json
import os
import tarfile
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

#: the one cell hot paths check before touching the recorder (mutable
#: list so callers read a stable module attribute, not a rebindable name)
flight_armed = [False]

#: declared schema version per bundle member; the manifest's
#: ``schema_versions`` map and :func:`validate_bundle` enforce these.
#: Bump a member's entry when its shape changes incompatibly.
BUNDLE_SCHEMAS = {
    "metrics.prom": 1, "metrics.json": 1, "events.jsonl": 1,
    "trace.json": 1, "slo.json": 1, "fleet.json": 1,
    "timelines.json": 1, "elastic.json": 1, "multihost.json": 1,
    "host_telemetry.json": 1, "autoscale.json": 1, "history.json": 1,
    "memory.json": 1, "journal.jsonl": 1, "manifest.json": 1,
}


class BundleError(Exception):
    """Structural bundle-validation failure; codes mirror
    ``serving.wire.WireError`` (``truncated`` / ``version_skew`` /
    ``schema`` / ``checksum_mismatch``)."""

    def __init__(self, code: str, detail: str = ""):
        self.code = code
        self.detail = detail
        super().__init__(f"bundle {code}: {detail}")

    def as_dict(self) -> Dict[str, str]:
        return {"error": "bundle", "code": self.code,
                "detail": self.detail}


class FlightRecorder:
    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._events: Deque[Dict[str, Any]] = deque(maxlen=capacity)
        self._spans: Deque[tuple] = deque(maxlen=capacity)
        self._metrics: Deque[Dict[str, Any]] = deque(maxlen=capacity)
        self._dump_dir: Optional[str] = None
        self._slo_monitor = None
        self._router = None
        self._signals = None
        self._elastic = None
        self._multihost = None
        self._autoscale = None
        self._auto_dumped: Dict[str, str] = {}   # reason -> bundle path
        self.dumps = 0

    # -- lifecycle ----------------------------------------------------------

    @property
    def armed(self) -> bool:
        return flight_armed[0]

    def arm(self, capacity: Optional[int] = None,
            dump_dir: Optional[str] = None) -> "FlightRecorder":
        """Start recording. ``dump_dir`` enables :meth:`auto_dump` (the
        watchdog/NaN/degrade hooks are no-ops without it)."""
        with self._lock:
            if capacity is not None and capacity != self._capacity:
                self._capacity = capacity
                self._events = deque(self._events, maxlen=capacity)
                self._spans = deque(self._spans, maxlen=capacity)
                self._metrics = deque(self._metrics, maxlen=capacity)
            if dump_dir is not None:
                self._dump_dir = dump_dir
            flight_armed[0] = True
        return self

    def disarm(self) -> None:
        flight_armed[0] = False

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._spans.clear()
            self._metrics.clear()
            self._auto_dumped.clear()

    def attach_slo_monitor(self, monitor) -> None:
        """Objective states land in ``slo.json`` of every bundle."""
        self._slo_monitor = monitor

    def attach_router(self, router) -> None:
        """Fleet router: its ``statusz()`` fleet view lands in
        ``fleet.json`` of every bundle, so a ``replica_ejected_<id>``
        auto-dump is self-contained — the breaker states, per-replica
        queues and parked/probe bookkeeping at the moment of ejection
        travel with the events and spans (``FleetRouter.__init__`` wires
        this; a later fleet replaces the earlier one)."""
        self._router = router

    def attach_elastic(self, controller) -> None:
        """Elastic resize controller: its ``timeline_snapshot()`` — the
        chip-loss → checkpoint → re-shard → rejoin state machine per
        resize, with the checkpointed flight state — lands in
        ``elastic.json`` of every bundle, so a chip-loss postmortem
        embeds the resize timeline (``ElasticServingController.__init__``
        wires this; a later controller replaces the earlier one)."""
        self._elastic = controller

    def attach_multihost(self, router) -> None:
        """Multi-host fleet: its ``multihost_snapshot()`` — per-host
        endpoint health, transport stats and the page-migration timeline
        (bytes/pages/latency per transfer) — lands in ``multihost.json``
        of every bundle, so a ``host_lost_<id>`` auto-dump embeds the
        migration record (``HostFleetRouter.__init__`` wires this; a
        later fleet replaces the earlier one)."""
        self._multihost = router

    def attach_autoscale(self, controller) -> None:
        """Autoscaling control plane: its ``timeline_snapshot()`` — the
        fleet's roles, in-flight drain operations and the versioned
        ``ScaleRecord`` decision ring — lands in ``autoscale.json`` of
        every bundle, so a scaling postmortem replays the exact signal
        snapshots each decision saw (``AutoscaleController.__init__``
        wires this; a later controller replaces the earlier one)."""
        self._autoscale = controller

    def attach_signals(self, bus) -> None:
        """Sensor plane: the SignalBus's ``history_snapshot()`` — metric
        time series, smoothed signals and emitted anomalies over the
        trailing window — lands in ``history.json`` of every bundle, so
        an ejection postmortem shows the minutes BEFORE the ejection
        (``SignalBus.__init__`` wires this; a later bus replaces the
        earlier one)."""
        self._signals = bus

    # -- recording (armed-only; callers gate on flight_armed[0]) ------------

    def note_event(self, record: Dict[str, Any]) -> None:
        """Called by ``events.EventLog.emit`` with the already-built
        record dict (shared, not copied — emit never mutates it after).

        The lock matters even though ``deque.append`` is atomic:
        :meth:`arm` REBINDS the rings when it resizes them, and an
        unlocked append can land in the abandoned deque — a recorded
        event silently missing from the next debug bundle (tpu-lint
        lock-unguarded-write)."""
        with self._lock:
            self._events.append(record)

    def note_span(self, span: tuple) -> None:
        """Called by ``profiler.record`` with a ``HostSpan`` tuple."""
        with self._lock:
            self._spans.append(span)

    def note_spans(self, spans) -> None:
        """Batch variant (``record.emit_spans``): one lock round for an
        engine step's whole span set."""
        with self._lock:
            self._spans.extend(spans)

    def note_metrics(self, label: str, payload: Dict[str, Any]) -> None:
        with self._lock:
            self._metrics.append({"label": label, **payload})

    def recent_events(self, n: int = 32) -> List[Dict[str, Any]]:
        """Last ``n`` ring events, oldest first — the telemetry frame's
        event tail (:func:`~.federation.collect_telemetry`)."""
        with self._lock:
            return list(self._events)[-n:]

    # -- dumping ------------------------------------------------------------

    def snapshot_status(self) -> Dict[str, Any]:
        with self._lock:
            return {"armed": flight_armed[0], "capacity": self._capacity,
                    "events": len(self._events), "spans": len(self._spans),
                    "metric_samples": len(self._metrics),
                    "dumps": self.dumps, "dump_dir": self._dump_dir}

    def _chrome_trace(self, spans: List[tuple]) -> Dict[str, Any]:
        """Ring spans as chrome://tracing JSON (same shape as
        ``profiler.export_chrome_tracing``, minus flow events — a ring is
        a window, so chains may be torn anyway)."""
        events = []
        for sp in spans:
            ev = {"name": sp.name, "cat": sp.event_type, "ph": "X",
                  "ts": sp.start_ns / 1000.0,
                  "dur": (sp.end_ns - sp.start_ns) / 1000.0,
                  "pid": sp.pid, "tid": sp.tid}
            args = dict(sp.args or {})
            if sp.trace_id:
                args.setdefault("trace_id", sp.trace_id)
            if args:
                ev["args"] = args
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump_debug_bundle(self, path: Optional[str] = None,
                          reason: str = "manual") -> str:
        """Write the postmortem tarball; returns its path. ``path`` may
        be a target file or a directory (a timestamped name is chosen
        inside); defaults to the armed ``dump_dir`` (cwd as a last
        resort)."""
        from .registry import get_registry

        with self._lock:
            events = list(self._events)
            spans = list(self._spans)
            metric_samples = list(self._metrics)
            seq = self.dumps        # claimed under the lock: concurrent
            self.dumps += 1         # dumps get distinct bundle names
        target = path if path is not None else (self._dump_dir or ".")
        if os.path.isdir(target) or not target.endswith((".tar.gz", ".tgz")):
            os.makedirs(target, exist_ok=True)
            stamp = time.strftime("%Y%m%d-%H%M%S")
            target = os.path.join(
                target, f"paddle_debug_{reason}_{stamp}_{os.getpid()}"
                        f"_{seq}.tar.gz")
        else:
            d = os.path.dirname(target)
            if d:
                os.makedirs(d, exist_ok=True)
        reg = get_registry()
        members: Dict[str, bytes] = {}
        schema_versions: Dict[str, int] = {}

        def _put_json(name: str, obj) -> None:
            # every JSON-object member declares its schema_version
            # inline; list members (slo.json) are versioned through the
            # manifest's schema_versions map only — their consumers
            # index them positionally and a header entry would break
            # them
            if isinstance(obj, dict):
                obj = dict(obj)
                obj.setdefault("schema_version",
                               BUNDLE_SCHEMAS.get(name, 1))
                schema_versions[name] = int(obj["schema_version"])
            else:
                schema_versions[name] = BUNDLE_SCHEMAS.get(name, 1)
            members[name] = json.dumps(
                obj, default=str, indent=1).encode()

        members["metrics.prom"] = reg.prometheus_text().encode()
        schema_versions["metrics.prom"] = BUNDLE_SCHEMAS["metrics.prom"]
        _put_json("metrics.json", reg.snapshot())
        members["events.jsonl"] = "".join(
            json.dumps(e, default=str, separators=(",", ":")) + "\n"
            for e in events).encode()
        schema_versions["events.jsonl"] = BUNDLE_SCHEMAS["events.jsonl"]
        _put_json("trace.json", self._chrome_trace(spans))
        if self._slo_monitor is not None:
            _put_json("slo.json", self._slo_monitor.states())
        if self._router is not None:
            # the fleet view at dump time; a torn router (this bundle may
            # BE the ejection postmortem) must not lose the whole bundle
            try:
                fleet = self._router.statusz()
            except Exception as e:
                fleet = {"error": repr(e)}
            _put_json("fleet.json", fleet)
        from .timeline import span_collector, timeline_armed
        if timeline_armed[0] or self._router is not None:
            # request timelines: the slowest-request exemplars (tree +
            # segments) plus every still-active trace tree — the
            # "where was each request" half of an ejection postmortem
            try:
                tz = span_collector.tracez()
            except Exception as e:
                tz = {"error": repr(e)}
            _put_json("timelines.json", tz)
        if self._elastic is not None:
            # the resize state machine (chip losses, per-phase timeline,
            # checkpointed flight state) — a torn controller must not
            # lose the bundle
            try:
                el = self._elastic.timeline_snapshot()
            except Exception as e:
                el = {"error": repr(e)}
            _put_json("elastic.json", el)
        if self._multihost is not None:
            # the multi-host fleet view: endpoint health + the page-
            # migration timeline (a torn fleet must not lose the bundle)
            try:
                mh = self._multihost.multihost_snapshot()
            except Exception as e:
                mh = {"error": repr(e)}
            _put_json("multihost.json", mh)
            hub = getattr(self._multihost, "federation", None)
            if hub is not None:
                # every host's last-known telemetry mirror — for a
                # host_lost bundle this is the dead host's final minutes,
                # frozen at mark_lost (a torn hub must not lose the
                # bundle)
                try:
                    tel = hub.snapshot()
                except Exception as e:
                    tel = {"error": repr(e)}
                _put_json("host_telemetry.json", tel)
        if self._autoscale is not None:
            # the scaling decision ring (records + the signal snapshots
            # they decided on) — a torn controller must not lose the
            # bundle
            try:
                sc = self._autoscale.timeline_snapshot()
            except Exception as e:
                sc = {"error": repr(e)}
            _put_json("autoscale.json", sc)
        if self._signals is not None:
            # the sensor plane's bounded window: series, signal trends
            # and anomalies leading up to this dump (a torn bus must not
            # lose the bundle)
            try:
                hist = self._signals.history_snapshot()
            except Exception as e:
                hist = {"error": repr(e)}
            _put_json("history.json", hist)
        from .memory import memory_armed, memory_ledger
        if memory_armed[0]:
            # the memory ledger's books: class bytes + peaks, per-pool
            # planner verdicts, per-request page holders and the last
            # OOM — an allocation failure's postmortem is the bundle
            # whose reason is ``oom_<source>``
            try:
                mem = memory_ledger.snapshot()
            except Exception as e:
                mem = {"error": repr(e)}
            _put_json("memory.json", mem)
        from .journal import journal, journal_armed
        if journal_armed[0]:
            # the black-box journal: the run's nondeterminism frontier,
            # versioned + crc-per-line — this member makes the bundle a
            # runnable incident (observability/replay.py)
            members["journal.jsonl"] = journal.encode()
            schema_versions["journal.jsonl"] = \
                BUNDLE_SCHEMAS["journal.jsonl"]
        schema_versions["manifest.json"] = BUNDLE_SCHEMAS["manifest.json"]
        members["manifest.json"] = json.dumps({
            "schema_version": BUNDLE_SCHEMAS["manifest.json"],
            "reason": reason, "pid": os.getpid(),
            "capacity": self._capacity, "events": len(events),
            "spans": len(spans), "metric_samples": len(metric_samples),
            "metric_deltas": metric_samples,
            "schema_versions": schema_versions,
        }, default=str, indent=1).encode()
        with tarfile.open(target, "w:gz") as tar:
            for name, data in members.items():
                info = tarfile.TarInfo(name)
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))
        from .events import emit_event
        emit_event("debug_dump", reason=reason, path=target,
                   events=len(events), spans=len(spans))
        return target

    def auto_dump(self, reason: str) -> Optional[str]:
        """Postmortem hook for the runtime (watchdog timeout, NaN
        rollback, scheduler degrade): dump once per distinct reason,
        only when armed with a dump_dir; never raises into the caller's
        failure path."""
        if not flight_armed[0] or self._dump_dir is None:
            return None
        with self._lock:
            if reason in self._auto_dumped:
                return None
            self._auto_dumped[reason] = ""   # reserve before the slow dump
        try:
            p = self.dump_debug_bundle(reason=reason)
        except Exception:
            return None
        with self._lock:
            self._auto_dumped[reason] = p
        return p


#: the process-global recorder the runtime hooks dump into
flight_recorder = FlightRecorder()


def validate_bundle(path: str) -> Dict[str, Any]:
    """THE shared structural validator for debug bundles: every member
    accounted for in the manifest's ``schema_versions`` map, every
    declared version one this tree speaks (:data:`BUNDLE_SCHEMAS`),
    every JSON member parseable with its inline ``schema_version``
    agreeing with the manifest, and an embedded ``journal.jsonl``
    passing its own versioned/checksummed decode. Raises
    :class:`BundleError` (or ``journal.JournalError`` for a torn
    journal member); returns ``{"path", "members", "manifest",
    "journal"}`` with ``journal`` a ``DecodedJournal`` or None."""
    members: Dict[str, bytes] = {}
    try:
        with tarfile.open(path, "r:gz") as tar:
            for info in tar.getmembers():
                f = tar.extractfile(info)
                members[info.name] = f.read() if f is not None else b""
    except (OSError, tarfile.TarError) as e:
        raise BundleError("truncated", f"unreadable tarball: {e!r}")
    if "manifest.json" not in members:
        raise BundleError("schema", "bundle has no manifest.json")
    try:
        manifest = json.loads(members["manifest.json"])
    except Exception:
        raise BundleError("schema", "manifest.json is not JSON")
    svs = manifest.get("schema_versions")
    if not isinstance(svs, dict):
        raise BundleError(
            "schema", "manifest declares no schema_versions map "
                      "(pre-ISSUE-20 bundle?)")
    for name in members:
        if name not in svs:
            raise BundleError(
                "schema",
                f"member {name!r} missing from manifest schema_versions")
        declared = BUNDLE_SCHEMAS.get(name)
        if declared is not None and int(svs[name]) != declared:
            raise BundleError(
                "version_skew",
                f"{name}: bundle declares schema_version {svs[name]}, "
                f"this tree speaks {declared}")
    for name, data in members.items():
        if name.endswith(".json"):
            try:
                obj = json.loads(data)
            except Exception:
                raise BundleError("schema", f"{name} is not valid JSON")
            if isinstance(obj, dict) \
                    and obj.get("schema_version") != int(svs[name]):
                raise BundleError(
                    "schema",
                    f"{name}: inline schema_version "
                    f"{obj.get('schema_version')!r} != manifest "
                    f"{svs[name]}")
        elif name == "events.jsonl":
            lines = data.decode("utf-8", errors="replace").splitlines()
            for i, line in enumerate(lines):
                if not line:
                    continue
                try:
                    json.loads(line)
                except Exception:
                    raise BundleError(
                        "schema", f"events.jsonl line {i} is not JSON")
    decoded = None
    if "journal.jsonl" in members:
        from .journal import decode_journal
        decoded = decode_journal(members["journal.jsonl"])
    return {"path": path, "members": members, "manifest": manifest,
            "journal": decoded}
