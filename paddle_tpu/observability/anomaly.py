"""Robust anomaly detection over metric time series.

The stack's signals are heavy-tailed (step timings, queue depths, burn
rates), so mean/stddev detectors page on every compile stall. Everything
here is median/MAD based — the same robust z-score
``goodput.StragglerDetector`` kept private until this module generalised
it — plus a CUSUM change-point detector for the slow drifts a windowed
z-score never sees (each drifted sample looks ordinary against the
window that drifted with it; the *accumulated* deviation does not).

Detectors are pure sample-driven state machines:

* :class:`RobustZScoreDetector` — score each new sample against the
  PREVIOUS window's median/MAD (a level shift must not dilute its own
  baseline); fires on ``|z| > z_threshold``. Catches level shifts and
  spikes.
* :class:`CusumDetector` — freeze a baseline median/MAD over the warmup
  window, then accumulate one-sided standardized deviations
  (``g+ = max(0, g+ + z - k)``; symmetrically ``g-``); fire when either
  side exceeds ``h`` and re-baseline so a sustained shift fires ONCE.
  Catches slow drifts a z-score window absorbs.

:class:`AnomalyMonitor` runs named series through both, with a
**per-series cooldown** on the injected timeline so a sustained shift
pages once, emitting ``anomaly`` JSONL events and the
``paddle_anomaly_*`` families declared in :mod:`.catalog`.

Time discipline: this module NEVER reads a clock — callers pass the
sample timestamp in (the :class:`~.signals.SignalBus` passes its
injected clock's now), so detection is byte-deterministic under fake
clocks: the same series always yields the same events
(lint-enforced alongside ``slo.py``/``goodput.py`` by tpu-lint's
``layer-wall-clock`` rule).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence

from .events import emit_event
from .registry import get_registry

#: MAD -> stddev-equivalent scale for a normal distribution
MAD_SCALE = 1.4826


def _mid(ordered: Sequence[float]) -> float:
    """Median of an ALREADY-SORTED non-empty sequence."""
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return ordered[mid]
    return 0.5 * (ordered[mid - 1] + ordered[mid])


def median(values: Sequence[float]) -> float:
    """Median of a non-empty sequence (sorts a copy)."""
    return _mid(sorted(values))


def mad(values: Sequence[float], center: Optional[float] = None) -> float:
    """Median absolute deviation around ``center`` (default: the
    median)."""
    med = median(values) if center is None else center
    return _mid(sorted(abs(v - med) for v in values))


def robust_scale(values: Sequence[float],
                 center: Optional[float] = None) -> float:
    """MAD-derived scale with the degenerate-window fallback the
    straggler detector established: a perfectly uniform window (MAD 0)
    falls back to a fraction of the median so a genuine outlier still
    scores instead of dividing by zero."""
    med = median(values) if center is None else center
    m = mad(values, center=med)
    return MAD_SCALE * m if m > 0 else max(abs(med) * 0.05, 1e-12)


def robust_zscore(value: float, window: Sequence[float],
                  min_samples: int = 2) -> float:
    """Robust z of ``value`` against ``window`` (0 while the window is
    still warming up). THE shared primitive: ``goodput.
    StragglerDetector`` delegates here, so straggler flagging and series
    anomaly detection share one definition of "how unusual"."""
    if len(window) < min_samples:
        return 0.0
    ordered = sorted(window)
    med = _mid(ordered)
    m = _mid(sorted(abs(v - med) for v in ordered))
    scale = MAD_SCALE * m if m > 0 else max(abs(med) * 0.05, 1e-12)
    return (value - med) / scale


class RobustZScoreDetector:
    """Level-shift/spike detector; see module docstring. ``observe``
    returns a firing dict (score + direction) or None, and ALWAYS admits
    the sample afterwards — score-then-admit keeps an outlier from
    diluting the baseline it is judged against."""

    kind = "zscore"

    def __init__(self, window: int = 64, z_threshold: float = 6.0,
                 min_samples: int = 8):
        self.window = int(window)
        self.z_threshold = float(z_threshold)
        self.min_samples = max(2, int(min_samples))
        self._samples: Deque[float] = deque(maxlen=self.window)
        self.last_score = 0.0

    def observe(self, value: float) -> Optional[Dict[str, Any]]:
        value = float(value)
        window = self._samples
        if len(window) >= self.min_samples:
            ordered = sorted(window)
            med = _mid(ordered)
            m = _mid(sorted(abs(v - med) for v in ordered))
            if m == 0 and med == 0:
                # constant-ZERO window (an idle queue, a parked-count
                # series): no scale information at all — the straggler
                # fallback (a fraction of the median) degenerates to
                # ~1e-12 and the first real sample would score z~1e11.
                # The first activity on an idle series is a level
                # START, not an anomaly: admit it and let the window
                # build real statistics.
                z = 0.0
            else:
                scale = MAD_SCALE * m if m > 0 \
                    else max(abs(med) * 0.05, 1e-12)
                z = (value - med) / scale
        else:
            z = 0.0
        self.last_score = z
        window.append(value)
        if abs(z) <= self.z_threshold:
            return None
        return {"score": round(z, 4),
                "direction": "up" if z > 0 else "down"}


class CusumDetector:
    """Slow-drift change-point detector; see module docstring.

    ``k`` is the slack (in robust sigmas) ordinary noise may wander
    without charging the accumulator; ``h`` is the alarm threshold on
    the accumulated excess. After an alarm the detector re-baselines
    (fresh warmup window) so a series that settled at a new level does
    not page forever.
    """

    kind = "cusum"

    def __init__(self, k: float = 0.5, h: float = 8.0,
                 baseline: int = 24):
        self.k = float(k)
        self.h = float(h)
        self.baseline = max(4, int(baseline))
        self._warmup: List[float] = []
        self._center: Optional[float] = None
        self._scale = 1.0
        self.g_pos = 0.0
        self.g_neg = 0.0
        self.last_score = 0.0

    def observe(self, value: float) -> Optional[Dict[str, Any]]:
        value = float(value)
        if self._center is None:
            self._warmup.append(value)
            if len(self._warmup) >= self.baseline:
                med = median(self._warmup)
                if med == 0 and mad(self._warmup, center=med) == 0:
                    # constant-zero warmup: no scale to standardize by
                    # (same idle-series hazard as the z-score detector)
                    # — slide the window until real signal appears
                    self._warmup.pop(0)
                else:
                    self._center = med
                    self._scale = robust_scale(self._warmup, center=med)
                    self._warmup = []
            self.last_score = 0.0
            return None
        z = (value - self._center) / self._scale
        self.g_pos = max(0.0, self.g_pos + z - self.k)
        self.g_neg = max(0.0, self.g_neg - z - self.k)
        self.last_score = max(self.g_pos, self.g_neg)
        if self.g_pos <= self.h and self.g_neg <= self.h:
            return None
        fired = {"score": round(self.last_score, 4),
                 "direction": "up" if self.g_pos > self.g_neg
                 else "down"}
        # re-baseline: the shift is now the new normal — collect a fresh
        # warmup window instead of alarming on every subsequent sample
        self._center = None
        self._warmup = []
        self.g_pos = self.g_neg = 0.0
        return fired


def default_detectors() -> List[Any]:
    """One of each: the level-shift z-score and the drift CUSUM."""
    return [RobustZScoreDetector(), CusumDetector()]


class _Watch:
    __slots__ = ("name", "detectors", "cooldown_s", "last_fire_t",
                 "fired", "suppressed", "samples")

    def __init__(self, name: str, detectors: List[Any],
                 cooldown_s: float):
        self.name = name
        self.detectors = detectors
        self.cooldown_s = float(cooldown_s)
        self.last_fire_t: Optional[float] = None
        self.fired = 0
        self.suppressed = 0
        self.samples = 0


class AnomalyMonitor:
    """Cooldown + emission layer over per-series detectors (see module
    docstring). Thread-safe (the DiagServer scrape thread reads
    ``snapshot()`` while the serving loop observes)."""

    def __init__(self, cooldown_s: float = 60.0,
                 detector_factory=default_detectors,
                 recent_limit: int = 64):
        self._lock = threading.Lock()
        self._watches: Dict[str, _Watch] = {}
        self._cooldown_s = float(cooldown_s)
        self._factory = detector_factory
        self._recent: Deque[Dict[str, Any]] = deque(maxlen=recent_limit)
        reg = get_registry()
        self._c_events = reg.counter(
            "paddle_anomaly_events_total",
            "anomaly detections per series and detector (post-cooldown)",
            labels=("series", "detector"))
        self._g_score = reg.gauge(
            "paddle_anomaly_score",
            "latest robust anomaly score per watched series "
            "(max over detectors)", labels=("series",))

    def watch(self, series: str, detectors: Optional[List[Any]] = None,
              cooldown_s: Optional[float] = None) -> None:
        """Register ``series`` with explicit detectors/cooldown;
        ``observe`` auto-registers unknown series with the defaults."""
        with self._lock:
            self._watches[series] = _Watch(
                series, detectors if detectors is not None
                else self._factory(),
                self._cooldown_s if cooldown_s is None else cooldown_s)

    def observe(self, series: str, value: float, now: float
                ) -> List[Dict[str, Any]]:
        """Run one sample of ``series`` (taken at injected time ``now``)
        through its detectors. Returns the anomaly records EMITTED this
        sample (cooldown-suppressed detections return nothing but are
        counted in ``snapshot()``)."""
        with self._lock:
            w = self._watches.get(series)
            if w is None:
                w = self._watches[series] = _Watch(
                    series, self._factory(), self._cooldown_s)
            w.samples += 1
            fired: List[Dict[str, Any]] = []
            score = 0.0
            for det in w.detectors:
                hit = det.observe(value)
                score = max(score, abs(det.last_score))
                if hit is None:
                    continue
                if (w.last_fire_t is not None
                        and now - w.last_fire_t < w.cooldown_s):
                    w.suppressed += 1
                    continue
                record = {"series": series, "detector": det.kind,
                          "t": round(float(now), 6),
                          "value": round(float(value), 6), **hit}
                fired.append(record)
            if fired:
                # one cooldown window per SERIES: both detectors firing
                # on the same shift page together, then go quiet
                w.last_fire_t = now
                w.fired += len(fired)
                self._recent.extend(fired)
        self._g_score.set(score, series=series)
        for record in fired:
            self._c_events.inc(series=series, detector=record["detector"])
            emit_event("anomaly", **record)
        return fired

    # -- reading ------------------------------------------------------------

    def recent(self) -> List[Dict[str, Any]]:
        """Emitted anomaly records, oldest first (bounded ring)."""
        with self._lock:
            return list(self._recent)

    def snapshot(self) -> Dict[str, Any]:
        """Per-series state for /varz and ``history.json``."""
        with self._lock:
            return {w.name: {
                "samples": w.samples,
                "fired": w.fired,
                "suppressed": w.suppressed,
                "cooldown_s": w.cooldown_s,
                "last_fire_t": w.last_fire_t,
                "score": round(max((abs(d.last_score)
                                    for d in w.detectors), default=0.0),
                               4),
            } for w in self._watches.values()}
