"""Prometheus exposition-format line builders — the ONE place bucket/
quantile/counter text is assembled.

Every metrics sink in the tree (``serving.metrics``, ``resilience.metrics``,
the registry's own metrics) delegates here; ``tests/test_observability.py``
lints that no other module grows a private ``_bucket{le=`` formatter again.
The emitted shapes are byte-compatible with what the serving and resilience
sinks produced before the unification (PR 1/PR 2), so existing scrape
configs and tests keep working.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

from ..core.histogram import DEFAULT_QUANTILES, Histogram

#: metric types valid in exposition format TYPE lines
VALID_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")

#: exposition comment-line prefixes (parsers outside this module key on
#: these names instead of growing their own "# TYPE " literals)
TYPE_PREFIX = "# TYPE "
HELP_PREFIX = "# HELP "


def type_line(family: str, kind: str) -> str:
    """``# TYPE <family> <kind>`` — the one emitter for TYPE lines."""
    return f"{TYPE_PREFIX}{family} {kind}"


def help_line(family: str, text: str) -> str:
    """``# HELP <family> <text>`` — the one emitter for HELP lines."""
    return f"{HELP_PREFIX}{family} {text}"


def escape_label_value(v: object) -> str:
    """Escape a label value per the exposition format spec."""
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def label_str(labels: Optional[Mapping[str, object]]) -> str:
    """``{k="v",...}`` (keys in insertion order), or '' for no labels."""
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in labels.items())
    return "{" + inner + "}"


def sample_line(name: str, labels: Optional[Mapping[str, object]],
                value: float) -> str:
    return f"{name}{label_str(labels)} {value:g}"


def counter_lines(metric: str, value: Optional[float] = None,
                  series: Optional[Iterable[Tuple[Mapping[str, object],
                                                  float]]] = None,
                  help: Optional[str] = None) -> List[str]:
    """One counter family: TYPE line + either an unlabeled sample or a
    labeled series (never both — an unlabeled grand-total sibling would
    double-count ``sum()`` queries over the family)."""
    lines = []
    if help is not None:
        lines.append(f"# HELP {metric} {help}")
    lines.append(f"# TYPE {metric} counter")
    if series is not None:
        for labels, v in series:
            lines.append(sample_line(metric, labels, v))
    else:
        lines.append(sample_line(metric, None, value or 0.0))
    return lines


def gauge_lines(metric: str, value: Optional[float] = None,
                series: Optional[Iterable[Tuple[Mapping[str, object],
                                                float]]] = None,
                help: Optional[str] = None) -> List[str]:
    lines = []
    if help is not None:
        lines.append(f"# HELP {metric} {help}")
    lines.append(f"# TYPE {metric} gauge")
    if series is not None:
        for labels, v in series:
            lines.append(sample_line(metric, labels, v))
    else:
        lines.append(sample_line(metric, None, value or 0.0))
    return lines


def histogram_lines(metric: str, h: Histogram,
                    help: Optional[str] = None,
                    quantiles: Optional[Sequence[float]] = None,
                    labels: Optional[Mapping[str, object]] = None,
                    include_type: bool = True) -> List[str]:
    """One histogram family: cumulative ``_bucket`` samples, ``_sum``,
    ``_count``; optionally a *sibling* ``<metric>_quantile`` gauge family
    with exact percentiles (mixing quantile samples into a histogram
    family is invalid exposition format, so it gets its own TYPE).
    ``include_type=False`` for the 2nd+ label-set of one family — a
    family may be TYPE'd only once per document."""
    lines = []
    if help is not None:
        lines.append(f"# HELP {metric} {help}")
    if include_type:
        lines.append(f"# TYPE {metric} histogram")
    base = dict(labels) if labels else {}
    acc = 0
    for bound, n in zip(h.bounds, h.bucket_counts):
        acc += n
        lines.append(sample_line(f"{metric}_bucket",
                                 {**base, "le": f"{bound:g}"}, acc))
    lines.append(sample_line(f"{metric}_bucket", {**base, "le": "+Inf"},
                             h.count))
    lines.append(sample_line(f"{metric}_sum", base or None, h.sum))
    lines.append(sample_line(f"{metric}_count", base or None, h.count))
    if quantiles:
        lines.append(f"# TYPE {metric}_quantile gauge")
        for q in quantiles:
            lines.append(sample_line(
                f"{metric}_quantile", {**base, "quantile": f"{q:g}"},
                h.percentile(q)))
    return lines


def validate_exposition_text(text: str) -> None:
    """Line-by-line exposition-format validator (used by tests and
    available to callers): TYPE lines name a valid type, sample lines
    parse as ``name{labels} value``, histogram buckets are cumulative,
    and no family name is TYPE'd twice."""
    import re

    sample_re = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
        r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
        r' (-?[0-9.eE+\-naif]+)$')
    typed: Dict[str, str] = {}
    bucket_acc: Dict[str, float] = {}
    for ln, line in enumerate(text.splitlines(), 1):
        if not line:
            raise ValueError(f"line {ln}: empty line inside exposition text")
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4 or parts[3] not in VALID_TYPES:
                raise ValueError(f"line {ln}: bad TYPE line {line!r}")
            if parts[2] in typed:
                raise ValueError(
                    f"line {ln}: family {parts[2]} TYPE'd twice")
            typed[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = sample_re.match(line)
        if not m:
            raise ValueError(f"line {ln}: unparseable sample {line!r}")
        name = m.group(1)
        float(m.group(4))  # value parses
        if name.endswith("_bucket"):
            fam = name[:-len("_bucket")]
            if typed.get(fam) != "histogram":
                raise ValueError(
                    f"line {ln}: bucket sample for non-histogram {fam}")
            v = float(m.group(4))
            if v < bucket_acc.get(fam + m.group(0).split("le=")[0], 0.0):
                raise ValueError(f"line {ln}: non-cumulative bucket {line!r}")
            bucket_acc[fam + m.group(0).split("le=")[0]] = v
