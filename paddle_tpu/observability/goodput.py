"""Goodput accounting + straggler detection for training runs.

"99% uptime" means little for a training job that spends half its wall
clock replaying steps after rollbacks. **Goodput** is the fraction of
run wall-clock spent making NEW forward progress; everything else is
attributed to a named loss bucket:

==================  ======================================================
bucket              attributed by ``ResilientTrainer``
==================  ======================================================
``productive``      first-time successful step execution
``retry``           failed step attempts + their backoff sleeps
``rollback_replay`` checkpoint restores after NaN/rollback, steps
                    re-executed below the previous high-water mark, and
                    step time wasted on attempts whose loss came back
                    non-finite
``checkpoint_stall``blocking portions of durable saves (sync saves,
                    async-save dispatch, harvest waits)
``restart``         auto-resume restore at run start
``untracked``       loop bookkeeping the trainer does not wrap (computed
                    as ``total - sum(buckets)``, so the breakdown always
                    sums to the run's wall clock exactly)
==================  ======================================================

This module is PURE accounting: callers measure durations with their own
clocks and feed seconds in, so the math is deterministic under fake
clocks and the lint rule (no wall-clock reads in ``slo.py``/
``goodput.py``) holds by construction.

:class:`StragglerDetector` flags per-step timing outliers with a rolling
median/MAD z-score (robust to the heavy tail that makes mean/stddev
useless on step timings); the trainer counts flags into
``paddle_stragglers_total`` and logs a ``straggler`` event carrying the
step and its z-score.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, Optional

from .anomaly import robust_zscore
from .registry import get_registry

#: attribution buckets, in reporting order
BUCKETS = ("productive", "retry", "rollback_replay", "checkpoint_stall",
           "restart")


class GoodputTracker:
    """Accumulates seconds into buckets; see module docstring."""

    def __init__(self):
        self._buckets: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self.total_s: Optional[float] = None
        self._g_ratio = get_registry().gauge(
            "paddle_goodput_ratio",
            "fraction of run wall-clock spent on new forward progress")

    def note(self, bucket: str, seconds: float) -> None:
        if bucket not in self._buckets:
            raise KeyError(f"unknown goodput bucket {bucket!r}; "
                           f"expected one of {BUCKETS}")
        if seconds > 0:
            self._buckets[bucket] += seconds

    def get(self, bucket: str) -> float:
        return self._buckets[bucket]

    def finalize(self, total_s: float) -> Dict[str, float]:
        """Close the run at ``total_s`` wall seconds and publish the
        goodput gauge. Attribution drift (a bucket measured inside
        another's span) cannot create time: ``untracked`` absorbs the
        exact remainder, clamped at 0."""
        self.total_s = float(total_s)
        return self.breakdown()

    @property
    def goodput_ratio(self) -> float:
        total = self.total_s or sum(self._buckets.values())
        if total <= 0:
            return 0.0
        return min(1.0, self._buckets["productive"] / total)

    def breakdown(self) -> Dict[str, float]:
        total = self.total_s if self.total_s is not None \
            else sum(self._buckets.values())
        out: Dict[str, float] = {"total_s": round(total, 6)}
        for b in BUCKETS:
            out[f"{b}_s"] = round(self._buckets[b], 6)
        out["untracked_s"] = round(
            max(0.0, total - sum(self._buckets.values())), 6)
        out["goodput_ratio"] = round(self.goodput_ratio, 6)
        self._g_ratio.set(out["goodput_ratio"])
        return out


class StragglerDetector:
    """Rolling median/MAD z-score over per-step timings.

    ``observe(seconds)`` returns the robust z-score of the new sample
    against the PREVIOUS window (a straggler must not dilute its own
    baseline); a sample is flagged when ``z > z_threshold`` once at
    least ``min_samples`` are in the window. The math is the shared
    :func:`~paddle_tpu.observability.anomaly.robust_zscore` primitive
    (this class used to keep a private copy; the anomaly plane
    generalised it), including its MAD-of-zero fallback: perfectly
    uniform timings fall back to a fraction of the median so a single
    slow step still flags instead of dividing by zero.
    """

    def __init__(self, window: int = 32, z_threshold: float = 4.0,
                 min_samples: int = 8):
        self.window = window
        self.z_threshold = float(z_threshold)
        self.min_samples = max(2, int(min_samples))
        self._samples: Deque[float] = deque(maxlen=window)
        self.flagged = 0
        self._c_stragglers = get_registry().counter(
            "paddle_stragglers_total",
            "per-step timing outliers (rolling MAD z-score)",
            labels=("source",))

    def zscore(self, value: float) -> float:
        """Robust z of ``value`` against the current window (0 when the
        window is still warming up)."""
        return robust_zscore(value, self._samples, self.min_samples)

    def observe(self, seconds: float, source: str = "train_step") -> float:
        """Score ``seconds`` against the window, THEN admit it; flags
        count into ``paddle_stragglers_total{source=…}``."""
        z = self.zscore(float(seconds))
        if z > self.z_threshold:
            self.flagged += 1
            self._c_stragglers.inc(source=source)
        self._samples.append(float(seconds))
        return z
