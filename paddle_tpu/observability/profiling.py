"""Continuous profiling: hot producer→consumer dispatch chains.

The always-on dispatch telemetry (``observability.runtime``) counts
every eager op through ``core.dispatch.apply`` and samples 1/64
durations — a free profile of exactly which op sequences dominate a
workload, but nothing consumed it for optimisation. This module folds
it into the artifact ROADMAP item 2's telemetry-guided fusion pass
needs: ranked **producer→consumer chains** (op A's output feeding op B,
observed as consecutive dispatches on one thread), each scored by
observed frequency × sampled mean op cost — the candidates a fusion
layer would rewrite into one jitted region (PAPERS.md: MPK
"Mega-Kernelizing Tensor Programs", FlashFuser).

Recording follows the telemetry layer's zero-overhead contract: the
dispatcher checks the module-level ``chain_armed`` cell (one list
index) and only then notes the transition — plain GIL-serialised dict
ops, no lock, same tolerance as ``DispatchTelemetry`` (a lost count
under free threading is acceptable for a profile). Armed overhead is
covered by ``benchmarks/bench_obs_overhead.py``'s ABBA harness.

:meth:`DispatchChainProfiler.export` emits a **stable JSON artifact**
(deterministic given the same counters: ties break lexicographically)
whose ops are resolved against :mod:`paddle_tpu.analysis.callgraph`'s
``ProjectIndex`` — each op maps to the qualified symbol of the function
that dispatches it (the ``op_name=`` literal's enclosing def), so the
fusion pass can go from a hot chain straight to the code to fuse. The
schema is documented in README "Request timelines & profiling".
"""

from __future__ import annotations

import ast
import functools
import json
import os
import platform
import sys
import threading
from typing import Any, Dict, List, Optional, Tuple

#: the one cell ``core.dispatch.apply`` checks per armed dispatch
chain_armed = [False]

#: artifact schema version (bump on breaking changes to the JSON shape;
#: stamped as ``schema_version`` like the bench one-line JSONs so the
#: fusion pass can refuse an incompatible artifact instead of
#: misreading it)
PROFILE_VERSION = 1


def run_metadata() -> Dict[str, str]:
    """Deterministic run metadata stamped into the artifact — the same
    fields ``benchmarks/_telemetry.run_header`` stamps into bench JSON
    lines (no wall clock: two exports over one capture must stay
    byte-identical)."""
    return {
        "python": platform.python_version(),
        "host_platform": sys.platform,
        "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
    }


def note_chain(*, op_name: str, dur_ns: Optional[float] = None) -> None:
    """Armed-only chain tap for host code that dispatches whole
    *compiled programs* rather than eager ops — the serving engine's
    plan/dispatch/unpack phases and the fused megaregions. Call with a
    literal ``op_name=`` keyword: :func:`dispatch_sites` resolves the
    literal to the enclosing function exactly like a ``core.dispatch``
    op, so profiled step phases map to engine symbols in the artifact.
    One list-index when disarmed."""
    if not chain_armed[0]:
        return
    chain_profiler.note(op_name)
    if dur_ns is not None:
        chain_profiler.note_duration(op_name, dur_ns)


@functools.lru_cache(maxsize=1)
def dispatch_sites() -> Dict[str, str]:
    """op name -> ``module.qualname`` of the function dispatching it,
    resolved statically over the analysis ProjectIndex (one parse of the
    tree, cached; never imports jax). Ops dispatched with a dynamic
    ``op_name`` (generated elementwise families) stay unresolved — the
    fusion pass treats those as opaque. Deterministic: among several
    dispatch sites the lexicographically-smallest symbol wins."""
    from ..analysis import REPO_ROOT
    from ..analysis.engine import Project

    project = Project(REPO_ROOT, roots=("paddle_tpu",))
    index = project.index
    sites: Dict[str, str] = {}

    def note(op: str, symbol: str) -> None:
        if op not in sites or symbol < sites[op]:
            sites[op] = symbol

    for mi in index.mods.values():
        for fi in mi.functions:
            for node in fi.own_nodes():
                if not isinstance(node, ast.Call):
                    continue
                for kw in node.keywords:
                    if (kw.arg == "op_name"
                            and isinstance(kw.value, ast.Constant)
                            and isinstance(kw.value.value, str)):
                        note(kw.value.value,
                             f"{mi.modname}.{fi.qualname}")
    return sites


class DispatchChainProfiler:
    """See module docstring. ``note``/``note_duration`` are the hot-path
    taps (lock-free by design — do NOT add a lock here, the dispatcher
    calls them per eager op); ``profile``/``export`` are the cold read
    side."""

    def __init__(self, max_pairs: int = 4096):
        self._max_pairs = max_pairs
        self._pairs: Dict[Tuple[str, str], int] = {}
        self._prev: Dict[int, str] = {}         # thread ident -> last op
        self._dur: Dict[str, List[float]] = {}  # op -> [sum_ns, samples]
        self.dropped_pairs = 0

    # -- lifecycle ----------------------------------------------------------

    @property
    def armed(self) -> bool:
        return chain_armed[0]

    def arm(self) -> "DispatchChainProfiler":
        # a fresh window must not stitch a phantom transition from the
        # previous armed window's last op to this one's first
        self._prev = {}
        chain_armed[0] = True
        return self

    def disarm(self) -> None:
        chain_armed[0] = False

    def reset(self) -> None:
        self._pairs = {}
        self._prev = {}
        self._dur = {}
        self.dropped_pairs = 0

    # -- recording (armed-only; the dispatcher gates on chain_armed[0]) -----

    def note(self, op_name: str) -> None:
        """One dispatch: count the (previous op -> this op) transition on
        this thread. Bounded: past ``max_pairs`` distinct transitions new
        pairs are dropped (counted), existing pairs keep counting."""
        ident = threading.get_ident()
        prev = self._prev.get(ident)
        self._prev[ident] = op_name
        if prev is None:
            return
        key = (prev, op_name)
        pairs = self._pairs
        n = pairs.get(key)
        if n is None:
            if len(pairs) >= self._max_pairs:
                self.dropped_pairs += 1
                return
            n = 0
        pairs[key] = n + 1

    def note_duration(self, op_name: str, dur_ns: float) -> None:
        """Sampled op wall time (the dispatcher's existing 1/64 sample)."""
        s = self._dur.get(op_name)
        if s is None:
            s = self._dur[op_name] = [0.0, 0]
        s[0] += dur_ns
        s[1] += 1

    # -- profiling ----------------------------------------------------------

    def mean_us(self, op_name: str) -> float:
        s = self._dur.get(op_name)
        return (s[0] / s[1]) / 1e3 if s and s[1] else 0.0

    def chains(self, top_n: int = 10, min_count: int = 2,
               max_len: int = 8, coherence: float = 0.5
               ) -> List[Dict[str, Any]]:
        """Ranked hot chains. Seeds are the hottest transitions; a chain
        extends along the dominant successor while that edge carries at
        least ``coherence`` of the chain's weight (and no op repeats —
        loops truncate). ``count`` is the chain's weakest edge, ``est_us``
        is count × Σ sampled mean op cost. Deterministic: every ordering
        breaks ties lexicographically on op names."""
        pairs = dict(self._pairs)
        consumed: set = set()
        built: List[Dict[str, Any]] = []
        for (a, b), c in sorted(pairs.items(),
                                key=lambda kv: (-kv[1], kv[0])):
            if c < min_count or (a, b) in consumed:
                continue
            ops = [a, b]
            consumed.add((a, b))
            weight = c
            while len(ops) < max_len:
                succs = sorted(
                    ((k[1], n) for k, n in pairs.items()
                     if k[0] == ops[-1] and k not in consumed),
                    key=lambda s: (-s[1], s[0]))
                if not succs:
                    break
                nxt, n = succs[0]
                if n < coherence * weight or nxt in ops:
                    break
                consumed.add((ops[-1], nxt))
                ops.append(nxt)
                weight = min(weight, n)
            built.append({
                "ops": ops,
                "count": weight,
                "est_us": round(weight * sum(self.mean_us(o)
                                             for o in ops), 3),
            })
        built.sort(key=lambda ch: (-ch["est_us"], -ch["count"], ch["ops"]))
        return built[:top_n]

    def profile(self, op_counts: Optional[Dict[str, int]] = None,
                top_n: int = 10, workload: str = "",
                resolve: bool = True) -> Dict[str, Any]:
        """The fusion-pass input document (see module docstring).
        ``op_counts`` defaults to the live dispatch telemetry's counters;
        ``resolve=False`` skips the (one-off ~seconds) static symbol
        resolution for hot-loop callers."""
        if op_counts is None:
            from .runtime import telemetry
            op_counts = telemetry.op_counts
        chains = self.chains(top_n=top_n)
        chain_ops = sorted({o for ch in chains for o in ch["ops"]})
        symbols: Dict[str, Optional[str]] = {}
        if resolve and chain_ops:
            sites = dispatch_sites()
            symbols = {op: sites.get(op) for op in chain_ops}
        return {
            "version": PROFILE_VERSION,
            "schema_version": PROFILE_VERSION,
            "kind": "paddle_tpu.hot_chains",
            "meta": run_metadata(),
            "workload": workload,
            "top_n": top_n,
            "transitions": len(self._pairs),
            "dropped_pairs": self.dropped_pairs,
            "op_totals": {op: int(op_counts[op])
                          for op in sorted(op_counts)},
            "symbols": symbols,
            "chains": chains,
        }

    def export(self, path: Optional[str] = None, **kw) -> Dict[str, Any]:
        """``profile()`` serialised to a stable JSON artifact (sorted
        keys, fixed separators — byte-deterministic for identical
        counters). Returns the document; writes it when ``path`` given."""
        doc = self.profile(**kw)
        if path is not None:
            with open(path, "w") as f:
                f.write(json.dumps(doc, sort_keys=True, indent=1))
        return doc


#: the process-global profiler the dispatcher taps while armed
chain_profiler = DispatchChainProfiler()
