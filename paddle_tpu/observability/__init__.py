"""``paddle_tpu.observability`` — unified telemetry for the whole tree.

PR 1 and PR 2 each grew a metrics island (``serving.metrics``,
``resilience.metrics``) and the profiler only sees inside explicit
capture windows. This package is the attribution layer the north star
needs (every future perf PR must be measurable):

* :mod:`.registry` — ONE process-global :class:`MetricsRegistry`
  (counters / gauges / histograms over ``core.histogram``); the serving
  and resilience sinks re-register into it, so
  ``get_registry().prometheus_text()`` is a single valid ``/metrics``
  document and ``snapshot()`` its JSON twin.
* :mod:`.trace` — trace-context propagation: an id minted per serving
  request and per training step flows scheduler → engine step →
  ``core.dispatch.apply`` RecordEvent spans via a contextvar, so
  ``export_chrome_tracing`` emits per-request timelines (queue wait →
  prefill → decode chunks) correlated by id, linked with Perfetto flow
  events.
* :mod:`.runtime` — always-on low-overhead dispatch telemetry (per-op
  counters, sampled durations), recompile detection (trace-cache-miss
  counter carrying op shapes), and the single-boolean fast-path flag the
  dispatcher checks (< 3% overhead, guarded by
  ``benchmarks/bench_dispatch_overhead.py``).
* :mod:`.step_timer` — per-step host/device breakdown, tokens/sec and an
  MFU estimate, wired into ``ResilientTrainer`` and the serving loop.
* :mod:`.events` — structured JSON-lines event log (size-capped
  rotation) shared by serving and resilience for shed / retry /
  rollback / preempt / recompile events.
* :mod:`.slo` — declarative objectives (latency quantiles, error
  ratios) judged with multi-window burn rates against the registry,
  emitting ``slo_breach``/``slo_recovered`` events and feeding the
  scheduler's degrade path.
* :mod:`.goodput` — wall-clock decomposition of training runs
  (productive / retry / rollback-replay / checkpoint-stall / restart)
  plus a rolling-MAD straggler detector.
* :mod:`.flight` — flight recorder: last-N events/spans/metric deltas
  in bounded rings, postmortem ``dump_debug_bundle`` tarballs,
  auto-dump hooks on watchdog timeout / NaN rollback / degrade.
* :mod:`.journal` — black-box incident journal: a bounded, armed-gated
  ring of versioned, crc-signed frames recording the serving fleet's
  nondeterminism frontier (arrivals + resolved seeds, injected-clock
  samples, chaos firings, breaker transitions, stream checksums),
  embedded in flight bundles as ``journal.jsonl``.
* :mod:`.replay` — deterministic postmortem replay:
  ``python -m paddle_tpu.observability.replay bundle.tar.gz`` rebuilds
  the fleet from the journal head frame, re-drives the incident and
  reports byte-identical streams or the first divergence.
* :mod:`.timeline` — request timelines: a bounded :class:`SpanCollector`
  assembles the span stream into per-request span trees (one trace id
  across router → replica → scheduler → engine, failovers included) and
  attributes each request's e2e latency to exclusive critical-path
  segments; slowest-request exemplars feed ``/tracez`` and debug
  bundles.
* :mod:`.profiling` — continuous profiling of the eager dispatch
  stream: :class:`DispatchChainProfiler` folds the always-on per-op
  counters and sampled durations into ranked producer→consumer hot
  chains, exported as the stable JSON artifact ROADMAP item 2's fusion
  pass consumes.
* :mod:`.timeseries` — :class:`MetricHistory`: bounded ring-buffer
  sampling over the registry on injected clocks; counters read back as
  windowed rates, gauges as levels + slopes, histograms as windowed
  quantile estimates.
* :mod:`.anomaly` — robust anomaly detection over those series: the
  shared median/MAD z-score primitive (the straggler detector
  delegates here), a CUSUM drift detector, and the cooldown'd
  :class:`AnomalyMonitor` emitting ``anomaly`` events.
* :mod:`.signals` — :class:`SignalBus`: named, smoothed,
  autoscaler-ready signals (burn trend, queue-depth slope, queue_wait
  share, pool pressure, spec-acceptance drift) served at ``/varz`` and
  embedded in flight bundles as ``history.json``.
* :mod:`.memory` — HBM memory ledger: byte-level device accounting by
  class (weights / kv_live / kv_spec / kv_cached / kv_free / optimizer)
  with peak watermarks and a byte conservation audit, a capacity
  planner (geometry + dtype + HBM budget → max pages / slots /
  context, validated against live pools), per-request page
  attribution, and OOM forensics (``oom_pressure`` events +
  ``memory.json`` flight bundles).
* :mod:`.server` — stdlib-only :class:`DiagServer` exposing
  ``/metrics``, ``/healthz``, ``/statusz``, ``/debugz``,
  ``/tracez``, ``/varz`` and ``/memz`` live.
* :mod:`.federation` — fleet-wide telemetry federation: per-host
  :class:`HostTelemetryMirror`\\ s inside a :class:`FederationHub`,
  clock-offset estimation from heartbeat round-trips (:class:`ClockSync`
  — offset from the RPC midpoint, EWMA-smoothed, RTT/2 error bound),
  skew-corrected remote spans merged into the parent's trace trees, one
  merged ``/metrics`` exposition under a ``host`` label, per-host +
  fleet-aggregate ``/varz`` signals, and the ``host_telemetry.json``
  bundle member that preserves a dead host's final telemetry.

Quick start::

    from paddle_tpu.observability import (get_registry,
                                          configure_event_log)
    configure_event_log("/var/log/paddle/events.jsonl")
    ...serve / train...
    print(get_registry().prometheus_text())   # one /metrics document
"""

from . import format  # noqa: F401
from .anomaly import (  # noqa: F401
    AnomalyMonitor, CusumDetector, RobustZScoreDetector, robust_zscore,
)
from .events import EventLog, configure_event_log, emit_event, event_log  # noqa: F401
from .federation import (  # noqa: F401
    ClockSync, FederationHub, HostTelemetryMirror, collect_telemetry,
    federation_armed, merge_expositions,
)
from .flight import FlightRecorder, flight_recorder  # noqa: F401
from .goodput import GoodputTracker, StragglerDetector  # noqa: F401
from .journal import (  # noqa: F401
    JournalError, JournalRecorder, journal, journal_armed, token_checksum,
)
from .memory import (  # noqa: F401
    CapacityPlan, MemoryLedger, memory_ledger, plan_capacity,
    pool_occupancy, pytree_nbytes,
)
from .registry import (  # noqa: F401
    Counter, Gauge, HistogramMetric, MetricsRegistry, get_registry,
)
from .runtime import (  # noqa: F401
    DispatchTelemetry, RecompileDetector, recompiles, telemetry,
)
from .profiling import DispatchChainProfiler, chain_profiler  # noqa: F401
from .server import DiagServer  # noqa: F401
from .slo import (  # noqa: F401
    SLObjective, SLOMonitor, latency_objective, ratio_objective,
)
from .signals import (  # noqa: F401
    SIGNAL_SNAPSHOT_VERSION, SignalBus, SignalSnapshot,
)
from .step_timer import StepTimer  # noqa: F401
from .timeline import SpanCollector, span_collector  # noqa: F401
from .timeseries import MetricHistory  # noqa: F401
from .trace import (  # noqa: F401
    TraceContext, current_trace, current_trace_id, new_trace_id,
    trace_context,
)

__all__ = [
    "Counter", "Gauge", "HistogramMetric", "MetricsRegistry",
    "get_registry", "DispatchTelemetry", "RecompileDetector", "recompiles",
    "telemetry", "StepTimer", "TraceContext", "current_trace",
    "current_trace_id", "new_trace_id", "trace_context", "EventLog",
    "configure_event_log", "emit_event", "event_log", "format",
    "SLObjective", "SLOMonitor", "latency_objective", "ratio_objective",
    "GoodputTracker", "StragglerDetector", "FlightRecorder",
    "flight_recorder", "DiagServer", "SpanCollector", "span_collector",
    "DispatchChainProfiler", "chain_profiler", "MetricHistory",
    "SignalBus", "SignalSnapshot", "SIGNAL_SNAPSHOT_VERSION",
    "AnomalyMonitor", "RobustZScoreDetector",
    "CusumDetector", "robust_zscore", "CapacityPlan", "MemoryLedger",
    "memory_ledger", "plan_capacity", "pool_occupancy", "pytree_nbytes",
    "ClockSync", "FederationHub", "HostTelemetryMirror",
    "collect_telemetry", "federation_armed", "merge_expositions",
    "JournalError", "JournalRecorder", "journal", "journal_armed",
    "token_checksum",
]
