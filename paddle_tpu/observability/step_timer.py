"""Per-step timing: host/device breakdown, tokens/sec, MFU estimate.

``StepTimer`` wraps one unit of work per iteration (a training step, a
serving engine round). The host/device split uses the dispatch–fence
structure of the runtime: the step function *returns* when the host has
finished dispatching (host time); materialising the result blocks until
the device finishes (device time). Callers mark the boundary with
:meth:`host_done`; without it the whole step counts as host time.

MFU — model FLOPs utilization — is ``achieved_flops / peak_flops``:
supply ``flops_per_step`` (e.g. ``6 * params * tokens`` for a dense
transformer step) and ``peak_flops_per_s`` for the chip; both optional
(without them :meth:`summary` reports ``mfu = None``).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ..core.histogram import Histogram


class StepTimer:
    def __init__(self, flops_per_step: Optional[float] = None,
                 peak_flops_per_s: Optional[float] = None):
        self.flops_per_step = flops_per_step
        self.peak_flops_per_s = peak_flops_per_s
        self.step_ms = Histogram()
        self.host_ms = Histogram()
        self.device_ms = Histogram()
        self.steps = 0
        self.tokens = 0
        self.total_s = 0.0
        self._t0: Optional[int] = None
        self._t_host: Optional[int] = None

    # -- one step -----------------------------------------------------------

    def begin(self) -> None:
        self._t0 = time.perf_counter_ns()
        self._t_host = None

    def host_done(self) -> None:
        """Host finished dispatching; the remainder until :meth:`end` is
        device wait (the fence)."""
        if self._t0 is not None:
            self._t_host = time.perf_counter_ns()

    def end(self, tokens: int = 0) -> Optional[float]:
        """Close the step; returns its wall seconds (None if begin() was
        never called — tolerated so error paths need no bookkeeping)."""
        if self._t0 is None:
            return None
        t1 = time.perf_counter_ns()
        step_s = (t1 - self._t0) / 1e9
        host_s = ((self._t_host or t1) - self._t0) / 1e9
        self.step_ms.record(step_s * 1e3)
        self.host_ms.record(host_s * 1e3)
        self.device_ms.record((step_s - host_s) * 1e3)
        self.steps += 1
        self.tokens += int(tokens)
        self.total_s += step_s
        self._t0 = None
        self._t_host = None
        return step_s

    def step(self, tokens: int = 0):
        """``with timer.step(tokens=n): ...`` convenience wrapper."""
        return _StepCtx(self, tokens)

    # -- derived rates ------------------------------------------------------

    @property
    def tokens_per_s(self) -> float:
        return self.tokens / self.total_s if self.total_s else 0.0

    def mfu(self) -> Optional[float]:
        """Mean MFU over the recorded steps (None without flops config)."""
        if (not self.steps or not self.total_s
                or self.flops_per_step is None
                or not self.peak_flops_per_s):
            return None
        achieved = self.flops_per_step * self.steps / self.total_s
        return achieved / self.peak_flops_per_s

    def summary(self) -> Dict[str, object]:
        return {
            "steps": self.steps,
            "step_ms": self.step_ms.summary(),
            "host_ms": self.host_ms.summary(),
            "device_ms": self.device_ms.summary(),
            "tokens": self.tokens,
            "tokens_per_s": self.tokens_per_s,
            "mfu": self.mfu(),
        }


class _StepCtx:
    def __init__(self, timer: StepTimer, tokens: int):
        self._timer = timer
        self._tokens = tokens

    def __enter__(self) -> StepTimer:
        self._timer.begin()
        return self._timer

    def __exit__(self, *exc) -> bool:
        self._timer.end(tokens=self._tokens)
        return False
