"""Global flag registry.

TPU-native rebuild of the reference's gflags-workalike flag plane
(reference: paddle/utils/flags.h, paddle/phi/core/flags.cc — see SURVEY.md §5.6).
Flags are plain Python values with env-var override (``FLAGS_<name>``),
inspectable via :func:`get_flags` / settable via :func:`set_flags`
(API parity with ``paddle.get_flags`` / ``paddle.set_flags``).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterable, List, Optional, Union

_REGISTRY: Dict[str, "_Flag"] = {}


class _Flag:
    __slots__ = ("name", "default", "value", "type", "help")

    def __init__(self, name: str, default: Any, typ: type, help_str: str):
        self.name = name
        self.default = default
        self.type = typ
        self.help = help_str
        self.value = self._from_env(default)

    def _from_env(self, default: Any) -> Any:
        raw = os.environ.get("FLAGS_" + self.name)
        if raw is None:
            return default
        return _parse(raw, self.type)


def _parse(raw: str, typ: type) -> Any:
    if typ is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return typ(raw)


def define_flag(name: str, default: Any, help_str: str = "") -> None:
    """Register a flag (idempotent; keeps the existing value on re-register)."""
    if name in _REGISTRY:
        return
    _REGISTRY[name] = _Flag(name, default, type(default), help_str)


def get_flags(flags: Union[str, Iterable[str], None] = None) -> Dict[str, Any]:
    if flags is None:
        names: List[str] = list(_REGISTRY)
    elif isinstance(flags, str):
        names = [flags]
    else:
        names = list(flags)
    out = {}
    for n in names:
        key = n[len("FLAGS_"):] if n.startswith("FLAGS_") else n
        if key not in _REGISTRY:
            raise ValueError(f"Unknown flag: {n}")
        out[n] = _REGISTRY[key].value
    return out


def set_flags(flags: Dict[str, Any]) -> None:
    for n, v in flags.items():
        key = n[len("FLAGS_"):] if n.startswith("FLAGS_") else n
        if key not in _REGISTRY:
            raise ValueError(f"Unknown flag: {n}")
        f = _REGISTRY[key]
        f.value = _parse(v, f.type) if isinstance(v, str) and f.type is not str else f.type(v)


def flag_value(name: str) -> Any:
    return _REGISTRY[name].value


# ---------------------------------------------------------------------------
# Core flag corpus (subset of reference's paddle/phi/core/flags.cc that is
# meaningful on TPU/XLA; allocator/cudnn/nccl flags have no analog).
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False, "Scan op outputs for nan/inf (debug pass).")
define_flag("check_nan_inf_level", 0, "0: report all; higher levels reduce verbosity.")
define_flag("use_pallas_kernels", True, "Use Pallas kernels on TPU (fall back to XLA ops otherwise).")
define_flag("use_pallas_rms_norm", True, "Use the Pallas rms_norm kernel (isolated knob for dispatch decisions).")
define_flag("use_pallas_layer_norm", False, "Use the fused Pallas LayerNorm kernel (round-4 experiment; engage per measured decision).")
define_flag("deterministic", False, "Force deterministic compilation/reductions where possible.")
define_flag("log_level", 0, "VLOG-style verbosity for framework-internal logging.")
define_flag("benchmark", False, "Block on every op for timing (eager debugging).")
define_flag("ring_attention_mode", "ring", "Long-context attention mode: 'ring' or 'ulysses'.")
define_flag("serving_a8w8_prefill", True,
            "When serving with int8-quantized weights, run PREFILL matmuls "
            "on the int8xint8->int32 MXU path with per-token activation "
            "scales (reference fused_multi_transformer_int8). Decode keeps "
            "weight-only dequant. 0 = weight-only everywhere.")
define_flag("dy2static_fallback", True,
            "On ConversionError (or an untraceable predicate) under "
            "to_static, warn and fall back to the eager path instead of "
            "raising — the reference SOT's graceful-fallback behaviour. "
            "Set to 0 for the strict raise.")
define_flag("dy2static_rebind_wrappers", True,
            "Allow dy2static conversion to re-bind a wraps-style "
            "decorator's closure cell onto the converted function. The "
            "rebind is PROCESS-WIDE: every call site of the shared wrapper "
            "switches to the converted body. Set to 0 to keep the wrapper "
            "untouched (its per-call behavior then only applies on the "
            "unconverted object).")
define_flag("remat_policy", "none", "Default rematerialisation policy: none|dots|everything.")
