"""Signal-driven autoscaling: the SignalBus sensor plane closed into a
control loop (ROADMAP item 2 — "turn the sensor plane into a control
plane").

The decision loop is a small state machine, evaluated once per
``interval_s`` on the router's clock:

::

    IDLE --(overload evidence x evidence_rounds)--> HOT
      HOT:  replicas < max and scale_up off cooldown  -> SCALE_UP
            else role imbalance and off cooldown      -> ROLE_CHANGE
    IDLE --(underload evidence x evidence_rounds)--> COLD
      COLD: replicas > min and scale_down off cooldown -> SCALE_DOWN
    any actuation in flight (drain pending)            -> HOLD

* **Evidence** maps the documented :class:`~paddle_tpu.observability.
  signals.SignalSnapshot` contract to booleans: queue-depth level AND
  slope, SLO fast-burn, queue-wait share of e2e, paged-pool pressure,
  speculation-acceptance drift, and any parked (unroutable) request —
  the clearest scale-up signal there is.
* **Hysteresis**: evidence must hold ``evidence_rounds`` consecutive
  evaluations before anything actuates, and each action kind has its
  own ``cooldown_s``, so a spiky burst cannot thrash the fleet.
* **Actuation** uses the router's existing primitives, one operation at
  a time: scale-up builds a replica from the ``engine_factory`` /
  ``handle_factory`` pair (the :class:`~.elastic.
  ElasticServingController` recipe) and registers it with a role;
  scale-down and role flips go through drain → (retag|remove) →
  undrain, advanced across evaluation rounds — a flip never races live
  admissions, a removal never strands a request.

Every decision appends a versioned :class:`ScaleRecord` (bounded ring):
``autoscale.json`` in every flight-recorder bundle, the ``/scalez``
DiagServer endpoint, ``paddle_autoscale_decisions_total{action}`` +
``paddle_autoscale_replicas``, and ``scale_up`` / ``scale_down`` events
(``role_changed`` is emitted by the router's ``set_role``).
"""

from __future__ import annotations

import itertools
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..observability.events import emit_event
from ..observability.flight import flight_recorder
from ..observability.journal import journal, journal_armed
from ..observability.registry import get_registry
from ..observability.signals import SignalBus, SignalSnapshot
from .roles import ReplicaRole

#: bump when ScaleRecord gains/renames a field
SCALE_RECORD_VERSION = 1

#: process-global record sequence — reasons stay unique across
#: controller rebuilds in one process (same idiom as elastic's _ARC_SEQ)
_REC_SEQ = itertools.count(1)


@dataclass
class AutoscaleConfig:
    """Policy thresholds. "up_*" are overload evidence (any one
    suffices), "down_*" underload (all must hold). Depth thresholds are
    per-replica averages so they survive scale changes unchanged."""

    min_replicas: int = 1
    max_replicas: int = 8
    up_queue_depth: float = 4.0      # avg queued/replica, with rising slope
    up_trend: float = 0.0            # queue-depth slope floor (units/s)
    up_burn: float = 1.0             # SLO fast-window burn
    up_wait_share: float = 0.5       # queue_wait share of e2e
    up_pressure: float = 0.85        # paged-pool occupancy
    spec_drift: float = 0.3          # acceptance drop below 1 - drift
    down_queue_depth: float = 0.25   # avg queued/replica below = idle
    evidence_rounds: int = 2         # consecutive rounds before acting
    cooldown_s: float = 10.0         # per-action-kind
    rebalance_backlog: float = 2.0   # prefill-side avg depth to retag at


@dataclass
class Decision:
    """One policy verdict. ``replica_id``/``role`` carry the actuation
    target: the new replica's role for scale_up, the victim for
    scale_down, the flipped replica + its new role for role_change."""

    action: str                      # scale_up | scale_down | role_change
    reason: str
    replica_id: Optional[int] = None
    role: Optional[str] = None


@dataclass
class ScaleRecord:
    """One logged decision + its actuation timeline. ``snapshot`` is
    the exact :class:`SignalSnapshot` the policy decided on — a scaling
    postmortem replays the inputs, not a story about them."""

    schema_version: int
    seq: int
    t: float
    action: str
    reason: str
    replica_id: Optional[int]
    role: Optional[str]
    state: str                       # applying | done | failed
    phases: List[Dict[str, Any]] = field(default_factory=list)
    snapshot: Dict[str, Any] = field(default_factory=dict)

    def phase(self, name: str, t: float, **extra: Any) -> None:
        self.phases.append({"phase": name, "t": round(t, 6), **extra})

    def as_dict(self) -> Dict[str, Any]:
        return asdict(self)


class AutoscalePolicy:
    """Pure decision function over (snapshot, roles): no router access,
    no side effects beyond its own hysteresis latches — unit-testable
    against synthetic snapshots."""

    def __init__(self, config: Optional[AutoscaleConfig] = None):
        self.config = config or AutoscaleConfig()
        self._hot = 0                # consecutive overloaded rounds
        self._cold = 0               # consecutive idle rounds
        self._last: Dict[str, float] = {}    # action kind -> last t

    # -- evidence ------------------------------------------------------------

    def overload_evidence(self, snap: SignalSnapshot,
                          n_replicas: int) -> List[str]:
        cfg = self.config
        n = max(1, n_replicas)
        out = []
        if snap.parked > 0:
            out.append(f"parked={snap.parked:g}")
        if (snap.queue_depth / n >= cfg.up_queue_depth
                and snap.queue_depth_trend > cfg.up_trend):
            out.append(f"queue_depth/replica="
                       f"{snap.queue_depth / n:.2f} rising "
                       f"({snap.queue_depth_trend:+.3f}/s)")
        if snap.slo_fast_burn >= cfg.up_burn:
            out.append(f"slo_fast_burn={snap.slo_fast_burn:.2f}")
        if snap.queue_wait_share >= cfg.up_wait_share:
            out.append(f"queue_wait_share={snap.queue_wait_share:.2f}")
        if snap.page_pressure >= cfg.up_pressure:
            out.append(f"page_pressure={snap.page_pressure:.2f}")
        if snap.spec_acceptance <= 1.0 - cfg.spec_drift:
            out.append(f"spec_acceptance={snap.spec_acceptance:.2f}")
        return out

    def underload(self, snap: SignalSnapshot, n_replicas: int) -> bool:
        cfg = self.config
        n = max(1, n_replicas)
        return (snap.parked == 0
                and snap.queue_depth / n <= cfg.down_queue_depth
                and snap.slo_fast_burn < cfg.up_burn
                and snap.page_pressure < cfg.up_pressure)

    # -- role balance --------------------------------------------------------

    def _qd(self, snap: SignalSnapshot, rid: int) -> float:
        return snap.per_replica.get(f"r{rid}", {}).get("queue_depth", 0.0)

    def _routable(self, snap: SignalSnapshot, rid: int) -> bool:
        """Per-replica ``routable`` signal; missing reads as routable
        (a bus without the signal must not paralyze the policy)."""
        return snap.per_replica.get(f"r{rid}", {}).get(
            "routable", 1.0) >= 0.5

    def _rebalance(self, snap: SignalSnapshot,
                   roles: Dict[int, str]) -> Optional[Decision]:
        """Flip a replica toward the pressured phase. Prompt-heavy load
        queues on the prefill-capable side while decode replicas idle
        (handoff queues are shallow): promote the least-loaded DECODE.
        The reverse (decode side drowning, a PREFILL idle) demotes a
        surplus PREFILL — never the last one. Only ROUTABLE replicas
        count on either side: an ejected prefill replica is not idle
        prefill capacity, and flipping a dead replica actuates nothing."""
        cfg = self.config
        pre = [r for r, ro in roles.items()
               if ro in (ReplicaRole.PREFILL, ReplicaRole.HYBRID)
               and self._routable(snap, r)]
        dec = [r for r, ro in roles.items() if ro == ReplicaRole.DECODE
               and self._routable(snap, r)]
        pre_load = (sum(self._qd(snap, r) for r in pre) / len(pre)
                    if pre else 0.0)
        dec_load = (sum(self._qd(snap, r) for r in dec) / len(dec)
                    if dec else 0.0)
        if (dec and pre_load >= cfg.rebalance_backlog
                and pre_load > 2.0 * dec_load):
            rid = min(dec, key=lambda r: (self._qd(snap, r), r))
            return Decision(
                "role_change",
                f"prefill backlog {pre_load:.2f}/replica vs decode "
                f"{dec_load:.2f}: promote r{rid} to prefill",
                replica_id=rid, role=ReplicaRole.PREFILL)
        strict_pre = [r for r, ro in roles.items()
                      if ro == ReplicaRole.PREFILL]
        if (len(strict_pre) > 1 and dec_load >= cfg.rebalance_backlog
                and dec_load > 2.0 * pre_load):
            rid = min(strict_pre, key=lambda r: (self._qd(snap, r), r))
            return Decision(
                "role_change",
                f"decode backlog {dec_load:.2f}/replica vs prefill "
                f"{pre_load:.2f}: demote r{rid} to decode",
                replica_id=rid, role=ReplicaRole.DECODE)
        return None

    def _new_replica_role(self, snap: SignalSnapshot,
                          roles: Dict[int, str]) -> str:
        """A scale-up lands where the pressure is: prompt-heavy fleets
        grow the prefill side, otherwise the new capacity stays HYBRID
        (useful for both phases, handoff-eligible as a target)."""
        pre = [r for r, ro in roles.items()
               if ro in (ReplicaRole.PREFILL, ReplicaRole.HYBRID)
               and self._routable(snap, r)]
        dec = [r for r, ro in roles.items() if ro == ReplicaRole.DECODE
               and self._routable(snap, r)]
        pre_load = (sum(self._qd(snap, r) for r in pre) / len(pre)
                    if pre else 0.0)
        dec_load = (sum(self._qd(snap, r) for r in dec) / len(dec)
                    if dec else 0.0)
        if dec and pre_load > 2.0 * dec_load:
            return ReplicaRole.PREFILL
        return ReplicaRole.HYBRID

    # -- the verdict ---------------------------------------------------------

    def _cooled(self, action: str, t: float) -> bool:
        last = self._last.get(action)
        return last is None or t - last >= self.config.cooldown_s

    def decide(self, snap: SignalSnapshot, roles: Dict[int, str],
               t: float) -> Optional[Decision]:
        cfg = self.config
        n = len(roles)
        evidence = self.overload_evidence(snap, n)
        if evidence:
            self._hot += 1
            self._cold = 0
        elif self.underload(snap, n):
            self._cold += 1
            self._hot = 0
        else:
            self._hot = self._cold = 0
        decision: Optional[Decision] = None
        if self._hot >= cfg.evidence_rounds:
            if n < cfg.max_replicas and self._cooled("scale_up", t):
                decision = Decision(
                    "scale_up", "; ".join(evidence),
                    role=self._new_replica_role(snap, roles))
            elif self._cooled("role_change", t):
                decision = self._rebalance(snap, roles)
        elif (self._cold >= cfg.evidence_rounds
                and n > cfg.min_replicas
                and self._cooled("scale_down", t)):
            # victim: the least-loaded replica, hybrids first (removing
            # one never unbalances the role split)
            order = {ReplicaRole.HYBRID: 0, ReplicaRole.DECODE: 1,
                     ReplicaRole.PREFILL: 2}
            rid = min(roles, key=lambda r: (order[roles[r]],
                                            self._qd(snap, r), r))
            decision = Decision(
                "scale_down",
                f"idle: queue_depth/replica="
                f"{snap.queue_depth / max(1, n):.2f}, parked=0",
                replica_id=rid)
        if decision is not None:
            self._last[decision.action] = t
            self._hot = self._cold = 0
        return decision


class AutoscaleController:
    """Applies :class:`AutoscalePolicy` verdicts to a live fleet. The
    router is any :class:`~.router.FleetRouter`; role actuation needs a
    :class:`~.roles.DisaggRouter` (a plain fleet is treated as all-
    HYBRID and only scales counts). ``engine_factory()`` builds a fresh
    engine, ``handle_factory(replica_id, engine)`` wraps it — the same
    split the elastic resize controller uses, so one pair of factories
    serves both controllers."""

    def __init__(self, router,
                 engine_factory: Callable[[], Any],
                 handle_factory: Callable[[int, Any], Any],
                 config: Optional[AutoscaleConfig] = None,
                 policy: Optional[AutoscalePolicy] = None,
                 bus: Optional[SignalBus] = None,
                 interval_s: float = 1.0,
                 max_records: int = 256):
        self.router = router
        self.engine_factory = engine_factory
        self.handle_factory = handle_factory
        self.policy = policy or AutoscalePolicy(config)
        self.config = self.policy.config
        if bus is None:
            bus = router.signal_bus
        if bus is None:
            bus = router.attach_signal_bus(interval_s=interval_s)
        self.bus = bus
        self._clock = router._clock
        self._interval = float(interval_s)
        self._last_eval: Optional[float] = None
        self._max_records = int(max_records)
        self.records: List[ScaleRecord] = []
        self._pending: List[Dict[str, Any]] = []     # drain ops in flight
        self.rounds = 0
        reg = get_registry()
        self._c_decisions = reg.counter(
            "paddle_autoscale_decisions_total",
            "autoscaler actuations by kind",
            labels=("action",))
        self._g_replicas = reg.gauge(
            "paddle_autoscale_replicas",
            "current fleet size under autoscaler control")
        self._g_replicas.set(len(router.replicas))
        # autoscale.json in every postmortem bundle (a later controller
        # replaces an earlier one, same lifecycle as attach_elastic)
        flight_recorder.attach_autoscale(self)

    # -- driving -------------------------------------------------------------

    def step(self, params) -> int:
        """One fleet round + one (decimated) control round — the drop-in
        replacement for ``router.step`` in a serving loop."""
        self.router.step(params)
        self.evaluate()
        return self.router.pending

    def run(self, params, max_steps: Optional[int] = None) -> None:
        """Drive until every request resolves (test/bench harness)."""
        steps = 0
        while self.router.pending:
            before = self.router.pending
            self.step(params)
            steps += 1
            if self.router.pending and max_steps is not None \
                    and steps >= max_steps:
                raise RuntimeError(
                    f"autoscale loop exceeded max_steps={max_steps} "
                    f"with {self.router.pending} requests pending")
            self.router._backoff_if_stalled(before)

    # -- the control loop ----------------------------------------------------

    def _roles(self) -> Dict[int, str]:
        roles = getattr(self.router, "roles", None)
        if roles is None:
            return {rid: ReplicaRole.HYBRID
                    for rid in self.router.replicas}
        return dict(roles)

    def evaluate(self) -> Optional[ScaleRecord]:
        """One control round: advance in-flight drains, then (at most
        once per ``interval_s``) snapshot the bus, ask the policy, and
        actuate. Returns the new record when a decision was made."""
        t = self._clock()
        self._advance_pending(t)
        if self._last_eval is not None \
                and t - self._last_eval < self._interval:
            return None
        self._last_eval = t
        self.rounds += 1
        # the controller is the bus's consumer: tick it here so the
        # control loop works with or without the history plane armed
        # (the router's own step-loop tick is gated on history_armed)
        self.bus.tick(now=t)
        if self._pending:
            return None          # one operation at a time (like elastic)
        snap = self.bus.snapshot_contract()
        decision = self.policy.decide(snap, self._roles(), t)
        if decision is None:
            return None
        rec = ScaleRecord(
            schema_version=SCALE_RECORD_VERSION, seq=next(_REC_SEQ),
            t=round(t, 6), action=decision.action, reason=decision.reason,
            replica_id=decision.replica_id, role=decision.role,
            state="applying", snapshot=snap.as_dict())
        self.records.append(rec)
        del self.records[:-self._max_records]
        self._c_decisions.inc(action=decision.action)
        if journal_armed[0]:
            # a scale frame in the journal is a replay *refusal* marker:
            # the fleet topology changed mid-incident, so the head frame
            # alone can no longer rebuild it. The frame carries the
            # ScaleRecord seq so the operator can pivot to autoscale.json.
            journal.note_scale(seq=rec.seq, action=rec.action,
                              reason=rec.reason, replica=rec.replica_id,
                              role=rec.role)
        try:
            self._apply(decision, rec, t)
        except Exception as e:  # noqa: BLE001 - a torn actuation must
            # not kill the serving loop; the record carries the autopsy
            rec.state = "failed"
            rec.phase("failed", t, error=repr(e))
        return rec

    def _apply(self, d: Decision, rec: ScaleRecord, t: float) -> None:
        router = self.router
        if d.action == "scale_up":
            new_rid = max(router.replicas) + 1
            engine = self.engine_factory()
            handle = self.handle_factory(new_rid, engine)
            rec.phase("built", self._clock(), replica=new_rid)
            if hasattr(router, "set_role"):
                router.add_replica(handle, role=d.role)
            else:
                router.add_replica(handle)
            rec.replica_id = new_rid
            # follow the fleet: per-replica signals for the new handle
            self.bus.attach_router(router)
            self._g_replicas.set(len(router.replicas))
            emit_event("scale_up", replica=new_rid, role=d.role,
                       replicas=len(router.replicas), reason=d.reason)
            rec.phase("added", self._clock(), role=d.role)
            rec.state = "done"
        elif d.action in ("scale_down", "role_change"):
            rid = d.replica_id
            router.drain(rid)
            rec.phase("drain", self._clock(), replica=rid)
            self._pending.append({"kind": d.action, "rid": rid,
                                  "role": d.role, "rec": rec})
        else:                                        # pragma: no cover
            raise ValueError(f"unknown action {d.action!r}")

    def _drained(self, rid: int) -> bool:
        r = self.router.replicas.get(rid)
        if r is None:
            return False
        if any(q.replica_id == rid and q.handle is not None
               for q in self.router._requests.values()):
            return False
        return r.pending == 0

    def _advance_pending(self, t: float) -> None:
        for op in list(self._pending):
            rid, rec = op["rid"], op["rec"]
            if rid not in self.router.replicas:
                # ejected/replaced under us: the op is moot
                self._pending.remove(op)
                rec.state = "failed"
                rec.phase("lost", t, replica=rid)
                continue
            if not self._drained(rid):
                continue
            self._pending.remove(op)
            if op["kind"] == "role_change":
                self.router.set_role(rid, op["role"], reason="autoscale")
                rec.phase("retag", t, role=op["role"])
                self.router.undrain(rid)
                rec.phase("undrain", t)
            else:
                self.router.remove_replica(rid)
                self._g_replicas.set(len(self.router.replicas))
                emit_event("scale_down", replica=rid,
                           replicas=len(self.router.replicas),
                           reason=rec.reason)
                rec.phase("removed", t)
            rec.state = "done"

    # -- observability -------------------------------------------------------

    def timeline_snapshot(self) -> Dict[str, Any]:
        """The ``autoscale.json`` bundle member / ``/scalez`` document:
        fleet shape, in-flight operations and the bounded decision
        ring."""
        return {
            "kind": "paddle_tpu.autoscale",
            "schema_version": SCALE_RECORD_VERSION,
            "replicas": len(self.router.replicas),
            "roles": {str(rid): role
                      for rid, role in sorted(self._roles().items())},
            "rounds": self.rounds,
            "pending_ops": [{"kind": op["kind"], "replica": op["rid"],
                             "role": op["role"]}
                            for op in self._pending],
            "config": asdict(self.config),
            "records": [r.as_dict() for r in self.records],
        }
