"""Serving metrics: latency histograms, utilization gauges, counters.

The serving scheduler records into one :class:`ServingMetrics` sink:

* **TTFT** (time-to-first-token) and **ITL** (inter-token latency)
  histograms per request. Tokens reach the host one decode *chunk* at a
  time (the engine's single fence per round), so ITL shows the chunk
  cadence: the first token of a chunk carries the device round's latency,
  the rest are ~0. That is the true serving profile, not an artifact.
* queue depth, slot and page utilization, sampled once per scheduler step
  (gauge = last value, histogram = distribution over the run).
* counters: submitted/completed/shed (by reason)/cancelled, step retries
  and failures, generated tokens.

Export surfaces:

* :meth:`ServingMetrics.to_prometheus_text` — Prometheus exposition text
  (histogram ``_bucket``/``_sum``/``_count`` plus exact-percentile
  ``_quantile`` gauges as a separate family — mixing quantile samples
  into a histogram family is invalid exposition format) ready for a
  /metrics endpoint or a scrape file.
* trace events — :meth:`ServingMetrics.span` returns a profiler
  ``RecordEvent`` so scheduler phases land in the host-span recorder (and
  in the XLA xplane trace when a profiler capture is active), correlated
  with device activity.

Thread-safe: the scheduler may run ``engine.step`` on a watchdog thread
(step timeouts), so every mutation takes the sink's lock.
"""

from __future__ import annotations

import threading
from typing import Dict, List

from ..core.histogram import (DEFAULT_BOUNDS_MS, DEFAULT_QUANTILES,  # noqa: F401
                              Histogram)
from ..observability import format as _fmt
from ..observability.registry import get_registry
from ..profiler.record import RecordEvent

#: observations per family before a non-record observation may replace
#: the exemplar anyway ("worst RECENT", not worst-ever: a p99 spike from
#: last week must not pin the exemplar forever)
EXEMPLAR_WINDOW = 128


class ServingMetrics:
    """Process-local metrics sink for one :class:`ServingScheduler`.

    Registered into the global :class:`~paddle_tpu.observability.registry.
    MetricsRegistry` under its namespace (a fresh sink replaces the
    previous one — normal per-server lifecycle), so the process-wide
    ``/metrics`` document includes serving without a second scrape."""

    def __init__(self, namespace: str = "paddle_serving"):
        self.namespace = namespace
        self._lock = threading.Lock()
        self.histograms: Dict[str, Histogram] = {
            "ttft_ms": Histogram(),
            "itl_ms": Histogram(),
            "e2e_ms": Histogram(),
            "queue_wait_ms": Histogram(),
            "step_ms": Histogram(),
            "queue_depth": Histogram(bounds=(0, 1, 2, 4, 8, 16, 32, 64,
                                             128, 256)),
        }
        self.counters: Dict[str, float] = {
            "requests_submitted_total": 0,
            "requests_completed_total": 0,
            "requests_cancelled_total": 0,
            "step_retries_total": 0,
            "step_failures_total": 0,
            "steps_total": 0,
            "tokens_generated_total": 0,
        }
        #: shed counts keyed by reason ("queue_full", "deadline", ...)
        self.shed: Dict[str, float] = {}
        #: last-value gauges (utilizations in [0, 1], depths in requests).
        #: EVERY family set_gauge() may touch is declared here — an
        #: undeclared name would be minted on first set and missing from
        #: /metrics until then, so the scrape schema would depend on
        #: which code paths have run (tpu-lint metric-contract)
        self.gauges: Dict[str, float] = {
            "queue_depth": 0.0,
            "slot_utilization": 0.0,
            "page_utilization": 0.0,
            "live_page_utilization": 0.0,
            "cached_page_utilization": 0.0,
            "inflight": 0.0,
            "degraded": 0.0,
            "slo_breached": 0.0,
        }
        #: per-family worst-recent exemplar: hist -> {"trace_id",
        #: "value", "n"} (n = observation count at capture; see
        #: EXEMPLAR_WINDOW). Answers "WHICH request was the p99" —
        #: the trace id keys straight into the span collector / /tracez.
        self._exemplars: Dict[str, Dict] = {}
        self._obs_counts: Dict[str, int] = {}
        get_registry().register_sink(self.namespace, self._prometheus_lines,
                                     self.summary)

    # -- recording ----------------------------------------------------------

    def observe(self, hist: str, value: float,
                trace_id: str = None) -> None:
        """Record into a histogram family; when ``trace_id`` is given the
        observation competes for the family's exemplar slot (kept when it
        is the worst seen, or when the current exemplar is older than
        ``EXEMPLAR_WINDOW`` observations)."""
        with self._lock:
            self.histograms[hist].record(value)
            if trace_id is None:
                return
            n = self._obs_counts.get(hist, 0) + 1
            self._obs_counts[hist] = n
            ex = self._exemplars.get(hist)
            if (ex is None or value >= ex["value"]
                    or n - ex["n"] >= EXEMPLAR_WINDOW):
                self._exemplars[hist] = {"trace_id": trace_id,
                                         "value": float(value), "n": n}

    def inc(self, counter: str, by: float = 1) -> None:
        with self._lock:
            self.counters[counter] = self.counters.get(counter, 0) + by

    def inc_shed(self, reason: str) -> None:
        with self._lock:
            self.shed[reason] = self.shed.get(reason, 0) + 1

    def set_gauge(self, gauge: str, value: float) -> None:
        with self._lock:
            self.gauges[gauge] = float(value)

    def span(self, name: str, event_type: str = "UserDefined",
             args: Dict[str, object] = None,
             trace_id: str = None, light: bool = False) -> RecordEvent:
        """A profiler span (``with metrics.span('serving.step'): ...``);
        shows up in the host recorder / xplane trace under
        ``<namespace>.<name>``. ``args``/``trace_id`` flow into the
        chrome-trace event (trace_id=None picks up the ambient trace
        context). ``light=True`` records only inside a profiler capture
        window (see :class:`~paddle_tpu.profiler.record.RecordEvent`) —
        for per-step spans whose flight-ring copies would be pure
        armed-loop cost."""
        return RecordEvent(f"{self.namespace}.{name}", event_type,
                           args=args, trace_id=trace_id, light=light)

    def mark(self, name: str) -> None:
        """Zero-length trace event (shed/cancel/retry markers)."""
        ev = self.span(name)
        ev.begin()
        ev.end()

    # -- export -------------------------------------------------------------

    @property
    def shed_total(self) -> float:
        with self._lock:
            return sum(self.shed.values())

    def exemplars_snapshot(self) -> Dict[str, Dict]:
        """{family: {"trace_id", "value"}} for the worst recent TTFT/ITL/
        e2e/queue-wait observations (exposed on /statusz; the exposition
        text stays exemplar-free — Prometheus 0.0.4 has no exemplar
        syntax and the line validator would reject a nonstandard one)."""
        with self._lock:
            return {k: {"trace_id": v["trace_id"], "value": v["value"]}
                    for k, v in sorted(self._exemplars.items())}

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Nested dict summary (histogram percentiles + counters + gauges)."""
        with self._lock:
            out: Dict[str, Dict[str, float]] = {
                name: h.summary() for name, h in self.histograms.items()}
            out["counters"] = dict(self.counters)
            out["counters"]["requests_shed_total"] = sum(self.shed.values())
            for reason, n in self.shed.items():
                out["counters"][f"requests_shed_total[{reason}]"] = n
            out["gauges"] = dict(self.gauges)
            if self._exemplars:
                out["exemplars"] = {
                    k: {"trace_id": v["trace_id"], "value": v["value"]}
                    for k, v in sorted(self._exemplars.items())}
        return out

    def _prometheus_lines(self) -> List[str]:
        """Exposition lines (assembled by ``observability.format``, the
        single formatter): every histogram as buckets/sum/count plus a
        sibling ``<name>_quantile`` gauge family with exact percentiles,
        counters as ``_total``, gauges as plain gauges."""
        ns = self.namespace
        lines: List[str] = []
        with self._lock:
            for name, h in self.histograms.items():
                lines.extend(_fmt.histogram_lines(
                    f"{ns}_{name}", h,
                    help=f"serving {name} distribution",
                    quantiles=DEFAULT_QUANTILES))
            for name, v in self.counters.items():
                lines.extend(_fmt.counter_lines(f"{ns}_{name}", value=v))
            # labeled per-reason series only: an unlabeled grand-total
            # sibling would double-count sum() queries over the family
            lines.extend(_fmt.counter_lines(
                f"{ns}_requests_shed_total",
                series=[({"reason": r}, n)
                        for r, n in sorted(self.shed.items())]))
            for name, v in self.gauges.items():
                lines.extend(_fmt.gauge_lines(f"{ns}_{name}_gauge", value=v))
        return lines

    def to_prometheus_text(self) -> str:
        """This sink alone as Prometheus exposition text (the registry's
        ``prometheus_text()`` gives the whole process)."""
        return "\n".join(self._prometheus_lines()) + "\n"
