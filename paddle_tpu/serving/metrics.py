"""Serving metrics: latency histograms, utilization gauges, counters.

The serving scheduler records into one :class:`ServingMetrics` sink:

* **TTFT** (time-to-first-token) and **ITL** (inter-token latency)
  histograms per request. Tokens reach the host one decode *chunk* at a
  time (the engine's single fence per round), so ITL shows the chunk
  cadence: the first token of a chunk carries the device round's latency,
  the rest are ~0. That is the true serving profile, not an artifact.
* queue depth, slot and page utilization, sampled once per scheduler step
  (gauge = last value, histogram = distribution over the run).
* counters: submitted/completed/shed (by reason)/cancelled, step retries
  and failures, generated tokens.

Export surfaces:

* :meth:`ServingMetrics.to_prometheus_text` — Prometheus exposition text
  (histogram ``_bucket``/``_sum``/``_count`` plus exact-percentile
  ``_quantile`` gauges as a separate family — mixing quantile samples
  into a histogram family is invalid exposition format) ready for a
  /metrics endpoint or a scrape file.
* trace events — :meth:`ServingMetrics.span` returns a profiler
  ``RecordEvent`` so scheduler phases land in the host-span recorder (and
  in the XLA xplane trace when a profiler capture is active), correlated
  with device activity.

Thread-safe: the scheduler may run ``engine.step`` on a watchdog thread
(step timeouts), so every mutation takes the sink's lock.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..profiler.record import RecordEvent

#: default latency bucket upper bounds (milliseconds)
DEFAULT_BOUNDS_MS: Tuple[float, ...] = (
    0.5, 1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000, 10000)

#: default quantiles reported in summaries and the Prometheus dump
DEFAULT_QUANTILES: Tuple[float, ...] = (0.5, 0.95, 0.99)


class Histogram:
    """Fixed-bucket histogram that also keeps raw samples (ring buffer,
    ``max_samples`` cap) so small/medium runs report *exact* percentiles;
    beyond the cap the ring keeps the most recent window."""

    def __init__(self, bounds: Sequence[float] = DEFAULT_BOUNDS_MS,
                 max_samples: int = 65536):
        self.bounds = tuple(float(b) for b in bounds)
        self.bucket_counts = [0] * (len(self.bounds) + 1)  # +inf bucket
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._samples: List[float] = []
        self._cap = max_samples
        self._sorted: Optional[List[float]] = None   # cache for percentile()

    def record(self, value: float) -> None:
        value = float(value)
        i = 0
        for b in self.bounds:
            if value <= b:
                break
            i += 1
        self.bucket_counts[i] += 1
        if len(self._samples) < self._cap:
            self._samples.append(value)
        else:
            self._samples[self.count % self._cap] = value
        self._sorted = None
        self.count += 1
        self.sum += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)

    def percentile(self, q: float) -> float:
        """Exact percentile over the retained samples (nearest-rank).
        The sort is cached until the next record() so a multi-quantile
        export costs one sort per histogram, not one per quantile — the
        per-token hot path shares the sink's lock with exports."""
        if not self._samples:
            return 0.0
        if self._sorted is None:
            self._sorted = sorted(self._samples)
        ordered = self._sorted
        rank = max(0, min(len(ordered) - 1,
                          int(round(q * (len(ordered) - 1)))))
        return ordered[rank]

    def summary(self, quantiles: Sequence[float] = DEFAULT_QUANTILES
                ) -> Dict[str, float]:
        out = {"count": float(self.count), "sum": self.sum,
               "min": self.min or 0.0, "max": self.max or 0.0,
               "mean": (self.sum / self.count) if self.count else 0.0}
        for q in quantiles:
            out[f"p{int(q * 100)}"] = self.percentile(q)
        return out


class ServingMetrics:
    """Process-local metrics sink for one :class:`ServingScheduler`."""

    def __init__(self, namespace: str = "paddle_serving"):
        self.namespace = namespace
        self._lock = threading.Lock()
        self.histograms: Dict[str, Histogram] = {
            "ttft_ms": Histogram(),
            "itl_ms": Histogram(),
            "e2e_ms": Histogram(),
            "queue_wait_ms": Histogram(),
            "step_ms": Histogram(),
            "queue_depth": Histogram(bounds=(0, 1, 2, 4, 8, 16, 32, 64,
                                             128, 256)),
        }
        self.counters: Dict[str, float] = {
            "requests_submitted_total": 0,
            "requests_completed_total": 0,
            "requests_cancelled_total": 0,
            "step_retries_total": 0,
            "step_failures_total": 0,
            "steps_total": 0,
            "tokens_generated_total": 0,
        }
        #: shed counts keyed by reason ("queue_full", "deadline", ...)
        self.shed: Dict[str, float] = {}
        #: last-value gauges (utilizations in [0, 1], depths in requests)
        self.gauges: Dict[str, float] = {
            "queue_depth": 0.0,
            "slot_utilization": 0.0,
            "page_utilization": 0.0,
            "inflight": 0.0,
            "degraded": 0.0,
        }

    # -- recording ----------------------------------------------------------

    def observe(self, hist: str, value: float) -> None:
        with self._lock:
            self.histograms[hist].record(value)

    def inc(self, counter: str, by: float = 1) -> None:
        with self._lock:
            self.counters[counter] = self.counters.get(counter, 0) + by

    def inc_shed(self, reason: str) -> None:
        with self._lock:
            self.shed[reason] = self.shed.get(reason, 0) + 1

    def set_gauge(self, gauge: str, value: float) -> None:
        with self._lock:
            self.gauges[gauge] = float(value)

    def span(self, name: str, event_type: str = "UserDefined") -> RecordEvent:
        """A profiler span (``with metrics.span('serving.step'): ...``);
        shows up in the host recorder / xplane trace under
        ``<namespace>.<name>``."""
        return RecordEvent(f"{self.namespace}.{name}", event_type)

    def mark(self, name: str) -> None:
        """Zero-length trace event (shed/cancel/retry markers)."""
        ev = self.span(name)
        ev.begin()
        ev.end()

    # -- export -------------------------------------------------------------

    @property
    def shed_total(self) -> float:
        with self._lock:
            return sum(self.shed.values())

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Nested dict summary (histogram percentiles + counters + gauges)."""
        with self._lock:
            out: Dict[str, Dict[str, float]] = {
                name: h.summary() for name, h in self.histograms.items()}
            out["counters"] = dict(self.counters)
            out["counters"]["requests_shed_total"] = sum(self.shed.values())
            for reason, n in self.shed.items():
                out["counters"][f"requests_shed_total[{reason}]"] = n
            out["gauges"] = dict(self.gauges)
        return out

    def to_prometheus_text(self) -> str:
        """Prometheus exposition format: every histogram as ``_bucket``/
        ``_sum``/``_count`` plus a sibling ``<name>_quantile`` gauge
        family with exact percentiles, counters as ``_total``, gauges as
        plain gauges."""
        ns = self.namespace
        lines: List[str] = []
        with self._lock:
            for name, h in self.histograms.items():
                metric = f"{ns}_{name}"
                lines.append(f"# HELP {metric} serving {name} distribution")
                lines.append(f"# TYPE {metric} histogram")
                acc = 0
                for bound, n in zip(h.bounds, h.bucket_counts):
                    acc += n
                    lines.append(
                        f'{metric}_bucket{{le="{bound:g}"}} {acc}')
                lines.append(
                    f'{metric}_bucket{{le="+Inf"}} {h.count}')
                lines.append(f"{metric}_sum {h.sum:g}")
                lines.append(f"{metric}_count {h.count}")
                lines.append(f"# TYPE {metric}_quantile gauge")
                for q in DEFAULT_QUANTILES:
                    lines.append(
                        f'{metric}_quantile{{quantile="{q:g}"}} '
                        f"{h.percentile(q):g}")
            for name, v in self.counters.items():
                metric = f"{ns}_{name}"
                lines.append(f"# TYPE {metric} counter")
                lines.append(f"{metric} {v:g}")
            # labeled per-reason series only: an unlabeled grand-total
            # sibling would double-count sum() queries over the family
            metric = f"{ns}_requests_shed_total"
            lines.append(f"# TYPE {metric} counter")
            for reason, n in sorted(self.shed.items()):
                lines.append(f'{metric}{{reason="{reason}"}} {n:g}')
            for name, v in self.gauges.items():
                metric = f"{ns}_{name}_gauge"
                lines.append(f"# TYPE {metric} gauge")
                lines.append(f"{metric} {v:g}")
        return "\n".join(lines) + "\n"
