"""SLO-aware request scheduler over the continuous-batching engine.

The engine (`paddle_tpu.inference.decoding.ContinuousBatchingEngine`) is a
closed batch loop: fixed decode slots, its own FIFO, one compiled decode
chunk per round. This module adds the request lifecycle a serving runtime
needs on top of it:

* **admission queue** — priority classes (lower number = more urgent),
  FIFO within a class, per-request ``deadline_ms`` and
  ``max_new_tokens``;
* **load shedding** — when queue depth exceeds ``max_queue_depth`` the
  victim is the *lowest-priority, latest-deadline* queued request (a
  no-deadline request sheds before any deadlined peer in the same
  class); queued requests whose deadline lapses before admission are
  shed as ``deadline``;
* **cancellation** — queued or mid-decode; a live cancel retires the
  engine slot immediately and returns its pages to the pool; a request
  parked on the retry/backoff path (``submit(defer_s=...)`` — the fleet
  router's failover resubmissions) cancels idempotently: a later
  promotion tick can never resurrect it;
* **robustness** — optional per-step wall-clock timeout and bounded
  retry-with-exponential-backoff around ``engine.step``; after the retry
  budget is spent the scheduler *degrades gracefully*: every in-flight
  and queued request is drained with a structured
  :class:`~paddle_tpu.serving.stream.ServingError` instead of the loop
  crashing;
* **streaming** — tokens are pushed into each request's
  :class:`~paddle_tpu.serving.stream.TokenStream` as the engine unpacks
  each decode chunk (via the engine's ``token_callback``), so consumers
  see tokens at chunk cadence rather than at final ``collect()``;
* **metrics** — TTFT/ITL/e2e/queue-wait histograms, queue-depth and
  slot/page-utilization samples, shed/cancel/retry counters, plus
  profiler ``RecordEvent`` spans (``paddle_serving.step`` etc.) so
  scheduler phases correlate with device activity in traces;
* **SLOs** — :meth:`ServingScheduler.make_slo_monitor` attaches a
  multi-window burn-rate monitor over the scheduler's own metrics and
  clock; ``step()`` ticks it once per round and a breach sheds part of
  the admission queue through the existing shedding policy (reason
  ``slo``). ``statusz()`` is the diagnostics server's live view, and
  the flight recorder auto-dumps a debug bundle on watchdog timeouts
  and degradation.

Determinism: scheduling order depends only on (priority, arrival order)
and on deadline comparisons against the injected ``clock``; with a fixed
engine seed and a deterministic clock, outputs are reproducible.

Typical single-threaded driving loop::

    sched = ServingScheduler(engine)
    h = sched.submit(prompt, priority=0, deadline_ms=500,
                     on_token=print)
    while sched.pending:
        sched.step(params)
    print(h.stream.result(), sched.metrics.to_prometheus_text())
"""

from __future__ import annotations

import bisect
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..observability.events import emit_event
from ..observability.flight import flight_recorder
from ..observability.journal import journal, journal_armed
from ..observability.memory import (memory_armed, memory_ledger,
                                    pool_occupancy)
from ..observability.step_timer import StepTimer
from ..observability.timeline import span_collector, timeline_armed
from ..observability.timeseries import history_armed
from ..observability.trace import new_trace_id, trace_context
from ..profiler.record import emit_span, emit_spans, make_span, spans_armed
from .metrics import ServingMetrics
from .stream import ServingError, TokenStream


class RequestState:
    """Lifecycle states of a :class:`ServingRequest`."""

    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"
    CANCELLED = "cancelled"
    SHED = "shed"
    FAILED = "failed"


@dataclass
class SchedulerConfig:
    """Scheduler knobs.

    ``max_queue_depth``: admission-queue cap; beyond it the scheduler
    sheds lowest-priority-latest-deadline first.
    ``step_timeout_s``: optional wall-clock budget per ``engine.step``;
    the step runs on a watchdog thread and a timeout counts as a failure
    (the hung attempt itself cannot be interrupted — on real hangs the
    retries exhaust and the scheduler degrades). Two engine steps never
    run concurrently: while a timed-out attempt is still executing,
    retries wait on it instead of launching a second step, and a slow
    attempt that eventually completes counts as the step.
    ``max_step_retries``: failed steps are retried this many times with
    exponential backoff (``retry_backoff_s * retry_backoff_multiplier**i``)
    before the scheduler degrades.
    """

    max_queue_depth: int = 64
    step_timeout_s: Optional[float] = None
    max_step_retries: int = 3
    retry_backoff_s: float = 0.05
    retry_backoff_multiplier: float = 2.0


@dataclass
class ServingRequest:
    """Handle for one submitted request (returned by ``submit``)."""

    rid: int
    prompt: np.ndarray
    priority: int = 0
    deadline_ms: Optional[float] = None
    max_new_tokens: Optional[int] = None
    stream: TokenStream = None
    state: str = RequestState.QUEUED
    engine_rid: Optional[int] = None
    submit_t: float = 0.0
    deadline_t: Optional[float] = None    # absolute, scheduler clock
    first_token_t: Optional[float] = None
    last_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    trace_id: str = ""                    # minted at submit; follows the
    sampler: Any = None                   # SamplerConfig (None = engine
    grammar: Any = None                   # default); TokenDFA constraint
    grammar_prefix: Any = None            # already-emitted tokens to
    # pre-advance the grammar through (failover continuations: the
    # streamed tokens became prompt, so the DFA must resume mid-string)
    token_checksum: Optional[int] = None  # crc32 of the engine-retired
    # tokens, stamped at finish — the journal's engine-side twin of the
    # router's stream checksum (a mismatch localizes divergence to the
    # stream plumbing rather than the decode loop)
    _span: Any = field(default=None, repr=False)  # request across layers
    _submit_ns: int = field(default=0, repr=False)  # perf-clock twin of
    # submit_t (submit_t may come from an injected/fake scheduler clock;
    # trace spans need the real perf_counter_ns timeline)
    _ready_t: float = field(default=0.0, repr=False)   # deferred requests
    _key: tuple = field(default=(), repr=False)        # (priority, seq)
    _no_shed: bool = field(default=False, repr=False)  # remediation: never
    # a queue-cap/SLO shed victim (deadlines still apply)

    @property
    def done(self) -> bool:
        return self.state in (RequestState.DONE, RequestState.CANCELLED,
                              RequestState.SHED, RequestState.FAILED)

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return (self.first_token_t - self.submit_t) * 1e3


class ServingScheduler:
    """Priority/deadline-aware admission + robust step loop over a
    ``ContinuousBatchingEngine`` (see module docstring)."""

    def __init__(self, engine, config: Optional[SchedulerConfig] = None,
                 metrics: Optional[ServingMetrics] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.engine = engine
        self.config = config or SchedulerConfig()
        self.metrics = metrics or ServingMetrics()
        self._clock = clock
        self._sleep = sleep
        self._next_rid = 0
        self._seq = 0                       # FIFO tiebreak within priority
        self._queue: List[ServingRequest] = []   # sorted by (priority, seq)
        self._order: List[tuple] = []            # parallel (priority, seq)
        # deferred admissions (retry/backoff): requests parked here until
        # the clock passes their _ready_t, then promoted into the queue
        # at their original (priority, seq) position
        self._backoff: List[ServingRequest] = []
        self._requests: Dict[int, ServingRequest] = {}
        self._by_engine_rid: Dict[int, ServingRequest] = {}
        self._watchdog: Optional[tuple] = None   # (thread, result box)
        self.step_timer = StepTimer()            # host/device + tokens/s
        # ONE reusable light step-span object: it wraps every scheduler
        # round, so re-building the RecordEvent (+ its namespace
        # f-string) per step would be standing armed-loop cost
        # (RecordEvent begin/end resets make sequential reuse safe)
        self._step_span = self.metrics.span("step", light=True)
        self.degraded = False
        self.slo_monitor = None                  # see attach_slo_monitor
        self.signal_bus = None                   # see attach_signal_bus
        self._slo_shed_fraction = 0.5
        # engine hooks: route chunk tokens / retirements into the streams
        engine.token_callback = self._on_engine_token
        engine.finish_callback = self._on_engine_finish

    def _engine_budget(self, max_new_tokens: Optional[int]) -> int:
        """Per-request new-token budget (override or engine default)."""
        return (max_new_tokens if max_new_tokens is not None
                else self.engine.config.max_new_tokens)

    # -- submission & cancellation ------------------------------------------

    def submit(self, prompt, priority: int = 0,
               deadline_ms: Optional[float] = None,
               max_new_tokens: Optional[int] = None,
               on_token: Optional[Callable[[int], None]] = None,
               defer_s: Optional[float] = None,
               no_shed: bool = False,
               trace_id: Optional[str] = None,
               sampler: Any = None,
               grammar: Any = None,
               grammar_prefix: Any = None) -> ServingRequest:
        """Queue a request. ``priority`` is a class (0 = most urgent, FIFO
        within a class); ``deadline_ms`` is the admission SLO relative to
        now — a request still queued past it is shed; ``max_new_tokens``
        overrides the engine default budget; ``on_token`` streams tokens
        synchronously as chunks unpack. ``defer_s`` parks the request in
        the backoff area until the scheduler clock passes ``now +
        defer_s`` (the retry/backoff path: the fleet router resubmits
        failed-over requests this way); deferred requests keep their
        arrival (priority, FIFO) position, count toward ``pending``, can
        be cancelled, and expire against their deadline like any queued
        request — but are exempt from queue-cap and SLO shedding while
        parked AND after promotion (they are remediation, not fresh
        load; a full queue sheds fresh victims around them, never them).
        ``no_shed`` grants the same exemption to an immediate
        (non-deferred) submission — the router's drain handoffs.
        ``trace_id`` adopts an outer layer's trace identity (the fleet
        router mints one id per router request and passes it through
        every dispatch, failover resubmissions included, so the whole
        path assembles into ONE span tree); None mints a fresh id.
        ``sampler`` (a ``SamplerConfig``) and ``grammar`` (a
        ``TokenDFA``) ride the handle into the engine's in-program
        sampling epilogue; ``grammar_prefix`` pre-advances the grammar
        through tokens already emitted before a failover continuation.
        Returns the request handle (its
        ``.stream`` is the consumption surface). The handle may come back
        already shed if the queue cap evicts it immediately.

        Infeasible requests — prompt + budget beyond the engine's
        ``max_seq_len``, or needing more KV pages than the whole pool
        holds — raise ``ValueError`` here instead of being queued: they
        could never be admitted, and letting them reach the engine would
        either leak a never-closed stream or (for the page case) turn a
        permanent per-request error into repeated step failures that
        degrade the whole scheduler."""
        if self.degraded:
            raise ServingError(
                "engine_failure",
                "scheduler is degraded after repeated step failures; "
                "create a fresh engine+scheduler")
        prompt = np.asarray(prompt, np.int32)
        total = len(prompt) + self._engine_budget(max_new_tokens)
        if total > self.engine.max_seq_len:
            raise ValueError(
                f"prompt of {len(prompt)} tokens + max_new_tokens="
                f"{self._engine_budget(max_new_tokens)} exceeds the "
                f"engine's max_seq_len={self.engine.max_seq_len}; raise "
                "max_seq_len or truncate the prompt")
        mgr = self.engine.mgr
        if mgr.pages_for(total) > mgr.usable_pages:
            raise ValueError(
                f"request of {total} total tokens needs "
                f"{mgr.pages_for(total)} KV pages but the engine pool "
                f"only holds {mgr.usable_pages}; enlarge num_pages or "
                "shrink the request")
        now = self._clock()
        rid = self._next_rid
        self._next_rid += 1
        req = ServingRequest(
            rid=rid, prompt=prompt,
            priority=int(priority), deadline_ms=deadline_ms,
            max_new_tokens=max_new_tokens,
            stream=TokenStream(rid, on_token=on_token),
            submit_t=now,
            deadline_t=None if deadline_ms is None
            else now + deadline_ms / 1e3,
            trace_id=trace_id or new_trace_id("req"),
            sampler=sampler, grammar=grammar,
            grammar_prefix=grammar_prefix)
        req._span = self.metrics.span("request",
                                      args={"request_id": rid},
                                      trace_id=req.trace_id)
        req._span.begin()
        # after begin(): the request envelope starts at or before every
        # phase span, so queue_wait nests inside it in the span tree
        req._submit_ns = time.perf_counter_ns()
        self._requests[rid] = req
        req._key = (req.priority, self._seq)
        self._seq += 1
        self.metrics.inc("requests_submitted_total")
        # deferred (failover) and explicitly-marked (drain handoff)
        # submissions are remediation traffic: exempt from queue-cap/SLO
        # shedding for good
        req._no_shed = bool(no_shed) or (defer_s is not None
                                         and defer_s > 0)
        if defer_s is not None and defer_s > 0:
            req._ready_t = now + defer_s
            self._backoff.append(req)
            return req
        self._enqueue(req)
        self._shed_overflow()
        return req

    def _enqueue(self, req: ServingRequest) -> None:
        i = bisect.bisect(self._order, req._key)
        self._order.insert(i, req._key)
        self._queue.insert(i, req)

    def _promote_backoff(self) -> None:
        """Move due deferred requests into the admission queue. A request
        cancelled (or otherwise finished) while parked here must NEVER be
        re-admitted by this tick — cancel() removes it from the backoff
        list, and the ``done`` filter catches any straggler reference."""
        if not self._backoff:
            return
        now = self._clock()
        due = [r for r in self._backoff
               if now >= r._ready_t and not r.done]
        self._backoff = [r for r in self._backoff
                         if now < r._ready_t and not r.done]
        for req in sorted(due, key=lambda r: r._key):
            self._enqueue(req)
        if due:
            self._shed_overflow()

    def cancel(self, rid: int) -> bool:
        """Cancel a queued or running request; frees its engine slot and
        pages immediately when mid-decode. False if unknown/finished."""
        req = self._requests.get(rid)
        if req is None or req.done:
            return False
        if req.state == RequestState.QUEUED:
            if req in self._backoff:
                # parked on the retry/backoff path: removing it here is
                # what keeps cancel-after-retry idempotent — a later
                # promotion tick must not resurrect it
                self._backoff.remove(req)
            else:
                i = self._queue.index(req)
                self._queue.pop(i)
                self._order.pop(i)
        elif req.state == RequestState.RUNNING:
            self.engine.cancel(req.engine_rid)
            self._by_engine_rid.pop(req.engine_rid, None)
        self._finish(req, RequestState.CANCELLED, "cancelled")
        self.metrics.inc("requests_cancelled_total")
        self.metrics.mark("cancel")
        emit_event("cancel", request_id=req.rid, trace_id=req.trace_id)
        return True

    # -- SLO wiring ---------------------------------------------------------

    def make_slo_monitor(self, ttft_p95_ms: Optional[float] = None,
                         itl_p99_ms: Optional[float] = None,
                         max_shed_ratio: Optional[float] = 0.01,
                         **monitor_kw):
        """Build an :class:`~paddle_tpu.observability.slo.SLOMonitor`
        over THIS scheduler's metrics sink and attach it: TTFT p95 /
        ITL p99 latency objectives (pass thresholds to enable) and a
        "submissions not shed or failed" ratio objective. Extra kwargs
        (windows, burn_threshold, clock) flow to the monitor; the
        scheduler's own clock is the default, so fake-clock tests stay
        deterministic end to end."""
        from ..observability.slo import (SLOMonitor, latency_objective,
                                         ratio_objective)
        m = self.metrics
        objectives = []
        if ttft_p95_ms is not None:
            objectives.append(latency_objective(
                "ttft", lambda: m.histograms["ttft_ms"], ttft_p95_ms,
                target=0.95))
        if itl_p99_ms is not None:
            objectives.append(latency_objective(
                "itl", lambda: m.histograms["itl_ms"], itl_p99_ms,
                target=0.99))
        if max_shed_ratio is not None:
            # exclude reason="slo" sheds: those are the monitor's OWN
            # remediation — counting them as bad events would let a
            # latency breach cascade into a self-inflicted shed breach
            objectives.append(ratio_objective(
                "shed", lambda: m.shed_total - m.shed.get("slo", 0.0)
                + m.counters.get("step_failures_total", 0),
                lambda: m.counters.get("requests_submitted_total", 0),
                target=1.0 - max_shed_ratio))
        if not objectives:
            raise ValueError("no objectives enabled; pass at least one "
                             "of ttft_p95_ms / itl_p99_ms / "
                             "max_shed_ratio")
        monitor_kw.setdefault("clock", self._clock)
        monitor = SLOMonitor(objectives, **monitor_kw)
        self.attach_slo_monitor(monitor)
        return monitor

    def attach_slo_monitor(self, monitor,
                           shed_fraction: float = 0.5) -> None:
        """Wire a monitor into the serving loop: ``step()`` ticks it
        once per round. The breach transition sheds ``shed_fraction``
        of the admission queue (worst victims first — the existing
        load-shedding policy), and for as long as the breach latch
        holds, every step keeps the queue capped at
        ``max_queue_depth * (1 - shed_fraction)`` so refilling traffic
        keeps being trimmed until the objective recovers."""
        self.slo_monitor = monitor
        self._slo_shed_fraction = float(shed_fraction)
        monitor.on_breach = self._on_slo_breach
        monitor.on_recover = self._on_slo_recover

    def attach_signal_bus(self, bus=None, **bus_kw):
        """Wire the sensor plane (ISSUE 11): a
        :class:`~paddle_tpu.observability.signals.SignalBus` over THIS
        scheduler's queue/engine/SLO state, ticked once per step while
        the plane is armed (``timeseries.history_armed`` — one list
        index disarmed; the tick itself is decimated to the bus
        interval). ``bus=None`` builds one on the scheduler's own clock
        so fake-clock tests stay deterministic end to end."""
        if bus is None:
            from ..observability.signals import SignalBus
            bus_kw.setdefault("clock", self._clock)
            bus = SignalBus(**bus_kw)
        bus.attach_scheduler(self)
        self.signal_bus = bus
        return bus

    def _on_slo_breach(self, name: str, state: dict) -> None:
        self.metrics.set_gauge("slo_breached", 1.0)
        self.metrics.mark("slo_breach")
        n_shed = int(len(self._queue) * self._slo_shed_fraction + 0.5)
        shed = 0
        for _ in range(n_shed):
            if not self._shed_worst("slo"):
                break       # only no-shed remediation requests remain
            shed += 1
        if shed:
            emit_event("slo_degrade_shed", slo=name, shed=shed,
                       queue_depth=len(self._queue))

    def _on_slo_recover(self, name: str, state: dict) -> None:
        if not self.slo_monitor.breached():
            self.metrics.set_gauge("slo_breached", 0.0)
        self.metrics.mark("slo_recovered")

    # -- queue policy -------------------------------------------------------

    def _shed_worst(self, reason: str) -> bool:
        """Shed one queued request: lowest priority class (max number),
        then latest deadline (None = +inf sheds first), then latest
        arrival. Remediation requests (``_no_shed`` — the router's
        failover resubmissions) are never victims; False when nothing
        sheddable remains."""
        def badness(iq):
            i, r = iq
            dl = float("inf") if r.deadline_t is None else r.deadline_t
            return (r.priority, dl, self._order[i][1])
        sheddable = [(i, r) for i, r in enumerate(self._queue)
                     if not r._no_shed]
        if not sheddable:
            return False
        i, victim = max(sheddable, key=badness)
        self._queue.pop(i)
        self._order.pop(i)
        self._shed(victim, reason)
        return True

    def _shed_overflow(self, cap: Optional[int] = None,
                       reason: str = "queue_full") -> None:
        if cap is None:
            cap = self.config.max_queue_depth
        while len(self._queue) > cap:
            if not self._shed_worst(reason):
                break       # only remediation left: cap soft-exceeded

    def _expire_deadlines(self) -> None:
        now = self._clock()
        keep_q, keep_o = [], []
        for req, key in zip(self._queue, self._order):
            if req.deadline_t is not None and now > req.deadline_t:
                self._shed(req, "deadline")
            else:
                keep_q.append(req)
                keep_o.append(key)
        self._queue, self._order = keep_q, keep_o
        if self._backoff:
            lapsed = [r for r in self._backoff
                      if r.deadline_t is not None and now > r.deadline_t]
            if lapsed:
                self._backoff = [r for r in self._backoff
                                 if r not in lapsed]
                for req in lapsed:
                    self._shed(req, "deadline")

    def _shed(self, req: ServingRequest, reason: str) -> None:
        self._finish(req, RequestState.SHED, f"shed:{reason}",
                     ServingError(f"shed_{reason}",
                                  f"request {req.rid} shed ({reason})",
                                  rid=req.rid))
        self.metrics.inc_shed(reason)
        self.metrics.mark(f"shed.{reason}")
        emit_event("shed", reason=reason, request_id=req.rid,
                   trace_id=req.trace_id, priority=req.priority)

    def _finish(self, req: ServingRequest, state: str, reason: str,
                error: Optional[ServingError] = None) -> None:
        req.state = state
        req.finish_t = self._clock()
        if (req.engine_rid is None and req._submit_ns
                and spans_armed()):
            # never admitted (queue-cap/SLO/deadline shed, queued
            # cancel): its whole life WAS queue wait — emit the segment
            # retroactively so the timeline attributes the shed latency
            emit_span(f"{self.metrics.namespace}.queue_wait",
                      req._submit_ns, time.perf_counter_ns(),
                      trace_id=req.trace_id,
                      args={"request_id": req.rid})
        req.stream.close(reason, error)
        if req._span is not None:
            req._span.end()
            req._span = None
        # evict from the registry or a long-running server leaks every
        # prompt/stream ever submitted; the caller keeps the handle
        self._requests.pop(req.rid, None)

    # -- the serving loop ---------------------------------------------------

    @property
    def pending(self) -> int:
        """Requests still queued, parked in backoff, or mid-decode."""
        return (len(self._queue) + len(self._backoff)
                + len(self._by_engine_rid))

    @property
    def active(self) -> int:
        """Requests the engine can make progress on THIS step (queued or
        mid-decode; deferred backoff requests excluded)."""
        return len(self._queue) + len(self._by_engine_rid)

    @property
    def queue_depth(self) -> int:
        """Admission pressure: queued + deferred-backoff requests (the
        fleet router's per-decision load signal — O(1), unlike the full
        ``statusz()`` document)."""
        return len(self._queue) + len(self._backoff)

    @property
    def inflight(self) -> int:
        """Requests currently decoding in engine slots."""
        return len(self._by_engine_rid)

    def step(self, params) -> int:
        """One scheduler round: expire deadlines, admit into free slots,
        run a robust engine step, account. Returns ``pending``.

        Ordinary engine exceptions stay inside the retry/degrade
        machinery; a non-``Exception`` ``BaseException`` (KeyboardInterrupt,
        SystemExit, a fatal runtime death) would otherwise fly past it
        and leave every consumer stream blocked forever — those drain the
        scheduler (terminal errors on every stream) and re-raise."""
        if self.degraded:
            return 0
        try:
            self._step_inner(params)
        except BaseException as e:
            if not isinstance(e, Exception):
                self._degrade(e)
            raise
        return self.pending

    def _step_inner(self, params) -> None:
        # each scheduler round gets its own trace id, so the step's op
        # dispatches correlate in the chrome trace (per-request lanes use
        # the request trace ids minted at submit)
        with trace_context(step=int(self.metrics.counters.get(
                "steps_total", 0))):
            # light + reused: the step span fires per scheduler round —
            # it records under a profiler capture window but skips the
            # flight ring (it would wrap the whole ring in <1s and its
            # HostSpan cost is THE per-step armed overhead; step timing
            # already lives in step_ms / StepTimer)
            with self._step_span:
                # expire BEFORE promoting: a deferred request whose
                # deadline lapsed while parked must shed as "deadline",
                # not first enter the queue (its no_shed exemption would
                # wrongfully push a viable fresh request over the cap)
                self._expire_deadlines()
                self._promote_backoff()
                self._admit()
                if self._by_engine_rid:
                    t0 = self._clock()
                    tokens_before = self.metrics.counters.get(
                        "tokens_generated_total", 0)
                    self.step_timer.begin()
                    ok = self._robust_step(params)
                    self.step_timer.end(
                        tokens=int(self.metrics.counters.get(
                            "tokens_generated_total", 0) - tokens_before))
                    self.metrics.observe("step_ms",
                                         (self._clock() - t0) * 1e3)
                    self.metrics.inc("steps_total")
                    if ok:
                        self.engine.collect()   # streams own the tokens
                self._sample_gauges()
                if self.slo_monitor is not None:
                    self.slo_monitor.tick()
                    if self.slo_monitor.breached():
                        # level-triggered remediation: the breach
                        # transition shed once, but refilling traffic
                        # must keep being trimmed while the latch holds
                        cap = int(self.config.max_queue_depth
                                  * (1 - self._slo_shed_fraction)) or 1
                        self._shed_overflow(cap=cap, reason="slo")
                if self.signal_bus is not None and history_armed[0]:
                    # sensor plane: decimated inside tick() — the common
                    # per-step cost is one clock read + compare
                    self.signal_bus.tick()

    def run(self, params, max_steps: Optional[int] = None) -> None:
        """Drive ``step`` until every request resolves (or degradation)."""
        steps = 0
        while self.pending and not self.degraded:
            self.step(params)
            steps += 1
            if self.pending and max_steps is not None \
                    and steps >= max_steps:
                raise RuntimeError(
                    f"serving loop exceeded max_steps={max_steps} with "
                    f"{self.pending} requests pending")
            if self.pending and self.active == 0:
                # only deferred backoff requests remain: nothing is
                # progressable until the clock passes the earliest ready
                # time — sleep straight to it instead of hot-spinning
                # (and exhausting max_steps on no-op rounds)
                wait = (min(r._ready_t for r in self._backoff)
                        - self._clock())
                if wait > 0:
                    self._sleep(wait)

    def _admit(self) -> None:
        """Feed the engine only requests it can place THIS step — a free
        slot AND enough free KV pages — in (priority, FIFO) order. The
        engine's internal FIFO must stay empty or priority inversions
        sneak in behind it: a request parked there (slot free but pages
        scarce) would be served before any later, higher-priority
        submission the moment pages return.

        Page math is unchanged by the unified ragged step, but the wave
        assumption is gone: an admission handed over here joins the
        engine's CURRENT step's ragged batch (prefill rides the same
        single dispatch as everyone's decode) instead of waiting for a
        bucketed prefill wave, so admission latency is one step, not one
        wave boundary."""
        if not self._queue:
            return              # steady decode: nothing to admit, and
        # the span/byte prelude below is armed-loop cost per step
        now = self._clock()
        armed = spans_armed()
        mgr = self.engine.mgr
        headroom = self.engine.num_free_slots - self.engine.num_queued
        free_pages = mgr.num_free_pages
        page_b = mgr.page_nbytes if armed else 0   # span-args byte unit
        cache = getattr(self.engine, "cache", None)
        protect: List[int] = []     # pages THIS step's admissions rely on
        while headroom > 0 and self._queue:
            req = self._queue[0]
            adm0_ns = time.perf_counter_ns() if armed else 0
            need = mgr.pages_for(
                len(req.prompt) + self._engine_budget(req.max_new_tokens))
            n_shared = 0
            reusing: List[int] = []
            if cache is not None:
                # charge only the UNCACHED SUFFIX: pages the prefix cache
                # will lend come for free (peek: no LRU/stat distortion);
                # the COW source isn't charged for but must survive too
                shareable, _cached_tokens, cow_src = cache.peek(req.prompt)
                n_shared = len(shareable)
                need -= n_shared
                reusing = shareable + ([cow_src] if cow_src is not None
                                       else [])
                if need > free_pages:
                    # reclaim cold cached pages before deferring — but
                    # never pages an admission already charged against
                    # this step (their refcounts rise only when the
                    # engine allocates), nor this request's own match
                    free_pages += cache.evict(need - free_pages,
                                              protect=protect + reusing)
            if need > free_pages:
                # deferred for pages: record the shortfall instead of
                # silently waiting — the rejects counter is ROADMAP item
                # 4's honest pressure signal, the oom_pressure event
                # carries the bytes short (deduped per blocked request)
                memory_ledger.note_admission_reject(
                    mgr, request_id=req.rid, need_pages=need,
                    free_pages=free_pages, trace_id=req.trace_id)
                break               # wait for a completion to free pages
            protect.extend(reusing)
            self._queue.pop(0)
            self._order.pop(0)
            req.engine_rid = self.engine.submit(
                req.prompt, max_new_tokens=req.max_new_tokens,
                trace_id=req.trace_id, sampler=req.sampler,
                grammar=req.grammar, grammar_prefix=req.grammar_prefix)
            req.state = RequestState.RUNNING
            self._by_engine_rid[req.engine_rid] = req
            if journal_armed[0]:
                # the scheduler rid <-> engine rid binding: lets replay
                # correlate outcome frames with engine-side checksums
                journal.note_admit(srid=req.rid,
                                   engine_rid=req.engine_rid,
                                   ns=self.metrics.namespace)
            if armed:
                # two non-overlapping timeline segments, one batch:
                # queued until this admission pass picked the request
                # up, then the admission work itself (cache peek/evict,
                # allocation, engine handover). The admission span and
                # the request envelope both carry the HBM attribution
                # (total pages held, cached-vs-fresh bytes) so /tracez
                # shows a request's memory cost next to its latency.
                ns = self.metrics.namespace
                if req._span is not None and req._span.args is not None:
                    req._span.args.update(
                        kv_pages=need + n_shared,
                        cached_bytes=n_shared * page_b,
                        fresh_bytes=need * page_b)
                emit_spans([
                    make_span(f"{ns}.queue_wait", req._submit_ns,
                              adm0_ns, trace_id=req.trace_id,
                              args={"request_id": req.rid}),
                    make_span(f"{ns}.admission", adm0_ns,
                              time.perf_counter_ns(),
                              trace_id=req.trace_id,
                              args={"request_id": req.rid,
                                    "kv_pages": need + n_shared,
                                    "cached_bytes": n_shared * page_b,
                                    "fresh_bytes": need * page_b}),
                ])
            self.metrics.observe("queue_wait_ms",
                                 (now - req.submit_t) * 1e3,
                                 trace_id=req.trace_id)
            headroom -= 1
            free_pages -= need

    # -- robustness ---------------------------------------------------------

    def _robust_step(self, params) -> bool:
        """engine.step with timeout + bounded exponential backoff; on
        exhaustion degrade (drain everything with a structured error)
        instead of raising. True if the step eventually succeeded."""
        cfg = self.config
        delay = cfg.retry_backoff_s
        last_err: Optional[BaseException] = None
        for attempt in range(cfg.max_step_retries + 1):
            try:
                self._timed_step(params)
                return True
            except Exception as e:              # noqa: BLE001 - rethrown
                last_err = e
                self.metrics.inc("step_failures_total")
                if attempt < cfg.max_step_retries:
                    self.metrics.inc("step_retries_total")
                    self.metrics.mark("step_retry")
                    emit_event("step_retry", attempt=attempt + 1,
                               error=repr(e), backoff_s=delay)
                    self._sleep(delay)
                    delay *= cfg.retry_backoff_multiplier
        self._degrade(last_err)
        return False

    def _timed_step(self, params) -> None:
        timeout = self.config.step_timeout_s
        if timeout is None:
            self.engine.step(params)
            return
        if self._watchdog is not None:
            prev, prev_box = self._watchdog
            if prev.is_alive():
                # a timed-out attempt is still executing inside the
                # engine; NEVER start a second concurrent engine.step
                # (they would race on slots/pages/rng). Spend this
                # attempt's budget waiting for the straggler instead.
                prev.join(timeout)
            if prev.is_alive():
                raise ServingError(
                    "engine_failure",
                    f"engine.step still running past another "
                    f"step_timeout_s={timeout} window; refusing a "
                    "concurrent step")
            self._watchdog = None
            if "error" in prev_box:
                raise prev_box["error"]
            return          # straggler completed: that WAS the step
        box: Dict[str, Any] = {}

        def worker():
            try:
                box["result"] = self.engine.step(params)
            except BaseException as e:          # noqa: BLE001 - rethrown
                box["error"] = e

        t = threading.Thread(target=worker, daemon=True,
                             name="serving-step")
        t.start()
        t.join(timeout)
        if t.is_alive():
            self._watchdog = (t, box)
            flight_recorder.auto_dump("watchdog_timeout")
            raise ServingError(
                "engine_failure",
                f"engine.step exceeded step_timeout_s={timeout}")
        if "error" in box:
            raise box["error"]

    def _degrade(self, err: Optional[BaseException]) -> None:
        """Repeated step failure: drain every in-flight and queued request
        with a structured error; the loop survives, the scheduler refuses
        new work."""
        self.degraded = True
        self.metrics.set_gauge("degraded", 1.0)
        self.metrics.mark("degraded")
        emit_event("degraded", error=repr(err) if err else None,
                   inflight=len(self._by_engine_rid),
                   queued=len(self._queue))
        # postmortem while the torn state is still inspectable (no-op
        # unless the flight recorder is armed with a dump dir)
        flight_recorder.auto_dump("engine_step_failure")
        cause = f": {err}" if err is not None else ""
        for req in list(self._by_engine_rid.values()):
            try:
                self.engine.cancel(req.engine_rid)  # reclaim slot + pages
            except Exception:   # noqa: BLE001 - engine state may be torn
                pass
            self._finish(req, RequestState.FAILED, "failed",
                         ServingError("engine_failure",
                                      f"engine step failed repeatedly"
                                      f"{cause}", rid=req.rid))
        self._by_engine_rid.clear()
        for req in self._queue + self._backoff:
            self._finish(req, RequestState.FAILED, "failed",
                         ServingError("engine_failure",
                                      f"engine degraded before admission"
                                      f"{cause}", rid=req.rid))
        self._queue.clear()
        self._order.clear()
        self._backoff.clear()

    # -- engine hook targets ------------------------------------------------

    def _on_engine_token(self, engine_rid: int, token: int) -> None:
        req = self._by_engine_rid.get(engine_rid)
        if req is None:
            return
        now = self._clock()
        if req.first_token_t is None:
            req.first_token_t = now
            self.metrics.observe("ttft_ms", (now - req.submit_t) * 1e3,
                                 trace_id=req.trace_id)
        else:
            self.metrics.observe("itl_ms",
                                 (now - req.last_token_t) * 1e3,
                                 trace_id=req.trace_id)
        req.last_token_t = now
        self.metrics.inc("tokens_generated_total")
        req.stream.push(int(token))

    def _on_engine_finish(self, engine_rid: int, tokens: list) -> None:
        req = self._by_engine_rid.pop(engine_rid, None)
        if req is None:
            return
        req.token_checksum = self.engine.finished_checksum(engine_rid)
        self._finish(req, RequestState.DONE, "complete")
        self.metrics.inc("requests_completed_total")
        self.metrics.observe("e2e_ms",
                             (req.finish_t - req.submit_t) * 1e3,
                             trace_id=req.trace_id)

    # -- accounting ---------------------------------------------------------

    def _sample_gauges(self) -> None:
        m = self.metrics
        depth = len(self._queue)
        m.set_gauge("queue_depth", depth)
        m.observe("queue_depth", depth)
        m.set_gauge("inflight", len(self._by_engine_rid))
        slots = self.engine.num_slots
        m.set_gauge("slot_utilization",
                    (slots - self.engine.num_free_slots) / slots)
        # ONE occupancy derivation (observability.memory.pool_occupancy):
        # these gauges, the signal bus's pool-pressure reader and the
        # ledger's byte split all read the same math, so /metrics and
        # the autoscaler can never disagree about what "full" means
        occ = pool_occupancy(self.engine.mgr)
        m.set_gauge("page_utilization", occ["pressure"])
        cache = getattr(self.engine, "cache", None)
        if cache is not None:
            # cached-vs-live split: how much of the occupied pool is
            # reusable cache vs pinned by in-flight sequences
            m.set_gauge("live_page_utilization", occ["live_utilization"])
            m.set_gauge("cached_page_utilization",
                        occ["cached_utilization"])
            cache.update_gauges()

    def statusz(self) -> Dict[str, Any]:
        """Live scheduler state for the diagnostics server's /statusz:
        queue composition, engine slot/page occupancy, lifecycle
        counters, step timing."""
        per_priority: Dict[int, int] = {}
        for req in self._queue:
            per_priority[req.priority] = per_priority.get(req.priority,
                                                          0) + 1
        mgr = self.engine.mgr
        out: Dict[str, Any] = {
            "queued": len(self._queue),
            "queued_by_priority": {str(k): v for k, v in
                                   sorted(per_priority.items())},
            "backoff": len(self._backoff),
            "inflight": len(self._by_engine_rid),
            "degraded": self.degraded,
            "slots": {"total": self.engine.num_slots,
                      "free": self.engine.num_free_slots},
            "pages": {"usable": mgr.usable_pages,
                      "free": mgr.num_free_pages},
            "counters": dict(self.metrics.counters),
            "shed": dict(self.metrics.shed),
            "step_ms": self.step_timer.step_ms.summary(),
            "tokens_per_s": self.step_timer.tokens_per_s,
        }
        cache = getattr(self.engine, "cache", None)
        if cache is not None:
            out["pages"]["live"] = mgr.num_live_pages
            out["pages"]["cached"] = mgr.num_cached_pages
            out["prefix_cache"] = cache.snapshot()
        spec = getattr(self.engine, "spec", None)
        if spec is not None:
            # speculation health (drafted/accepted/acceptance ratio):
            # /statusz and the router's fleet view surface it per engine
            out["speculation"] = spec.snapshot()
        ex = self.metrics.exemplars_snapshot()
        if ex:
            # the worst recent TTFT/ITL/e2e observation, each carrying
            # the trace id to pull from /tracez — histogram families
            # alone can't answer "WHICH request was the p99"
            out["exemplars"] = ex
        if timeline_armed[0]:
            # slowest-requests table (trace id, e2e, exclusive
            # critical-path segments) from the span collector; the full
            # trees live on /tracez
            out["slowest_requests"] = span_collector.slowest()
        if self.slo_monitor is not None:
            out["slo"] = self.slo_monitor.states()
        if self.signal_bus is not None:
            # smoothed signal values + windowed trends (the full series
            # and anomaly document lives on /varz)
            out["signals"] = self.signal_bus.values()
        if memory_armed[0]:
            # HBM ledger summary (class bytes + planner verdicts); the
            # per-request page table lives on /memz
            out["memory"] = memory_ledger.statusz()
        return out
