"""One engine replica inside a router fleet.

A :class:`ReplicaHandle` bundles everything the
:class:`~paddle_tpu.serving.router.FleetRouter` needs to own about a
single ``ContinuousBatchingEngine``: its :class:`~.scheduler.
ServingScheduler` (admission, retry, streaming), a per-replica
:class:`~.health.HealthTracker` (the circuit breaker the router drives),
the replica's share of the fleet metrics (its scheduler metrics register
under ``paddle_serving_r<id>``), and a deterministic chaos surface.

The chaos surface is how router chaos tests stay reproducible without
real crashes or real hangs:

* :meth:`kill` — every subsequent :meth:`step` raises
  :class:`ReplicaFault` before touching the engine (a dead replica);
* :meth:`stall` — steps raise for a wall-clock window on the injected
  clock (a hung step after the watchdog flags it), then recover;
* :meth:`slow` — steps sleep extra for a window (a straggler), then
  recover.

Faults raise *before* the scheduler runs, so the replica's engine state
stays coherent: in-flight sequences freeze rather than tear, which is
exactly what lets the router cancel + fail them over and lets a stalled
replica resume cleanly after re-admission.

Everything the router (or an operator surface) consumes is public —
``submit``/``cancel``/``step``, ``statusz()``, ``queue_depth``/
``inflight``/``pending``, ``health``, ``draining``. The scheduler and
fault cell are private; ``tests/test_observability_lint.py`` enforces
that nothing outside ``paddle_tpu/serving/`` reaches into them.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

from .health import HealthConfig, HealthTracker
from .metrics import ServingMetrics
from .scheduler import SchedulerConfig, ServingRequest, ServingScheduler


class ReplicaFault(RuntimeError):
    """Injected replica-level failure (chaos: die / stall)."""


class ReplicaHandle:
    """See module docstring."""

    def __init__(self, replica_id: int, engine,
                 config: Optional[SchedulerConfig] = None,
                 health_config: Optional[HealthConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.replica_id = int(replica_id)
        self.engine = engine
        self._clock = clock
        self._sleep = sleep
        self._scheduler = ServingScheduler(
            engine, config,
            metrics=ServingMetrics(
                namespace=f"paddle_serving_r{self.replica_id}"),
            clock=clock, sleep=sleep)
        self.health = HealthTracker(health_config, clock=clock)
        spec = getattr(engine, "spec", None)
        if spec is not None:
            # stamp the replica id into the engine's paddle_spec_* label
            # so fleet-wide speculation metrics split per replica
            spec.replica = str(self.replica_id)
        self.draining = False
        self.drained_event_sent = False     # router's once-only latch
        self._fault: Optional[tuple] = None  # ("die",) | ("stall", t_end)
        #                                    # | ("slow", t_end, delay_s)

    # -- request lifecycle (delegated to the scheduler) ---------------------

    def submit(self, prompt, priority: int = 0,
               deadline_ms: Optional[float] = None,
               max_new_tokens: Optional[int] = None,
               on_token: Optional[Callable[[int], None]] = None,
               defer_s: Optional[float] = None,
               no_shed: bool = False,
               trace_id: Optional[str] = None,
               sampler: Any = None,
               grammar: Any = None,
               grammar_prefix: Any = None) -> ServingRequest:
        return self._scheduler.submit(
            prompt, priority=priority, deadline_ms=deadline_ms,
            max_new_tokens=max_new_tokens, on_token=on_token,
            defer_s=defer_s, no_shed=no_shed, trace_id=trace_id,
            sampler=sampler, grammar=grammar,
            grammar_prefix=grammar_prefix)

    def cancel(self, rid: int) -> bool:
        return self._scheduler.cancel(rid)

    def step(self, params) -> int:
        """One scheduler round — after the chaos gate. Dead/stalled
        replicas raise :class:`ReplicaFault` here (the router records
        the failure); slow replicas pay their extra latency first."""
        f = self._fault
        if f is not None:
            kind = f[0]
            if kind == "die":
                raise ReplicaFault(
                    f"replica {self.replica_id} is dead")
            if kind == "stall":
                if self._clock() < f[1]:
                    raise ReplicaFault(
                        f"replica {self.replica_id} step stalled past "
                        "the watchdog")
                self._fault = None
            elif kind == "slow":
                if self._clock() < f[1]:
                    self._sleep(f[2])
                else:
                    self._fault = None
        return self._scheduler.step(params)

    # -- router-facing state ------------------------------------------------

    @property
    def default_max_new_tokens(self) -> int:
        return self.engine.config.max_new_tokens

    @property
    def pending(self) -> int:
        """Unresolved requests on this replica (incl. deferred backoff)."""
        return self._scheduler.pending

    @property
    def active(self) -> int:
        """Requests a step can progress right now (queued or decoding)."""
        return self._scheduler.active

    @property
    def inflight(self) -> int:
        return self._scheduler.inflight

    @property
    def queue_depth(self) -> int:
        return self._scheduler.queue_depth

    @property
    def progress_marker(self) -> tuple:
        """Changes whenever the replica does useful work (tokens
        generated, requests completed, active-work level). The router
        refreshes the health watchdog only when this moves while busy —
        a wedged replica whose steps return without serving anything
        still trips the watchdog."""
        c = self._scheduler.metrics.counters
        return (c.get("tokens_generated_total", 0),
                c.get("requests_completed_total", 0),
                self._scheduler.active)

    @property
    def degraded(self) -> bool:
        """The scheduler spent its retry budget: this replica needs a
        fresh engine + handle (``FleetRouter.replace_replica``)."""
        return self._scheduler.degraded

    @property
    def slo_monitor(self):
        return self._scheduler.slo_monitor

    def make_slo_monitor(self, **kw):
        """Per-replica SLOs (see ``ServingScheduler.make_slo_monitor``);
        the router folds the monitor's health into routing weights."""
        return self._scheduler.make_slo_monitor(**kw)

    def statusz(self) -> Dict[str, Any]:
        """The scheduler's live view plus replica identity, breaker
        state and chaos status — one entry of the router's fleet view."""
        out = self._scheduler.statusz()
        out["replica_id"] = self.replica_id
        out["health"] = self.health.snapshot()
        out["draining"] = self.draining
        if self._fault is not None:
            out["injected_fault"] = self._fault[0]
        return out

    def journal_spec(self) -> Dict[str, Any]:
        """This replica's slice of a journal head frame: the exact
        constructor geometry :mod:`~paddle_tpu.observability.replay`
        needs to rebuild an identical engine + scheduler + breaker.
        Lives here (not in replay) so the journal never reaches into
        ``._scheduler``/``._fault`` from outside ``serving/``."""
        from dataclasses import asdict
        eng = self.engine
        return {
            "replica_id": self.replica_id,
            "engine": {
                "num_slots": eng.num_slots,
                "page_size": eng.page_size,
                "chunk": eng.chunk,
                "max_seq_len": eng.max_seq_len,
                "num_pages": eng.mgr.num_pages,
                "prefix_cache": eng.cache is not None,
                "speculative": eng._speculative,
                "spec_k": eng.spec_k,
                "unified": eng._unified,
            },
            "generation": asdict(eng.config),
            "scheduler": asdict(self._scheduler.config),
            "health": asdict(self.health.config),
        }

    # -- chaos surface (deterministic fault injection) ----------------------

    def kill(self) -> None:
        """Permanent death: every later step raises. Only
        ``FleetRouter.replace_replica`` brings the slot back."""
        self._fault = ("die",)

    def stall(self, duration_s: float) -> None:
        """Steps raise until ``duration_s`` passes on the injected
        clock, then the replica serves again (the re-admission path)."""
        self._fault = ("stall", self._clock() + float(duration_s))

    def slow(self, duration_s: float, delay_s: float) -> None:
        """Each step sleeps ``delay_s`` extra until ``duration_s``
        passes — a straggler the load-aware router routes around."""
        self._fault = ("slow", self._clock() + float(duration_s),
                       float(delay_s))
