"""Per-replica failure detection: a circuit breaker over step outcomes.

The fleet router (:mod:`.router`) owns N engine replicas and needs a
local, deterministic answer to "is this replica safe to route to?". A
:class:`HealthTracker` derives it from two signals only — consecutive
step failures and a watchdog on the time since the last successful step
— through the classic circuit-breaker state machine:

``HEALTHY`` → (failures ≥ ``suspect_after``) → ``SUSPECT`` →
(failures ≥ ``eject_after``) → ``EJECTED`` → (``probe_cooldown_s``
elapses) → ``HALF_OPEN`` → one probe request → back to ``HEALTHY`` on
probe success, or back to ``EJECTED`` with the cooldown doubled on
probe failure (bounded by ``max_cooldown_s``).

* ``SUSPECT`` replicas still serve (the router deprioritizes them);
  one successful step returns them to ``HEALTHY``.
* ``EJECTED`` replicas receive no traffic at all.
* ``HALF_OPEN`` admits **exactly one** request — the probe. Only the
  probe *completing* closes the circuit (``record_probe_success``); a
  trivially successful idle step must not re-admit a replica whose
  failures show up only under load.

Time is an injected ``clock`` (the router shares one clock across the
fleet), so chaos tests driving a fake clock get byte-deterministic
transitions. The tracker holds no engine references — it is pure state,
and the router translates transitions into ejection/drain/failover.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional


class ReplicaState:
    """Circuit-breaker states (see module docstring)."""

    HEALTHY = "healthy"
    SUSPECT = "suspect"
    EJECTED = "ejected"
    HALF_OPEN = "half_open"


#: numeric codes for the ``paddle_router_replica_state`` gauge (the
#: router adds 4 for draining and 5 for fully drained replicas)
STATE_CODE: Dict[str, int] = {
    ReplicaState.HEALTHY: 0,
    ReplicaState.SUSPECT: 1,
    ReplicaState.EJECTED: 2,
    ReplicaState.HALF_OPEN: 3,
}


@dataclass
class HealthConfig:
    """Breaker thresholds.

    ``suspect_after``/``eject_after``: consecutive step failures before
    the respective transition. ``watchdog_s``: a replica with work
    pending and no successful step for this long counts one failure per
    check (None disables). ``probe_cooldown_s``: EJECTED → HALF_OPEN
    delay; each failed probe multiplies it by ``cooldown_multiplier`` up
    to ``max_cooldown_s``.
    """

    suspect_after: int = 1
    eject_after: int = 3
    watchdog_s: Optional[float] = None
    probe_cooldown_s: float = 1.0
    cooldown_multiplier: float = 2.0
    max_cooldown_s: float = 60.0


class HealthTracker:
    """See module docstring. One per :class:`~paddle_tpu.serving.replica.
    ReplicaHandle`; every mutation returns the (possibly unchanged)
    state so the caller can act on transition edges."""

    def __init__(self, config: Optional[HealthConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config or HealthConfig()
        if self.config.suspect_after > self.config.eject_after:
            raise ValueError("suspect_after must be <= eject_after")
        self._clock = clock
        self.state = ReplicaState.HEALTHY
        self.consecutive_failures = 0
        self.failures_total = 0
        self.ejections_total = 0
        self.last_failure: Optional[str] = None
        self.last_ok_t: float = clock()
        self._ejected_t: Optional[float] = None
        self._cooldown = self.config.probe_cooldown_s

    # -- signals ------------------------------------------------------------

    def record_success(self) -> str:
        """A step completed. Clears the failure streak; SUSPECT heals to
        HEALTHY. HALF_OPEN stays HALF_OPEN — only the probe request
        completing (:meth:`record_probe_success`) closes the circuit."""
        self.consecutive_failures = 0
        self.last_ok_t = self._clock()
        if self.state == ReplicaState.SUSPECT:
            self.state = ReplicaState.HEALTHY
        return self.state

    def record_probe_success(self) -> str:
        """The HALF_OPEN probe request completed: close the circuit and
        reset the cooldown backoff."""
        self.consecutive_failures = 0
        self.last_ok_t = self._clock()
        self.state = ReplicaState.HEALTHY
        self._cooldown = self.config.probe_cooldown_s
        self._ejected_t = None
        return self.state

    def record_failure(self, reason: str = "") -> str:
        """A step failed (raised / timed out / watchdog). HALF_OPEN goes
        straight back to EJECTED with the cooldown doubled."""
        cfg = self.config
        self.consecutive_failures += 1
        self.failures_total += 1
        self.last_failure = reason or None
        if self.state == ReplicaState.HALF_OPEN:
            self._eject()
            self._cooldown = min(self._cooldown * cfg.cooldown_multiplier,
                                 cfg.max_cooldown_s)
        elif self.state != ReplicaState.EJECTED:
            if self.consecutive_failures >= cfg.eject_after:
                self._eject()
            elif self.consecutive_failures >= cfg.suspect_after:
                self.state = ReplicaState.SUSPECT
        return self.state

    def force_eject(self, reason: str = "") -> str:
        """Immediate ejection regardless of the failure streak (the
        router uses this when a replica's scheduler degrades: that state
        is unrecoverable without a fresh engine)."""
        self.last_failure = reason or None
        self.failures_total += 1
        if self.state != ReplicaState.EJECTED:
            self._eject()
        return self.state

    def _eject(self) -> None:
        self.state = ReplicaState.EJECTED
        self.ejections_total += 1
        self._ejected_t = self._clock()

    def check_watchdog(self, busy: bool) -> bool:
        """True (and one failure recorded) when the replica has work but
        no successful step within ``watchdog_s``. Call once per router
        step, before stepping the replica."""
        w = self.config.watchdog_s
        if (w is None or not busy
                or self.state == ReplicaState.EJECTED):
            return False
        now = self._clock()
        if now - self.last_ok_t <= w:
            return False
        self.record_failure(f"watchdog: no successful step in {w:g}s")
        # restart the window: ONE failure per silent watchdog period —
        # without this, a replica whose steps also raise would be
        # double-charged every step and eject at half the configured
        # threshold
        self.last_ok_t = now
        return True

    def tick(self) -> str:
        """Advance the cooldown: EJECTED becomes HALF_OPEN once
        ``cooldown`` seconds have passed since ejection. The watchdog
        window restarts at that transition — ``last_ok_t`` froze while
        the replica sat ejected (unstepped), and judging the probe
        against that stale stamp would kill it before it ever ran."""
        if (self.state == ReplicaState.EJECTED
                and self._ejected_t is not None
                and self._clock() - self._ejected_t >= self._cooldown):
            self.state = ReplicaState.HALF_OPEN
            self.last_ok_t = self._clock()
        return self.state

    # -- derived ------------------------------------------------------------

    @property
    def accepting(self) -> bool:
        """Routable under normal policy (HALF_OPEN only takes the probe)."""
        return self.state in (ReplicaState.HEALTHY, ReplicaState.SUSPECT)

    @property
    def cooldown_s(self) -> float:
        return self._cooldown

    def snapshot(self) -> Dict[str, object]:
        """JSON-able state for /statusz and debug bundles."""
        return {
            "state": self.state,
            "consecutive_failures": self.consecutive_failures,
            "failures_total": self.failures_total,
            "ejections_total": self.ejections_total,
            "last_failure": self.last_failure,
            "cooldown_s": self._cooldown,
        }
