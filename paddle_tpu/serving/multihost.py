"""Multi-host serving: engine *processes* behind one router, with DCN
page migration, heartbeat health and cross-host failover.

The fleet tier (:mod:`.router`) fronts N in-process engine replicas; a
real deployment fronts N engine **hosts** — separate processes (in prod,
separate machines) that can die without taking the router with them, and
whose KV pages can *move*: a graceful drain migrates each live request's
pages over DCN to a sibling so the continuation prefills only the tail
instead of recomputing the whole prefix. This module is that tier:

* :class:`HostServer` — runs *inside* the engine process: one
  ``ContinuousBatchingEngine`` (+ prefix cache) under a
  ``ServingScheduler``, answering wire-framed commands (:mod:`.wire`):
  ``hello`` / ``submit`` / ``step`` / ``cancel`` / ``export_flight`` /
  ``import_prefix`` / ``statusz`` / ``shutdown``.
* :class:`PipeTransport` — a real child process (``multiprocessing``
  spawn + pipe); :class:`LocalTransport` — the same server in-process,
  still round-tripping every frame through the encoder so wire coverage
  is identical while tests stay single-process and fake-clocked.
* :class:`HostEndpoint` — the client half: per-call timeout, bounded
  retry with exponential backoff, stale-reply discard (message ids),
  injectable link latency, and a liveness probe
  (:meth:`HostEndpoint.alive`) that consumer ``TokenStream``\\ s poll so
  a blocked reader of a dead host terminates with a structured
  ``ServingError("producer_dead")`` instead of hanging.
* :class:`HostHandle` — duck-types :class:`~.replica.ReplicaHandle` so
  :class:`HostFleetRouter` IS a :class:`~.router.FleetRouter`: the
  ``step`` RPC doubles as the heartbeat (a missed beat is a recorded
  failure; consecutive misses walk the ``HealthTracker`` HEALTHY →
  SUSPECT → EJECTED exactly like in-process replicas), and per-request
  mirrors replay the child's token stream into the router's.
* :class:`HostFleetRouter` — adds :meth:`migrate_host` (graceful drain
  WITH pages: export at src → checksummed wire frame → import into the
  dst prefix cache → continuation dispatched to dst, so only the
  un-filled tail prefills), host-scoped chaos (``host_die`` kills the
  real process; ``host_stall`` / ``link_slow`` degrade the transport),
  ``host_lost`` forensics and the migration observability surface:
  ``paddle_migration_{bytes,pages,requests}_total``,
  ``paddle_migration_seconds``, ``paddle_host_state`` and
  ``page_migration`` events, with per-transfer byte accounting fed to
  the HBM memory ledger (``note_migration``).

Failure atomicity: an import that dies partway rolls back inside
``PrefixCache.import_prefix`` (staged pages returned to the free list,
``check_conservation`` re-run), the wire CRC rejects truncated or
corrupted transfers *before* any bytes touch a pool, and a failed
migration falls back to the plain failover path — the continuation
recomputes its prefix, correct just slower. Host loss without a prior
drain replays only the un-migrated pages: whatever earlier migrations
already planted in a sibling's prefix cache is hit, not recomputed.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability.events import emit_event
from ..observability.federation import (FederationHub, collect_telemetry,
                                        federation_armed)
from ..observability.flight import flight_recorder
from ..observability.memory import memory_armed, memory_ledger
from ..observability.registry import get_registry
from ..observability.timeline import timeline_armed
from ..profiler.record import emit_span, spans_armed
from .health import HealthConfig, HealthTracker
from .metrics import ServingMetrics
from .router import FleetRouter, RouterConfig
from .scheduler import RequestState, SchedulerConfig, ServingScheduler
from .stream import ServingError, TokenStream
from .wire import (WireError, decode_message, decode_pages, encode_message,
                   grammar_from_wire, grammar_to_wire, telemetry_from_wire,
                   telemetry_to_wire)


class HostFault(RuntimeError):
    """Transport-level failure talking to an engine host: timeout, dead
    process, broken pipe, stalled link. The router treats it like any
    replica step failure (breaker food), never as a caller error."""


# ---------------------------------------------------------------------------
# child side: the engine process
# ---------------------------------------------------------------------------

def llama_tiny_host(seed: int = 3, max_new_tokens: int = 8,
                    num_slots: int = 2, page_size: int = 4,
                    max_seq_len: int = 48, chunk: int = 2,
                    num_hidden_layers: int = 2,
                    eos_token_id: Optional[int] = None,
                    grammar_states: int = 0):
    """Default host factory (``module:function`` target for
    :class:`PipeTransport`): a seeded tiny-llama engine WITH a prefix
    cache — page import lands there, so migrated continuations prefill
    only their tail. Returns ``(engine, params)``; every host built from
    the same kwargs is bit-identical, which is what makes cross-host
    continuation byte-exact."""
    from ..inference.decoding import (ContinuousBatchingEngine,
                                      GenerationConfig)
    from ..models import llama as L
    cfg = L.llama_tiny(num_hidden_layers=num_hidden_layers)
    params = L.init_stacked_params(cfg, seed=seed)
    engine = ContinuousBatchingEngine(
        cfg, GenerationConfig(max_new_tokens=max_new_tokens, seed=seed,
                              eos_token_id=eos_token_id),
        num_slots=num_slots, page_size=page_size, max_seq_len=max_seq_len,
        chunk=chunk, prefix_cache=True, grammar_states=grammar_states)
    return engine, params


class HostServer:
    """Wire-command handler around one engine + scheduler — the whole
    child-process brain. Parent request ids (``rid`` in every command)
    are the identity; retried ``submit`` frames dedup on them, and
    terminal request states keep being re-reported every ``step`` until
    the parent acks them, so a lost reply can never strand a mirror."""

    def __init__(self, engine, params, host_id: int = 0,
                 scheduler_config: Optional[SchedulerConfig] = None):
        self.engine = engine
        self.params = params
        self.host_id = int(host_id)
        self._scheduler = ServingScheduler(
            engine, scheduler_config,
            metrics=ServingMetrics(namespace=f"paddle_host_h{host_id}"))
        self._reqs: Dict[int, Any] = {}     # parent rid -> ServingRequest
        self._sent: Dict[int, int] = {}     # parent rid -> tokens reported
        self._span_marks: Dict[str, int] = {}   # telemetry watermarks
        self._telemetry_seq = 0
        self.shutdown_requested = False

    # -- framing ------------------------------------------------------------

    def handle_bytes(self, buf: bytes) -> bytes:
        """Decode one command frame, run it, encode the reply. Every
        failure mode becomes a structured reply — the child never lets
        an exception escape to kill the serving loop."""
        mid = -1
        try:
            kind, meta, arrays = decode_message(buf)
            mid = meta.get("__mid", -1)
            fn = getattr(self, f"_cmd_{kind}", None)
            if fn is None:
                raise WireError("schema", f"unknown command {kind!r}")
            out_meta, out_arrays = fn(meta, arrays)
            out_meta["__mid"] = mid
            out_meta["ok"] = True
            return encode_message("reply", out_meta, out_arrays)
        except WireError as e:
            err = {"type": "WireError", "code": e.code, "msg": e.detail}
        except ServingError as e:
            err = {"type": "ServingError", "code": e.code, "msg": str(e)}
        except (ValueError, KeyError, MemoryError) as e:
            err = {"type": type(e).__name__, "msg": str(e)}
        except Exception as e:    # noqa: BLE001 - reply, don't die
            err = {"type": type(e).__name__, "msg": repr(e)}
        return encode_message("reply",
                              {"__mid": mid, "ok": False, "error": err}, {})

    # -- commands -----------------------------------------------------------

    def _cmd_hello(self, meta, arrays) -> Tuple[dict, dict]:
        eng, mgr = self.engine, self.engine.mgr
        return ({"host_id": self.host_id,
                 "page_size": int(mgr.page_size),
                 "usable_pages": int(mgr.usable_pages),
                 "page_nbytes": int(mgr.page_nbytes),
                 "max_seq_len": int(eng.max_seq_len),
                 "eos_token_id": eng.config.eos_token_id,
                 "default_max_new_tokens": int(eng.config.max_new_tokens),
                 "kv_dtype": str(mgr.k_pages.dtype),
                 "prefix_cache": eng.cache is not None}, {})

    def _cmd_submit(self, meta, arrays) -> Tuple[dict, dict]:
        rid = int(meta["rid"])
        if rid in self._reqs:           # retried frame: first one won
            return ({"rid": rid}, {})
        sampler = None
        if meta.get("sampler") is not None:
            from ..inference.sampling import SamplerConfig
            sampler = SamplerConfig(**meta["sampler"])
        grammar = None
        if meta.get("grammar") is not None:
            grammar = grammar_from_wire(meta["grammar"], arrays)
        req = self._scheduler.submit(
            np.asarray(meta["prompt"], np.int32),
            priority=int(meta.get("priority", 0)),
            deadline_ms=meta.get("deadline_ms"),
            max_new_tokens=meta.get("max_new_tokens"),
            defer_s=meta.get("defer_s"),
            no_shed=bool(meta.get("no_shed", False)),
            trace_id=meta.get("trace_id"),
            sampler=sampler, grammar=grammar,
            grammar_prefix=meta.get("grammar_prefix"))
        self._reqs[rid] = req
        self._sent[rid] = 0
        return ({"rid": rid}, {})

    def _cmd_cancel(self, meta, arrays) -> Tuple[dict, dict]:
        req = self._reqs.pop(int(meta["rid"]), None)
        self._sent.pop(int(meta["rid"]), None)
        ok = False if req is None else self._scheduler.cancel(req.rid)
        return ({"cancelled": bool(ok)}, {})

    def _cmd_step(self, meta, arrays) -> Tuple[dict, dict]:
        for rid in meta.get("ack", ()):
            self._reqs.pop(int(rid), None)
            self._sent.pop(int(rid), None)
        sch = self._scheduler
        sch.step(self.params)
        updates: Dict[str, dict] = {}
        for rid, req in self._reqs.items():
            toks = req.stream.tokens
            new = toks[self._sent[rid]:]
            self._sent[rid] = len(toks)
            u: Dict[str, Any] = {"state": req.state}
            if new:
                u["new"] = [int(t) for t in new]
            if req.done:
                u["finish_reason"] = req.stream.finish_reason
                if req.stream.error is not None:
                    u["error"] = {"code": req.stream.error.code,
                                  "msg": str(req.stream.error)}
            updates[str(rid)] = u
        return ({"updates": updates,
                 "pending": sch.pending, "active": sch.active,
                 "inflight": sch.inflight,
                 "queue_depth": sch.queue_depth,
                 "degraded": sch.degraded}, {})

    def _cmd_export_flight(self, meta, arrays) -> Tuple[dict, dict]:
        """Snapshot one live request for migration: its full token
        stream (child-authoritative — the parent mirror may trail by a
        chunk) plus the KV pages of every *settled* full block. The last
        token's KV may not be written yet (it is the next step's input),
        so the export stops one token short of the committed length —
        the importer's continuation prefills the remainder."""
        rid = int(meta["rid"])
        req = self._reqs.get(rid)
        if req is None:
            raise KeyError(f"no live request {rid} on host {self.host_id}")
        mgr = self.engine.mgr
        tokens = [int(t) for t in req.prompt] + \
            [int(t) for t in req.stream.tokens]
        out: Dict[str, Any] = {"tokens": tokens, "state": req.state,
                               "n_pages": 0,
                               "kv_dtype": str(mgr.k_pages.dtype)}
        out_arrays: Dict[str, np.ndarray] = {}
        if req.engine_rid is not None:
            table = mgr.sequence_pages(req.engine_rid)
            settled = min(len(tokens), mgr.sequence_len(req.engine_rid))
            n_full = min(max(settled - 1, 0) // mgr.page_size, len(table))
            if n_full > 0:
                ks, vs = zip(*(mgr.export_page(p)
                               for p in table[:n_full]))
                out["n_pages"] = n_full
                out_arrays = {"k_slabs": np.stack(ks),
                              "v_slabs": np.stack(vs)}
        return (out, out_arrays)

    def _cmd_import_prefix(self, meta, arrays) -> Tuple[dict, dict]:
        """Land migrated pages in the prefix cache, then audit: pool
        conservation runs inside ``import_prefix`` (and on its rollback
        path), and the memory ledger re-balances the byte books while
        armed — a partial transfer can only ever leave this host exactly
        as it was."""
        if self.engine.cache is None:
            raise ServingError(
                "no_prefix_cache",
                f"host {self.host_id} has no prefix cache to import into")
        if meta.get("kv_dtype") and \
                meta["kv_dtype"] != str(self.engine.mgr.k_pages.dtype):
            raise WireError(
                "schema", f"kv dtype {meta['kv_dtype']} does not match "
                f"this pool's {self.engine.mgr.k_pages.dtype}")
        ks, vs = decode_pages(meta, arrays)
        res = self.engine.cache.import_prefix(meta["tokens"], ks, vs)
        if memory_armed[0]:
            memory_ledger.observe(self.engine.mgr)
        return (dict(res), {})

    def _cmd_statusz(self, meta, arrays) -> Tuple[dict, dict]:
        out = self._scheduler.statusz()
        out["host_id"] = self.host_id
        return ({"statusz": out}, {})

    def _cmd_telemetry(self, meta, arrays) -> Tuple[dict, dict]:
        """One federation beat: build a versioned telemetry frame —
        registry exposition, serving gauges, new completed spans since
        the previous frame (``_span_marks`` watermarks), event tail,
        memory class bytes. ``meta["arm"]`` arms the host-side span
        collector on first contact, so a child process starts recording
        the moment the parent federation wants spans."""
        if meta.get("arm") and not timeline_armed[0]:
            timeline_armed[0] = True
        seq = self._telemetry_seq
        self._telemetry_seq += 1
        frame = collect_telemetry(
            self.host_id, self._span_marks, seq,
            gauges=self._scheduler.metrics.gauges)
        return telemetry_to_wire(frame)

    def _cmd_shutdown(self, meta, arrays) -> Tuple[dict, dict]:
        self.shutdown_requested = True
        return ({}, {})


def _host_child_main(conn, factory: str, factory_kwargs: dict,
                     host_id: int) -> None:
    """Child-process entry (module-level: spawn pickles the reference).
    ``factory`` is a ``"module:function"`` spec returning ``(engine,
    params)`` — hosts rebuild their engine from seeds, nothing traced
    crosses the process boundary."""
    import importlib
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    mod_name, fn_name = factory.split(":")
    build = getattr(importlib.import_module(mod_name), fn_name)
    engine, params = build(**(factory_kwargs or {}))
    server = HostServer(engine, params, host_id=host_id)
    while True:
        try:
            buf = conn.recv_bytes()
        except (EOFError, OSError):
            break
        reply = server.handle_bytes(buf)
        try:
            conn.send_bytes(reply)
        except (BrokenPipeError, OSError):
            break
        if server.shutdown_requested:
            break
    conn.close()


# ---------------------------------------------------------------------------
# parent side: transports
# ---------------------------------------------------------------------------

class PipeTransport:
    """A real engine process on the other end of a duplex pipe. The
    pipe is the DCN stand-in: every frame that crosses it is a
    length-prefixed byte string, so the wire format is exercised exactly
    as it would be over a socket (transport framing is the pipe's;
    integrity is the frame's own CRC)."""

    def __init__(self, factory: str = "paddle_tpu.serving.multihost:"
                                      "llama_tiny_host",
                 factory_kwargs: Optional[dict] = None, host_id: int = 0):
        import multiprocessing as mp
        ctx = mp.get_context("spawn")
        self._conn, child_conn = ctx.Pipe()
        self._proc = ctx.Process(
            target=_host_child_main,
            args=(child_conn, factory, dict(factory_kwargs or {}),
                  int(host_id)),
            daemon=True)
        self._proc.start()
        child_conn.close()      # parent keeps one end only

    def send(self, buf: bytes) -> None:
        try:
            self._conn.send_bytes(buf)
        except (BrokenPipeError, OSError, EOFError) as e:
            raise HostFault(f"send failed: {e!r}")

    def recv(self, timeout_s: float) -> bytes:
        try:
            if not self._conn.poll(timeout_s):
                raise HostFault(f"no reply within {timeout_s}s")
            return self._conn.recv_bytes()
        except (EOFError, OSError) as e:
            raise HostFault(f"recv failed: {e!r}")

    def alive(self) -> bool:
        return self._proc.is_alive()

    def kill(self) -> None:
        self._proc.kill()

    def close(self) -> None:
        """Graceful teardown: best-effort shutdown command, then join,
        then kill — never leaves a zombie child behind a test run."""
        try:
            self.send(encode_message("shutdown", {"__mid": -1}, {}))
        except HostFault:
            pass
        self._proc.join(timeout=5)
        if self._proc.is_alive():
            self._proc.kill()
            self._proc.join(timeout=5)


class LocalTransport:
    """The same :class:`HostServer` in-process, every frame still
    round-tripping through encode/decode — identical wire coverage,
    deterministic fake-clock time, and a ``dead`` switch standing in
    for a killed process."""

    def __init__(self, server: HostServer):
        self.server = server
        self._replies: List[bytes] = []
        self.dead = False

    def send(self, buf: bytes) -> None:
        if self.dead:
            raise HostFault("host process is dead")
        self._replies.append(self.server.handle_bytes(buf))

    def recv(self, timeout_s: float) -> bytes:
        if self.dead:
            raise HostFault("host process is dead")
        if not self._replies:
            raise HostFault(f"no reply within {timeout_s}s")
        return self._replies.pop(0)

    def alive(self) -> bool:
        return not self.dead

    def kill(self) -> None:
        self.dead = True

    def close(self) -> None:
        self.dead = True


class HostEndpoint:
    """Client half of one host link: request/reply over a transport
    with per-call timeout, bounded exponential-backoff retry, message-id
    matching (a late reply to a timed-out attempt is discarded, never
    mis-delivered), injectable link latency (``link_slow`` chaos) and a
    parent-side stall window (``host_stall`` chaos — calls fail fast as
    if the host stopped answering). Non-idempotent commands stay safe
    under retry because the server dedups on parent request ids."""

    def __init__(self, transport, clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 timeout_s: float = 120.0, retries: int = 2,
                 backoff_s: float = 0.05):
        self.transport = transport
        self._clock = clock
        self._sleep = sleep
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self._mid = 0
        self._dead = False
        self._stall_until = 0.0
        self._slow_until = 0.0
        self._slow_delay = 0.0
        self.calls = 0
        self.retried = 0
        self.bytes_sent = 0
        self.bytes_received = 0

    # -- chaos levers (parent-side mirrors of the replica surface) ----------

    def kill(self) -> None:
        self._dead = True
        try:
            self.transport.kill()
        except Exception:       # a dead transport cannot veto its death
            pass

    def stall(self, duration_s: float) -> None:
        self._stall_until = self._clock() + float(duration_s)

    def slow_link(self, duration_s: float, delay_s: float) -> None:
        self._slow_until = self._clock() + float(duration_s)
        self._slow_delay = float(delay_s)

    def alive(self) -> bool:
        """Producer-liveness probe for consumer token streams: False
        once the process is gone (a stalled or slow host is alive —
        slow is not dead)."""
        return not self._dead and self.transport.alive()

    # -- the one call path --------------------------------------------------

    def call(self, kind: str, meta: Optional[dict] = None,
             arrays: Optional[Dict[str, np.ndarray]] = None,
             timeout_s: Optional[float] = None,
             retries: Optional[int] = None
             ) -> Tuple[dict, Dict[str, np.ndarray]]:
        if self._dead:
            raise HostFault("host endpoint is dead")
        now = self._clock()
        if now < self._stall_until:
            raise HostFault("host is stalled (no heartbeat reply)")
        if now < self._slow_until:
            self._sleep(self._slow_delay)       # injected DCN latency
        timeout_s = self.timeout_s if timeout_s is None else timeout_s
        retries = self.retries if retries is None else int(retries)
        last: Optional[Exception] = None
        for attempt in range(retries + 1):
            if attempt:
                self.retried += 1
                self._sleep(self.backoff_s * (2 ** (attempt - 1)))
            mid = self._mid = self._mid + 1
            frame = encode_message(kind, dict(meta or {}, __mid=mid),
                                   arrays)
            try:
                self.calls += 1
                self.bytes_sent += len(frame)
                self.transport.send(frame)
                r_meta, r_arrays = self._recv_reply(mid, timeout_s)
            except (HostFault, WireError) as e:
                last = e
                continue
            err = r_meta.get("error")
            if err is not None:
                raise _raise_remote(err)
            return r_meta, r_arrays
        raise HostFault(f"{kind} failed after {retries + 1} attempts: "
                        f"{last!r}")

    def _recv_reply(self, mid: int, timeout_s: float
                    ) -> Tuple[dict, Dict[str, np.ndarray]]:
        deadline = self._clock() + timeout_s
        while True:
            remaining = max(deadline - self._clock(), 0.0)
            buf = self.transport.recv(remaining)
            self.bytes_received += len(buf)
            _kind, meta, arrays = decode_message(buf)
            if meta.get("__mid") == mid:
                return meta, arrays
            # stale reply from a timed-out earlier attempt: drop it
            if self._clock() >= deadline:
                raise HostFault(f"no matching reply within {timeout_s}s")

    def stats(self) -> Dict[str, Any]:
        return {"calls": self.calls, "retried": self.retried,
                "bytes_sent": self.bytes_sent,
                "bytes_received": self.bytes_received,
                "alive": self.alive()}

    def close(self) -> None:
        try:
            self.transport.close()
        except Exception:
            pass
        self._dead = True


def _raise_remote(err: dict) -> Exception:
    """Rehydrate a structured child-side error for the caller: the
    types the router's control flow dispatches on come back as
    themselves, everything else as :class:`HostFault`."""
    t = err.get("type")
    msg = err.get("msg", "")
    if t == "ServingError":
        return ServingError(err.get("code", "engine_failure"), msg)
    if t == "WireError":
        return WireError(err.get("code", "schema"), msg)
    if t == "ValueError":
        return ValueError(msg)
    if t == "MemoryError":
        return MemoryError(msg)
    if t == "KeyError":
        return KeyError(msg)
    return HostFault(f"{t}: {msg}")


# ---------------------------------------------------------------------------
# parent side: the ReplicaHandle-shaped host
# ---------------------------------------------------------------------------

class _FacadeMgr:
    """Enough of a page pool for the router's admission math."""

    def __init__(self, page_size: int, usable_pages: int):
        self.page_size = int(page_size)
        self.usable_pages = int(usable_pages)

    def pages_for(self, n_tokens: int) -> int:
        return (n_tokens + self.page_size - 1) // self.page_size


class _FacadeConfig:
    def __init__(self, eos_token_id, max_new_tokens: int):
        self.eos_token_id = eos_token_id
        self.max_new_tokens = int(max_new_tokens)


class _EngineFacade:
    """Parent-side stand-in for ``handle.engine`` built from the
    ``hello`` reply — the router reads geometry and limits off it
    without ever holding the remote engine."""

    def __init__(self, hello: dict):
        self.page_size = int(hello["page_size"])
        self.max_seq_len = int(hello["max_seq_len"])
        self.mgr = _FacadeMgr(hello["page_size"], hello["usable_pages"])
        self.config = _FacadeConfig(hello["eos_token_id"],
                                    hello["default_max_new_tokens"])
        self.page_nbytes = int(hello["page_nbytes"])
        self.kv_dtype = hello.get("kv_dtype", "")
        self.has_prefix_cache = bool(hello.get("prefix_cache", False))


@dataclass
class RemoteRequest:
    """Parent-side mirror of one request living on a host: state and
    tokens arrive via ``step`` replies; the stream is the same
    ``TokenStream`` contract the router consumes on in-process
    replicas, with the endpoint's liveness probe attached so a consumer
    of a dead host's stream terminates instead of hanging."""

    rid: int
    prompt: np.ndarray
    stream: TokenStream = None
    state: str = RequestState.QUEUED
    _closed: bool = field(default=False, repr=False)

    @property
    def done(self) -> bool:
        return self.state in (RequestState.DONE, RequestState.CANCELLED,
                              RequestState.SHED, RequestState.FAILED)

    def _apply(self, update: dict) -> int:
        """Fold one step-reply entry into the mirror; returns the number
        of new tokens delivered."""
        new = update.get("new", ())
        for tok in new:
            self.stream.push(int(tok))
        self.state = update.get("state", self.state)
        if self.done and not self._closed:
            self._closed = True
            err = update.get("error")
            self.stream.close(
                update.get("finish_reason") or "complete",
                None if err is None else ServingError(
                    err.get("code", "engine_failure"),
                    err.get("msg", ""), rid=self.rid))
        return len(new)


class HostHandle:
    """One engine host, duck-typing :class:`~.replica.ReplicaHandle`
    (same surface, checked by the router tests): ``step`` is the
    heartbeat RPC — a transport failure raises and the router's
    ``HealthTracker`` walks SUSPECT → EJECTED on consecutive missed
    beats; ``kill``/``stall``/``slow`` map host chaos onto the process
    (a real ``SIGKILL`` under :class:`PipeTransport`) and the link."""

    def __init__(self, host_id: int, endpoint: HostEndpoint,
                 health_config: Optional[HealthConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 step_timeout_s: float = 120.0):
        self.replica_id = int(host_id)
        self.endpoint = endpoint
        self._clock = clock
        self._sleep = sleep
        self.step_timeout_s = float(step_timeout_s)
        hello, _ = endpoint.call("hello", retries=1)
        self.engine = _EngineFacade(hello)
        self.health = HealthTracker(health_config, clock=clock)
        self.draining = False
        self.drained_event_sent = False
        self._mirrors: Dict[int, RemoteRequest] = {}
        self._next_rid = 0
        self._ack: List[int] = []
        self._tokens_total = 0
        self._completed_total = 0
        self._last: Dict[str, Any] = {"pending": 0, "active": 0,
                                      "inflight": 0, "queue_depth": 0,
                                      "degraded": False}
        #: set by HostFleetRouter — the parent-side telemetry sink this
        #: handle's heartbeat feeds while ``federation_armed``
        self.federation: Optional[FederationHub] = None
        self._statusz_cache: Dict[str, Any] = {}
        self._statusz_last_success: Optional[float] = None
        reg = get_registry()
        self._c_statusz_err = reg.counter(
            "paddle_host_statusz_errors_total",
            "statusz endpoint round-trips that failed "
            "(the host view is served from cache, marked stale)",
            labels=("host",))
        self._h_rtt = reg.histogram(
            "paddle_host_heartbeat_rtt_seconds",
            "telemetry-beat RPC round-trip time per host (the samples "
            "the clock-offset estimator consumes)",
            labels=("host",),
            bounds=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5))

    # -- request lifecycle --------------------------------------------------

    def submit(self, prompt, priority: int = 0,
               deadline_ms: Optional[float] = None,
               max_new_tokens: Optional[int] = None,
               on_token: Optional[Callable[[int], None]] = None,
               defer_s: Optional[float] = None,
               no_shed: bool = False,
               trace_id: Optional[str] = None,
               sampler: Any = None,
               grammar: Any = None,
               grammar_prefix: Any = None) -> RemoteRequest:
        rid = self._next_rid
        self._next_rid += 1
        prompt = np.asarray(prompt, np.int32)
        meta: Dict[str, Any] = {
            "rid": rid, "prompt": [int(t) for t in prompt],
            "priority": int(priority), "deadline_ms": deadline_ms,
            "max_new_tokens": max_new_tokens, "defer_s": defer_s,
            "no_shed": bool(no_shed), "trace_id": trace_id}
        arrays: Dict[str, np.ndarray] = {}
        if sampler is not None:
            meta["sampler"] = {"temperature": sampler.temperature,
                               "top_k": sampler.top_k,
                               "top_p": sampler.top_p,
                               "seed": sampler.seed}
        if grammar is not None:
            g_meta, g_arrays = grammar_to_wire(grammar)
            meta["grammar"] = g_meta
            arrays.update(g_arrays)
        if grammar_prefix:
            meta["grammar_prefix"] = [int(t) for t in grammar_prefix]
        try:
            self.endpoint.call("submit", meta, arrays)
        except HostFault as e:
            # the router's routing loop dispatches on ServingError:
            # "this host refused/failed" -> breaker food + next sibling
            raise ServingError("host_unreachable",
                               f"host {self.replica_id}: {e}", rid=rid)
        mirror = RemoteRequest(rid=rid, prompt=prompt,
                               stream=TokenStream(rid, on_token=on_token))
        mirror.stream.attach_producer(self.endpoint.alive)
        self._mirrors[rid] = mirror
        return mirror

    def cancel(self, rid: int) -> bool:
        mirror = self._mirrors.pop(rid, None)
        if mirror is not None and not mirror.done:
            mirror.state = RequestState.CANCELLED
            mirror._closed = True
            mirror.stream.close("cancelled", None)
        try:
            meta, _ = self.endpoint.call("cancel", {"rid": rid}, retries=0)
            return bool(meta.get("cancelled", False))
        except (HostFault, ServingError, WireError):
            return False        # a dead host cannot veto a cancel

    def step(self, params) -> int:
        """One heartbeat: step the remote scheduler and fold its reply
        into the mirrors. ``params`` is unused (the host owns its own) —
        kept for the ReplicaHandle signature. No retry: a missed beat
        must surface to the breaker, not be papered over."""
        ack, self._ack = self._ack, []
        try:
            meta, _ = self.endpoint.call(
                "step", {"ack": ack}, retries=0,
                timeout_s=self.step_timeout_s)
        except (HostFault, WireError):
            self._ack = ack + self._ack     # re-ack next beat
            raise
        for rid_s, update in meta.get("updates", {}).items():
            mirror = self._mirrors.get(int(rid_s))
            if mirror is None:
                self._ack.append(int(rid_s))    # cancelled under us
                continue
            was_done = mirror.done
            self._tokens_total += mirror._apply(update)
            if mirror.done and not was_done:
                self._ack.append(int(rid_s))
                if mirror.state == RequestState.DONE:
                    self._completed_total += 1
        for k in ("pending", "active", "inflight", "queue_depth",
                  "degraded"):
            self._last[k] = meta.get(k, self._last[k])
        if federation_armed[0] and self.federation is not None:
            self._telemetry_beat()
        return int(meta.get("pending", 0))

    def _telemetry_beat(self) -> None:
        """Pull one telemetry frame after a successful heartbeat. The
        round-trip is stamped with ``perf_counter_ns`` on both ends —
        the same samples feed the RTT histogram AND the hub's clock
        estimator. A failed beat marks the mirror stale; it is never
        breaker food (the heartbeat proper owns health)."""
        hub = self.federation
        t0 = time.perf_counter_ns()
        try:
            meta, arrays = self.endpoint.call(
                "telemetry", {"arm": timeline_armed[0]}, retries=0,
                timeout_s=self.step_timeout_s)
            t1 = time.perf_counter_ns()
            frame = telemetry_from_wire(meta, arrays)
        except (HostFault, ServingError, WireError) as e:
            hub.mark_stale(self.replica_id, repr(e))
            return
        self._h_rtt.observe((t1 - t0) / 1e9, host=f"h{self.replica_id}")
        hub.ingest(self.replica_id, frame, t0, t1)

    # -- page migration RPCs ------------------------------------------------

    def export_flight(self, mirror: RemoteRequest
                      ) -> Tuple[List[int], List[np.ndarray],
                                 List[np.ndarray]]:
        """Pull one live request's flight state: authoritative token
        list + settled KV pages. Tokens the child generated but had not
        yet heart-beaten to us are folded into the mirror here, so the
        router's stream is caught up before the continuation
        dispatches."""
        meta, arrays = self.endpoint.call(
            "export_flight", {"rid": mirror.rid}, retries=1)
        tokens = [int(t) for t in meta["tokens"]]
        known = len(mirror.prompt) + len(mirror.stream.tokens)
        if len(tokens) > known:
            self._tokens_total += mirror._apply(
                {"new": tokens[known:], "state": mirror.state})
        ks, vs = decode_pages(meta, arrays)
        return tokens, ks, vs

    def import_prefix(self, tokens: Sequence[int],
                      k_slabs: Sequence[np.ndarray],
                      v_slabs: Sequence[np.ndarray]) -> Dict[str, int]:
        """Push migrated pages into this host's prefix cache."""
        meta: Dict[str, Any] = {"tokens": [int(t) for t in tokens],
                                "n_pages": len(k_slabs)}
        arrays: Dict[str, np.ndarray] = {}
        if k_slabs:
            ks, vs = np.stack(k_slabs), np.stack(v_slabs)
            meta["kv_dtype"] = str(ks.dtype)
            arrays = {"k_slabs": ks, "v_slabs": vs}
        meta_r, _ = self.endpoint.call("import_prefix", meta, arrays,
                                       retries=1)
        return {k: v for k, v in meta_r.items()
                if k in ("imported_pages", "skipped_pages",
                         "imported_bytes", "evicted_pages")}

    # -- router-facing state ------------------------------------------------

    @property
    def default_max_new_tokens(self) -> int:
        return self.engine.config.max_new_tokens

    @property
    def pending(self) -> int:
        return int(self._last["pending"])

    @property
    def active(self) -> int:
        """Live mirrors — parent-side truth, so the watchdog arms the
        moment a submit lands even before the first heartbeat reply."""
        return sum(1 for m in self._mirrors.values() if not m.done)

    @property
    def inflight(self) -> int:
        return int(self._last["inflight"])

    @property
    def queue_depth(self) -> int:
        return int(self._last["queue_depth"])

    @property
    def degraded(self) -> bool:
        return bool(self._last["degraded"])

    @property
    def progress_marker(self) -> tuple:
        return (self._tokens_total, self._completed_total, self.active)

    @property
    def slo_monitor(self):
        return None             # per-host SLOs live host-side; the
        # router's fleet monitor covers the outcome objective

    def statusz(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "replica_id": self.replica_id,
            "health": self.health.snapshot(),
            "draining": self.draining,
            "transport": self.endpoint.stats(),
            "mirrors": len(self._mirrors),
            "last_heartbeat": dict(self._last)}
        try:
            meta, _ = self.endpoint.call("statusz", retries=0,
                                         timeout_s=2.0)
        except (HostFault, ServingError, WireError) as e:
            # an unreachable endpoint must not look healthy: serve the
            # last good view, visibly STALE, and count the failure
            self._c_statusz_err.inc(host=f"h{self.replica_id}")
            view = dict(self._statusz_cache)
            view["stale"] = True
            view["stale_error"] = repr(e)
            view["last_success_t"] = self._statusz_last_success
            out["host"] = view
        else:
            self._statusz_cache = dict(meta.get("statusz", {}))
            self._statusz_last_success = self._clock()
            view = dict(self._statusz_cache)
            view["stale"] = False
            out["host"] = view
        return out

    # -- chaos surface ------------------------------------------------------

    def kill(self) -> None:
        """Host death — under :class:`PipeTransport` a real process
        kill, mid-decode state and all."""
        self.endpoint.kill()

    def stall(self, duration_s: float) -> None:
        self.endpoint.stall(duration_s)

    def slow(self, duration_s: float, delay_s: float) -> None:
        self.endpoint.slow_link(duration_s, delay_s)

    def close(self) -> None:
        self.endpoint.close()


# ---------------------------------------------------------------------------
# the multi-host router
# ---------------------------------------------------------------------------

MAX_MIGRATION_LOG = 64


class HostFleetRouter(FleetRouter):
    """A :class:`~.router.FleetRouter` whose replicas are engine
    processes: everything the fleet tier proved — prefix-affinity
    routing, breaker-driven ejection, byte-identical mid-stream
    failover, drain, probes — applies unchanged, because
    :class:`HostHandle` speaks the replica surface. This subclass adds
    what only exists once replicas are processes: host-scoped chaos,
    :meth:`migrate_host` (drain WITH the KV pages), ``host_lost``
    forensics, and the migration metric families."""

    def __init__(self, hosts: Sequence[HostHandle],
                 config: Optional[RouterConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 fault_injector=None):
        super().__init__(hosts, config=config, clock=clock, sleep=sleep,
                         fault_injector=fault_injector)
        self._migration_log: List[dict] = []
        reg = get_registry()
        self._c_mig_bytes = reg.counter(
            "paddle_migration_bytes_total",
            "KV bytes moved across host boundaries (wire payload)")
        self._c_mig_pages = reg.counter(
            "paddle_migration_pages_total",
            "KV pages moved across host boundaries")
        self._c_mig_reqs = reg.counter(
            "paddle_migration_requests_total",
            "live requests migrated between hosts, by outcome",
            labels=("outcome",))
        self._h_mig_s = reg.histogram(
            "paddle_migration_seconds",
            "end-to-end per-request migration latency "
            "(export -> import -> redispatch)")
        self._g_host = reg.gauge(
            "paddle_host_state",
            "host breaker state: 0 healthy / 1 suspect / 2 ejected / "
            "3 half-open / 4 draining / 5 drained",
            labels=("host",))
        #: parent-side telemetry federation: every handle's heartbeat
        #: feeds it while armed; bundles embed its snapshot
        self.federation = FederationHub()
        for h in self.replicas.values():
            if isinstance(h, HostHandle):
                h.federation = self.federation
        # host-loss bundles embed the migration timeline + host states
        flight_recorder.attach_multihost(self)

    # -- the fleet loop -----------------------------------------------------

    def _step_inner(self, params) -> None:
        cfg = self.config
        if self.injector is not None \
                and hasattr(self.injector, "fire_host"):
            # host chaos fires before the base loop (1-based step ids,
            # aligned with the base replica events)
            step = self._steps + 1
            for hid, h in self.replicas.items():
                if self.injector.fire_host("host_die", step,
                                           host=hid) is not None:
                    h.kill()
                if self.injector.fire_host("host_stall", step,
                                           host=hid) is not None:
                    h.stall(cfg.stall_s)
                f = self.injector.fire_host("link_slow", step, host=hid)
                if f is not None:
                    h.slow(cfg.slow_s, f.delay_s if f.delay_s is not None
                           else cfg.slow_delay_s)
        super()._step_inner(params)
        for hid, h in self.replicas.items():
            self._g_host.set(self._state_code(h), host=str(hid))

    # -- host loss ----------------------------------------------------------

    def _eject(self, rid: int, r, reason: str) -> None:
        live = [req for req in self._requests.values()
                if req.replica_id == rid and req.handle is not None
                and not req.done]
        process_dead = isinstance(r, HostHandle) \
            and not r.endpoint.alive()
        emit_event("host_lost", host=rid, error=reason,
                   inflight=len(live), process_dead=process_dead,
                   migrations=len(self._migration_log))
        if process_dead:
            # freeze the dead host's telemetry mirror as its last-known
            # state — the host_lost bundle embeds it (host_telemetry.json)
            self.federation.mark_lost(rid)
            # the pages died with the process: a surviving affinity
            # slice would route same-prefix traffic at a cold (or
            # never-returning) host on re-admission
            self.invalidate_index(rid)
        else:
            self.federation.mark_stale(rid, reason)
        super()._eject(rid, r, reason)

    # -- live migration -----------------------------------------------------

    def migrate_host(self, src: int, dst: Optional[int] = None
                     ) -> Dict[str, Any]:
        """Gracefully move host ``src``'s work to ``dst`` (least-loaded
        accepting sibling when None), pages included: per live request
        — export at src, import into dst's prefix cache, redispatch the
        continuation to dst (its prefill hits the imported blocks and
        computes only the tail), then cancel at src to free the pages.
        A request whose transfer fails (dead src, corrupt frame, full
        dst pool after rollback) falls back to plain failover routing —
        recomputed, not lost. Returns a per-migration summary; totals
        land in the ``paddle_migration_*`` families, the memory
        ledger's migration timeline and one ``page_migration`` event
        per request."""
        r = self.replicas[src]
        if dst is None:
            cands = [hid for hid in sorted(self.replicas)
                     if hid != src
                     and not self.replicas[hid].draining
                     and not self.replicas[hid].degraded
                     and self.replicas[hid].health.accepting]
            if not cands:
                raise ServingError(
                    "no_migration_target",
                    f"no accepting sibling to migrate host {src} to")
            dst = min(cands,
                      key=lambda c: (self._load(self.replicas[c]), c))
        if dst == src:
            raise ValueError(f"cannot migrate host {src} onto itself")
        d = self.replicas[dst]
        self.drain(src)         # queued work hands off page-free
        live = [req for req in self._requests.values()
                if req.replica_id == src and req.handle is not None
                and not req.done]
        summary = {"src": src, "dst": dst, "requests": 0, "pages": 0,
                   "bytes": 0, "skipped_pages": 0, "failed": 0,
                   "seconds": 0.0}
        for req in live:
            t0 = self._clock()
            trace = spans_armed()
            mig_ns0 = time.perf_counter_ns() if trace else 0
            mirror = req.handle
            try:
                tokens, ks, vs = r.export_flight(mirror)
                nbytes = int(sum(a.nbytes for a in ks)
                             + sum(a.nbytes for a in vs))
                imported = (d.import_prefix(tokens, ks, vs) if ks
                            else {"imported_pages": 0, "skipped_pages": 0,
                                  "imported_bytes": 0, "evicted_pages": 0})
                dcn_ns1 = time.perf_counter_ns() if trace else 0
                # pages now live at dst: teach the affinity index, free
                # the src copy, land the continuation where the KV is
                self._index_insert(dst, tokens)
                try:
                    r.cancel(mirror.rid)
                except Exception:
                    pass
                self._dispatch(req, dst, None)
                dt = self._clock() - t0
                if trace:
                    # the DCN window (export -> import) nests inside the
                    # whole-migration span, so the exclusive sweep grows
                    # dcn_transfer + migration segments that still tile
                    # the root request envelope
                    emit_span("router.dcn_transfer", mig_ns0, dcn_ns1,
                              trace_id=req.trace_id,
                              args={"request_id": req.rid,
                                    "bytes": nbytes, "pages": len(ks)})
                    emit_span("router.migration", mig_ns0,
                              time.perf_counter_ns(),
                              trace_id=req.trace_id,
                              args={"request_id": req.rid, "src": src,
                                    "dst": dst, "pages": len(ks),
                                    "bytes": nbytes})
                self._c_mig_bytes.inc(nbytes)
                self._c_mig_pages.inc(len(ks))
                self._c_mig_reqs.inc(outcome="ok")
                self._h_mig_s.observe(dt)
                self._c_requests.inc(replica=str(src), outcome="migrated")
                if memory_armed[0]:
                    memory_ledger.note_migration(
                        nbytes=nbytes, pages=len(ks), seconds=dt,
                        src_host=src, dst_host=dst, outcome="ok")
                entry = {"request_id": req.rid, "src": src, "dst": dst,
                         "pages": len(ks), "bytes": nbytes,
                         "imported_pages": imported["imported_pages"],
                         "skipped_pages": imported["skipped_pages"],
                         "seconds": round(dt, 6), "outcome": "ok"}
                emit_event("page_migration", trace_id=req.trace_id,
                           **entry)
                summary["requests"] += 1
                summary["pages"] += len(ks)
                summary["bytes"] += nbytes
                summary["skipped_pages"] += imported["skipped_pages"]
                summary["seconds"] += dt
            except Exception as e:  # noqa: BLE001 - per-request fallback
                dt = self._clock() - t0
                self._c_mig_reqs.inc(outcome="failed")
                self._h_mig_s.observe(dt)
                if memory_armed[0]:
                    memory_ledger.note_migration(
                        nbytes=0, pages=0, seconds=dt, src_host=src,
                        dst_host=dst, outcome="failed")
                entry = {"request_id": req.rid, "src": src, "dst": dst,
                         "pages": 0, "bytes": 0, "seconds": round(dt, 6),
                         "outcome": "failed", "error": repr(e)}
                emit_event("page_migration", trace_id=req.trace_id,
                           **entry)
                summary["failed"] += 1
                # destination rolled back (import_prefix's except path);
                # the request itself survives via the plain
                # recompute-the-prefix failover route
                try:
                    r.cancel(mirror.rid)
                except Exception:
                    pass
                try:
                    self._route(req, exclude={src})
                except ServingError:
                    pass        # parked; the step loop keeps retrying
            self._migration_log.append(entry)
            del self._migration_log[:-MAX_MIGRATION_LOG]
        return summary

    # -- observability ------------------------------------------------------

    def multihost_snapshot(self) -> Dict[str, Any]:
        """The multi-host slice of a debug bundle (``multihost.json``):
        per-host breaker + transport state and the migration timeline —
        a host-loss bundle answers "what moved where before it died"
        without correlating external logs."""
        return {
            "steps": self._steps,
            "hosts": {str(hid): {
                "state": self._state_code(h),
                "health": h.health.snapshot(),
                "transport": (h.endpoint.stats()
                              if isinstance(h, HostHandle) else {}),
                "draining": h.draining,
            } for hid, h in sorted(self.replicas.items())},
            "migrations": [dict(e) for e in self._migration_log],
        }

    def statusz(self) -> Dict[str, Any]:
        out = super().statusz()
        out["multihost"] = {
            "migrations": len(self._migration_log),
            "migrated_pages": sum(e.get("pages", 0)
                                  for e in self._migration_log),
            "migrated_bytes": sum(e.get("bytes", 0)
                                  for e in self._migration_log),
        }
        return out

    def close(self) -> None:
        """Tear the fleet down: shut every host process/endpoint."""
        self._alive[0] = False
        for h in self.replicas.values():
            if isinstance(h, HostHandle):
                h.close()
