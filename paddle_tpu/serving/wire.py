"""Versioned, checksummed wire format for cross-host serving traffic.

Everything that crosses a host boundary — RPC commands, flight
snapshots, page-granular KV payloads, compiled grammars — rides ONE
self-describing frame so a single decoder guards every entry point:

.. code-block:: text

    offset  size  field
    0       4     magic  b"PDLW"
    4       2     version (u16 LE)   — WIRE_VERSION; skew is refused
    6       2     reserved (u16 LE)  — zero; room for flags
    8       4     header_len (u32 LE) — JSON header byte length
    12      4     crc32 (u32 LE)     — over header + payload
    16      H     header: UTF-8 JSON {kind, meta, arrays}
    16+H    *     payload: the arrays' raw bytes, concatenated in order

The header's ``arrays`` entry is a list of ``{name, dtype, shape,
nbytes}`` records; the payload is each array's C-contiguous bytes in
listed order. Integrity first: :func:`decode_message` verifies magic,
version and CRC32 *before* any JSON is parsed or any bytes reach a KV
pool, so a truncated or corrupted transfer dies at the boundary with a
structured :class:`WireError` and the destination stays byte-conserved
by construction.

Only stdlib + numpy — both host processes decode without touching JAX.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

MAGIC = b"PDLW"
WIRE_VERSION = 1

_PREAMBLE = struct.Struct("<4sHHII")   # magic, version, reserved, hlen, crc
PREAMBLE_NBYTES = _PREAMBLE.size

#: WireError codes, in the order the decoder checks them
WIRE_ERROR_CODES = ("truncated", "bad_magic", "version_skew",
                    "checksum_mismatch", "schema")


class WireError(Exception):
    """Structured decode failure. ``code`` is one of
    :data:`WIRE_ERROR_CODES`; ``detail`` is the human-readable half."""

    def __init__(self, code: str, detail: str = ""):
        if code not in WIRE_ERROR_CODES:
            raise ValueError(f"unknown wire error code {code!r}")
        self.code = code
        self.detail = detail
        super().__init__(f"{code}: {detail}" if detail else code)

    def as_dict(self) -> Dict[str, str]:
        return {"error": "wire", "code": self.code, "detail": self.detail}


# -- encode -----------------------------------------------------------------

def encode_message(kind: str, meta: Optional[dict] = None,
                   arrays: Optional[Dict[str, np.ndarray]] = None) -> bytes:
    """Frame ``kind`` + JSON-safe ``meta`` + named numpy ``arrays`` into
    one wire message (layout in the module docstring)."""
    meta = meta or {}
    arrays = arrays or {}
    specs: List[dict] = []
    chunks: List[bytes] = []
    for name, arr in arrays.items():
        a = np.ascontiguousarray(arr)
        # extension dtypes (bfloat16 via ml_dtypes) stringify as opaque
        # void ('<V2'); their NAME round-trips bit-faithfully instead
        dstr = a.dtype.str if a.dtype.kind != "V" else a.dtype.name
        specs.append({"name": str(name), "dtype": dstr,
                      "shape": list(a.shape), "nbytes": int(a.nbytes)})
        chunks.append(a.tobytes())
    header = json.dumps({"kind": kind, "meta": meta, "arrays": specs},
                        separators=(",", ":")).encode("utf-8")
    payload = b"".join(chunks)
    crc = zlib.crc32(header + payload) & 0xFFFFFFFF
    # lazy: this module is importable with nothing but stdlib + numpy
    # (cross-host receivers), so the journal tap must not promote
    # observability into a hard import-time dependency
    from ..observability.journal import journal as _journal
    from ..observability.journal import journal_armed as _armed
    if _armed[0]:
        _journal.note_wire(kind=kind, crc=int(crc),
                           nbytes=len(header) + len(payload))
    return (_PREAMBLE.pack(MAGIC, WIRE_VERSION, 0, len(header), crc)
            + header + payload)


def _dtype(dstr: str) -> np.dtype:
    """Resolve a wire dtype string; extension names (``bfloat16``) need
    ``ml_dtypes`` registered before numpy knows them."""
    try:
        return np.dtype(dstr)
    except TypeError:
        import ml_dtypes  # noqa: F401  (registers bfloat16 et al.)
        return np.dtype(dstr)


# -- decode -----------------------------------------------------------------

def decode_message(buf: bytes) -> Tuple[str, dict, Dict[str, np.ndarray]]:
    """Verify and unpack one frame -> ``(kind, meta, arrays)``. Raises
    :class:`WireError` (never a bare struct/json/numpy error); integrity
    checks run before any content is interpreted."""
    if len(buf) < PREAMBLE_NBYTES:
        raise WireError("truncated",
                        f"{len(buf)} bytes < {PREAMBLE_NBYTES} preamble")
    magic, version, _reserved, hlen, crc = _PREAMBLE.unpack_from(buf)
    if magic != MAGIC:
        raise WireError("bad_magic", repr(magic))
    if version != WIRE_VERSION:
        raise WireError(
            "version_skew",
            f"peer speaks wire v{version}, this host v{WIRE_VERSION}")
    body = buf[PREAMBLE_NBYTES:]
    if len(body) < hlen:
        raise WireError("truncated",
                        f"header needs {hlen} bytes, {len(body)} left")
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise WireError("checksum_mismatch",
                        f"crc32 over {len(body)} body bytes")
    try:
        header = json.loads(body[:hlen].decode("utf-8"))
        kind = header["kind"]
        meta = header["meta"]
        specs = header["arrays"]
    except (ValueError, KeyError, UnicodeDecodeError) as e:
        raise WireError("schema", f"bad header: {e}")
    arrays: Dict[str, np.ndarray] = {}
    off = hlen
    for spec in specs:
        try:
            name, dstr = spec["name"], spec["dtype"]
            shape, nbytes = tuple(spec["shape"]), int(spec["nbytes"])
        except (TypeError, KeyError, ValueError) as e:
            raise WireError("schema", f"bad array spec: {e}")
        raw = body[off:off + nbytes]
        if len(raw) < nbytes:
            raise WireError("truncated",
                            f"array {name!r} needs {nbytes} bytes, "
                            f"{len(raw)} left")
        try:
            arrays[name] = np.frombuffer(raw, dtype=_dtype(dstr)
                                         ).reshape(shape).copy()
        except (TypeError, ValueError) as e:
            raise WireError("schema", f"array {name!r}: {e}")
        off += nbytes
    return kind, meta, arrays


# -- KV page payloads -------------------------------------------------------

def encode_pages(kind: str, meta: dict,
                 k_slabs: Sequence[np.ndarray],
                 v_slabs: Sequence[np.ndarray]) -> bytes:
    """Frame per-page K/V slab pairs (``pool.export_page`` output) as
    ``k_slabs``/``v_slabs`` stacked arrays (bfloat16 travels by dtype
    NAME — see :func:`encode_message`); ``meta['kv_dtype']`` records the
    pool dtype so the importer can refuse a mismatched pool early."""
    meta = dict(meta)
    meta["n_pages"] = len(k_slabs)
    arrays: Dict[str, np.ndarray] = {}
    if k_slabs:
        ks, vs = np.stack(k_slabs), np.stack(v_slabs)
        meta.setdefault("kv_dtype", ks.dtype.str)
        arrays = {"k_slabs": ks, "v_slabs": vs}
    return encode_message(kind, meta, arrays)


def decode_pages(meta: dict, arrays: Dict[str, np.ndarray]
                 ) -> Tuple[List[np.ndarray], List[np.ndarray]]:
    """Unstack a pages frame back into per-page slab lists."""
    n = int(meta.get("n_pages", 0))
    if n == 0:
        return [], []
    try:
        ks, vs = arrays["k_slabs"], arrays["v_slabs"]
    except KeyError as e:
        raise WireError("schema", f"pages frame missing {e}")
    if ks.shape[0] != n or vs.shape[0] != n:
        raise WireError("schema",
                        f"n_pages={n} but slab stacks are "
                        f"{ks.shape[0]}/{vs.shape[0]} deep")
    return list(ks), list(vs)


# -- telemetry frames -------------------------------------------------------

#: telemetry frame schema version — independent of WIRE_VERSION so the
#: envelope and the observability payload can evolve separately; skew is
#: refused at :func:`telemetry_from_wire` with the same structured error
TELEMETRY_VERSION = 1


def telemetry_to_wire(frame: dict) -> Tuple[dict, Dict[str, np.ndarray]]:
    """Split a telemetry frame (``observability.federation.
    collect_telemetry`` output) into JSON meta + arrays: span timestamps
    travel as int64 arrays, everything else rides the JSON header."""
    spans = frame.get("spans") or []
    meta = {
        "telemetry_version": TELEMETRY_VERSION,
        "telemetry": {k: v for k, v in frame.items() if k != "spans"},
        "span_names": [s["name"] for s in spans],
        "span_types": [s.get("event_type", "UserDefined") for s in spans],
        "span_traces": [s.get("trace_id", "") for s in spans],
        "span_args": [s.get("args") for s in spans],
    }
    arrays: Dict[str, np.ndarray] = {}
    if spans:
        arrays["span_start_ns"] = np.asarray(
            [s["start_ns"] for s in spans], np.int64)
        arrays["span_end_ns"] = np.asarray(
            [s["end_ns"] for s in spans], np.int64)
    return meta, arrays


def telemetry_from_wire(meta: dict, arrays: Dict[str, np.ndarray]) -> dict:
    """Rebuild and validate a telemetry frame. Version skew and missing
    or inconsistent columns die here with a structured
    :class:`WireError` — a malformed frame never reaches a mirror."""
    version = meta.get("telemetry_version")
    if version != TELEMETRY_VERSION:
        raise WireError(
            "version_skew",
            f"peer telemetry v{version}, this host v{TELEMETRY_VERSION}")
    try:
        base = dict(meta["telemetry"])
        names = list(meta["span_names"])
        types = list(meta["span_types"])
        traces = list(meta["span_traces"])
        argss = list(meta["span_args"])
    except (KeyError, TypeError) as e:
        raise WireError("schema", f"telemetry frame missing {e}")
    for key in ("host_id", "pid", "seq", "t_ns"):
        if key not in base:
            raise WireError("schema", f"telemetry frame missing {key!r}")
    n = len(names)
    if not (len(types) == len(traces) == len(argss) == n):
        raise WireError("schema",
                        "telemetry span columns disagree on length")
    spans = []
    if n:
        try:
            starts, ends = arrays["span_start_ns"], arrays["span_end_ns"]
        except KeyError as e:
            raise WireError("schema", f"telemetry frame missing {e}")
        if starts.shape[0] != n or ends.shape[0] != n:
            raise WireError(
                "schema", f"{n} spans but timestamp arrays are "
                f"{starts.shape[0]}/{ends.shape[0]} deep")
        for i in range(n):
            spans.append({"name": names[i], "event_type": types[i],
                          "start_ns": int(starts[i]),
                          "end_ns": int(ends[i]),
                          "trace_id": traces[i], "args": argss[i]})
    base["spans"] = spans
    return base


def encode_telemetry(frame: dict) -> bytes:
    """One standalone ``telemetry`` wire frame (the command reply embeds
    the same meta/arrays inside its reply envelope instead)."""
    meta, arrays = telemetry_to_wire(frame)
    return encode_message("telemetry", meta, arrays)


def decode_telemetry(buf: bytes) -> dict:
    kind, meta, arrays = decode_message(buf)
    if kind != "telemetry":
        raise WireError("schema",
                        f"expected a telemetry frame, got {kind!r}")
    return telemetry_from_wire(meta, arrays)


# -- compiled grammars ------------------------------------------------------

def grammar_to_wire(dfa) -> Tuple[dict, Dict[str, np.ndarray]]:
    """Split a ``TokenDFA`` into JSON meta + arrays for a frame."""
    meta = {"start": int(dfa.start), "eos_token_id": int(dfa.eos_token_id),
            "pattern": dfa.pattern, "fingerprint": dfa.fingerprint}
    return meta, {"grammar_trans": np.asarray(dfa.trans, np.int32),
                  "grammar_accepting": np.asarray(dfa.accepting, bool)}


def grammar_from_wire(meta: dict, arrays: Dict[str, np.ndarray]):
    """Rebuild the ``TokenDFA`` a peer framed with
    :func:`grammar_to_wire` (lazy import keeps wire JAX-free)."""
    from ..inference.constrain import TokenDFA
    try:
        return TokenDFA(trans=arrays["grammar_trans"],
                        accepting=arrays["grammar_accepting"],
                        start=int(meta["start"]),
                        eos_token_id=int(meta["eos_token_id"]),
                        pattern=meta.get("pattern", ""),
                        fingerprint=meta.get("fingerprint", ""))
    except KeyError as e:
        raise WireError("schema", f"grammar frame missing {e}")
