"""``paddle_tpu.serving`` — SLO-aware serving runtime over the
continuous-batching engine.

The inference stack's ``ContinuousBatchingEngine`` is a closed batch loop;
this package adds the request-serving layer the ROADMAP north star calls
for: a priority/deadline admission scheduler with load shedding and
cancellation (:mod:`.scheduler`), per-request streaming token delivery
(:mod:`.stream`), TTFT/ITL/utilization metrics exported as Prometheus
text and profiler trace events (:mod:`.metrics`), and the fleet tier —
a prefix-aware router over N engine replicas with circuit-breaker
failure detection, graceful drain and mid-stream failover
(:mod:`.router`, :mod:`.replica`, :mod:`.health`), and elastic mesh
resize for TP-sharded replicas that survive chip loss (:mod:`.elastic`).

Quick start::

    from paddle_tpu.inference.decoding import (ContinuousBatchingEngine,
                                               GenerationConfig)
    from paddle_tpu.serving import ServingScheduler

    eng = ContinuousBatchingEngine(model_cfg, GenerationConfig(
        max_new_tokens=64), num_slots=8)
    sched = ServingScheduler(eng)
    handle = sched.submit(prompt_ids, priority=0, deadline_ms=500,
                          on_token=lambda t: print(t, end=" "))
    while sched.pending:
        sched.step(params)
    tokens = handle.stream.result()
    print(sched.metrics.to_prometheus_text())
"""

from .autoscale import (  # noqa: F401
    AutoscaleConfig, AutoscaleController, AutoscalePolicy, Decision,
    ScaleRecord,
)
from .elastic import (  # noqa: F401
    ElasticServingController, FlightSnapshot, ResizeRecord,
)
from .health import (  # noqa: F401
    HealthConfig, HealthTracker, ReplicaState,
)
from .metrics import Histogram, ServingMetrics  # noqa: F401
from .multihost import (  # noqa: F401
    HostEndpoint, HostFault, HostFleetRouter, HostHandle, HostServer,
    LocalTransport, PipeTransport, RemoteRequest,
)
from .replica import ReplicaFault, ReplicaHandle  # noqa: F401
from .roles import DisaggRouter, ReplicaRole  # noqa: F401
from .router import FleetRouter, RouterConfig, RouterRequest  # noqa: F401
from .scheduler import (  # noqa: F401
    RequestState, SchedulerConfig, ServingRequest, ServingScheduler,
)
from .stream import ServingError, TokenStream  # noqa: F401
from .wire import (  # noqa: F401
    WIRE_VERSION, WireError, decode_message, decode_pages, encode_message,
    encode_pages,
)

__all__ = [
    "Histogram", "ServingMetrics", "RequestState", "SchedulerConfig",
    "ServingRequest", "ServingScheduler", "ServingError", "TokenStream",
    "HealthConfig", "HealthTracker", "ReplicaState", "ReplicaFault",
    "ReplicaHandle", "FleetRouter", "RouterConfig", "RouterRequest",
    "ElasticServingController", "FlightSnapshot", "ResizeRecord",
    "HostEndpoint", "HostFault", "HostFleetRouter", "HostHandle",
    "HostServer", "LocalTransport", "PipeTransport", "RemoteRequest",
    "WIRE_VERSION", "WireError", "encode_message", "decode_message",
    "encode_pages", "decode_pages",
    "DisaggRouter", "ReplicaRole", "AutoscaleConfig", "AutoscaleController",
    "AutoscalePolicy", "Decision", "ScaleRecord",
]
