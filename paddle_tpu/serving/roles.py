"""Disaggregated prefill/decode fleet: replica roles + KV page handoff.

A hybrid replica interleaves prompt prefill chunks with decode steps on
the same slots, so a prompt-heavy burst inflates every in-flight
stream's inter-token latency — the admission work and the decode work
fight for the same step budget. The paper's remedy (and the reason the
page-table KV layout exists — PAPERS.md "Ragged Paged Attention") is to
split the fleet by phase: **PREFILL** replicas take fresh prompts and
run prompt-heavy admission; once a request has produced its first
token, its finished KV pages are *handed off* to a **DECODE** replica,
which never admits fresh prompts and therefore decodes at a steady
cadence. **HYBRID** replicas do both (the pre-roles behaviour — a fleet
of hybrids is exactly a plain :class:`~.router.FleetRouter`).

The handoff rides the refcounted page export/import path PR 17 built
for cross-host migration, wire-framed even in-process so every transfer
is CRC-checked end to end:

1. export every *settled* full page at the source
   (``mgr.sequence_pages`` / ``mgr.export_page`` — stops one token
   short of the committed length, exactly like
   ``HostServer._cmd_export_flight``);
2. round-trip through :func:`~.wire.encode_pages` /
   :func:`~.wire.decode_message` (versioned frame, CRC verified before
   content, dtype checked against the destination pool);
3. adopt into the destination's prefix cache
   (``PrefixCache.import_prefix`` — all-or-nothing, rolls back on
   failure) and **audit**: ``check_conservation()`` on both pools and a
   memory-ledger re-balance after every import;
4. re-dispatch the continuation to the destination — the router's
   standard failover continuation already carries the trace id, the
   sampler seed pinned at router submit, and the streamed tokens as
   ``grammar_prefix``, so the resumed stream is **byte-identical** to a
   hybrid-replica run (greedy, sampled-seeded and grammar-constrained
   alike); the destination prefills only the un-exported tail (at most
   one page plus the unsettled token);
5. cancel at the source, freeing its copy of the pages.

A handoff that fails at ANY point is not an outage: export/import are
non-destructive (the destination rolls back, conservation re-checked),
so the request simply keeps decoding where it is — a hybrid-style
completion, still byte-identical.

Role flips are the autoscaler's actuation surface (:mod:`.autoscale`):
``set_role`` retags a replica and emits a ``role_changed`` event; the
controller wraps it in drain → retag → undrain so a flip never races
live admissions.

Telemetry: ``paddle_router_replica_role{replica}`` (0 hybrid /
1 prefill / 2 decode), ``paddle_handoff_requests_total{outcome}``,
``paddle_handoff_pages_total`` / ``paddle_handoff_bytes_total`` /
``paddle_handoff_seconds``, one ``kv_handoff`` event and a
``router.kv_handoff`` span per transfer.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Set

from ..observability.events import emit_event
from ..observability.journal import journal, journal_armed
from ..observability.memory import memory_armed, memory_ledger
from ..observability.registry import get_registry
from ..profiler.record import emit_span, spans_armed
from .health import ReplicaState
from .replica import ReplicaHandle
from .router import FleetRouter, RouterRequest
from .stream import ServingError
from .wire import WireError, decode_message, decode_pages, encode_pages


class ReplicaRole:
    """Replica phase assignment (string constants, like RequestState)."""

    PREFILL = "prefill"
    DECODE = "decode"
    HYBRID = "hybrid"


#: gauge encoding for ``paddle_router_replica_role``
ROLE_CODE = {ReplicaRole.HYBRID: 0, ReplicaRole.PREFILL: 1,
             ReplicaRole.DECODE: 2}

_ROLES = frozenset(ROLE_CODE)


class DisaggRouter(FleetRouter):
    """A :class:`FleetRouter` whose replicas carry roles. See module
    docstring. ``roles`` maps replica id -> role (unlisted replicas are
    HYBRID); ``handoff_min_streamed`` is how many tokens a request must
    have streamed on a PREFILL replica before its pages hand off (1 =
    hand off at prompt completion, the first decoded token proving the
    prefill settled)."""

    def __init__(self, replicas: Sequence[ReplicaHandle],
                 roles: Optional[Dict[int, str]] = None,
                 handoff_min_streamed: int = 1, **kw):
        super().__init__(replicas, **kw)
        self.roles: Dict[int, str] = {rid: ReplicaRole.HYBRID
                                      for rid in self.replicas}
        for rid, role in (roles or {}).items():
            if rid not in self.replicas:
                raise KeyError(f"no replica {rid} in the fleet")
            if role not in _ROLES:
                raise ValueError(f"unknown role {role!r}")
            self.roles[rid] = role
        self._handoff_min = max(1, int(handoff_min_streamed))
        self._handed: Set[int] = set()      # router rids already handed off
        # local mirrors (tests stay independent of registry resets)
        self.handoffs_ok = 0
        self.handoffs_failed = 0
        self.handoff_pages_total = 0
        reg = get_registry()
        self._g_role = reg.gauge(
            "paddle_router_replica_role",
            "replica role: 0 hybrid / 1 prefill / 2 decode",
            labels=("replica",))
        self._c_handoff_reqs = reg.counter(
            "paddle_handoff_requests_total",
            "prefill->decode KV handoffs by outcome",
            labels=("outcome",))
        self._c_handoff_pages = reg.counter(
            "paddle_handoff_pages_total",
            "KV pages handed from prefill to decode replicas")
        self._c_handoff_bytes = reg.counter(
            "paddle_handoff_bytes_total",
            "KV bytes handed from prefill to decode replicas")
        self._h_handoff_s = reg.histogram(
            "paddle_handoff_seconds",
            "per-request handoff latency (export -> import -> redispatch)")
        for rid in self.replicas:
            self._g_role.set(ROLE_CODE[self.roles[rid]], replica=str(rid))

    # -- roles ---------------------------------------------------------------

    def role(self, replica_id: int) -> str:
        return self.roles[replica_id]

    def set_role(self, replica_id: int, role: str,
                 reason: str = "operator") -> None:
        """Retag a replica. Emits ``role_changed``; callers that must
        not race live admissions (the autoscaler) wrap this in drain →
        retag → undrain."""
        if role not in _ROLES:
            raise ValueError(f"unknown role {role!r}")
        old = self.roles[replica_id]
        if old == role:
            return
        self.roles[replica_id] = role
        self._g_role.set(ROLE_CODE[role], replica=str(replica_id))
        emit_event("role_changed", replica=replica_id, role=role,
                   previous=old, reason=reason)

    def add_replica(self, handle: ReplicaHandle,
                    role: str = ReplicaRole.HYBRID) -> None:
        if role not in _ROLES:
            raise ValueError(f"unknown role {role!r}")
        super().add_replica(handle)
        self.roles[handle.replica_id] = role
        self._g_role.set(ROLE_CODE[role], replica=str(handle.replica_id))

    def remove_replica(self, replica_id: int) -> None:
        super().remove_replica(replica_id)
        self.roles.pop(replica_id, None)

    # -- role-aware routing --------------------------------------------------

    def _pick(self, prompt, exclude: Set[int]):
        """Fresh admissions (and failover continuations) avoid DECODE
        replicas — those receive work only via handoff. Two carve-outs
        keep the fleet live: a HALF_OPEN decode replica still takes its
        recovery probe (the breaker cannot close without one), and when
        NO prefill-capable replica is routable, availability beats role
        purity — traffic spills to the decode side rather than parking
        while healthy capacity idles."""
        blocked = {rid for rid, role in self.roles.items()
                   if role == ReplicaRole.DECODE
                   and rid in self.replicas
                   and self.replicas[rid].health.state
                   != ReplicaState.HALF_OPEN}
        rid, affinity, probe = super()._pick(prompt,
                                             set(exclude) | blocked)
        if rid is None and blocked:
            return super()._pick(prompt, exclude)
        return rid, affinity, probe

    # -- the handoff ---------------------------------------------------------

    def _step_inner(self, params) -> None:
        super()._step_inner(params)
        self._handoff_scan()

    def _pick_decode(self, exclude: Set[int]) -> Optional[int]:
        """Least-loaded accepting DECODE replica (HYBRID as fallback);
        None when nothing can take the pages."""
        for want in ((ReplicaRole.DECODE,), (ReplicaRole.HYBRID,)):
            cands = [rid for rid in sorted(self.replicas)
                     if rid not in exclude
                     and self.roles.get(rid) in want
                     and not self.replicas[rid].draining
                     and not self.replicas[rid].degraded
                     and self.replicas[rid].health.accepting]
            if cands:
                return min(cands,
                           key=lambda c: (self._load(self.replicas[c]), c))
        return None

    def _handoff_scan(self) -> None:
        for req in list(self._requests.values()):
            if req.done or req.rid in self._handed:
                continue
            src = req.replica_id
            if (src is None or req.handle is None
                    or self.roles.get(src) != ReplicaRole.PREFILL):
                continue
            if req.handle.done:
                continue            # terminal at the replica: scan closes it
            toks = req.stream.tokens
            if len(toks) < self._handoff_min:
                continue            # prompt not proven settled yet
            eos = self.replicas[src].engine.config.eos_token_id
            if len(toks) >= req.budget or (eos is not None and toks
                                           and toks[-1] == eos):
                continue            # finishing at src; nothing left to move
            dst = self._pick_decode(exclude={src})
            if dst is None:
                continue            # no decode capacity: finish hybrid-style
            self._handoff(req, src, dst)

    def _handoff(self, req: RouterRequest, src: int, dst: int) -> bool:
        """Move one request's settled KV pages src -> dst and re-bind
        its stream there (module docstring, steps 1-5). Never raises:
        a failed handoff leaves the request decoding at src."""
        r, d = self.replicas[src], self.replicas[dst]
        t0 = self._clock()
        trace = spans_armed()
        ns0 = time.perf_counter_ns() if trace else 0
        self._handed.add(req.rid)
        cancelled = False
        try:
            tokens = [int(t) for t in req.prompt] + \
                [int(t) for t in req.stream.tokens]
            mgr = r.engine.mgr
            ks: Any = ()
            vs: Any = ()
            erid = req.handle.engine_rid
            if erid is not None:
                # settled full pages only: the newest token's KV is the
                # next step's input and may not be written yet
                table = mgr.sequence_pages(erid)
                settled = min(len(tokens), mgr.sequence_len(erid))
                n_full = min(max(settled - 1, 0) // mgr.page_size,
                             len(table))
                if n_full > 0:
                    ks, vs = zip(*(mgr.export_page(p)
                                   for p in table[:n_full]))
            # wire round-trip even in-process: the CRC + schema check is
            # the same trust boundary the cross-host path crosses
            buf = encode_pages(
                "kv_handoff",
                {"tokens": tokens, "kv_dtype": str(mgr.k_pages.dtype)},
                list(ks), list(vs))
            _kind, meta, arrays = decode_message(buf)
            ks2, vs2 = decode_pages(meta, arrays)
            nbytes = int(sum(a.nbytes for a in ks2)
                         + sum(a.nbytes for a in vs2))
            if ks2:
                if meta["kv_dtype"] != str(d.engine.mgr.k_pages.dtype):
                    raise WireError(
                        "schema",
                        f"kv dtype {meta['kv_dtype']} does not match "
                        f"replica {dst}'s {d.engine.mgr.k_pages.dtype}")
                if d.engine.cache is None:
                    raise ServingError(
                        "no_prefix_cache",
                        f"replica {dst} has no prefix cache to import "
                        "into", rid=req.rid)
                imported = d.engine.cache.import_prefix(
                    meta["tokens"], ks2, vs2)
            else:
                imported = {"imported_pages": 0, "skipped_pages": 0,
                            "imported_bytes": 0, "evicted_pages": 0}
            # the page-exact audit: byte conservation after EVERY import
            d.engine.mgr.check_conservation()
            if memory_armed[0]:
                memory_ledger.observe(d.engine.mgr)
            # pages now live at dst: teach the affinity index, free the
            # src copy, land the continuation where the KV is
            self._index_insert(dst, tokens)
            try:
                r.cancel(req.handle.rid)
            except Exception:
                pass
            cancelled = True
            r.engine.mgr.check_conservation()
            self._dispatch(req, dst, None)
            dt = self._clock() - t0
            if trace:
                emit_span("router.kv_handoff", ns0,
                          time.perf_counter_ns(), trace_id=req.trace_id,
                          args={"request_id": req.rid, "src": src,
                                "dst": dst, "pages": len(ks2),
                                "bytes": nbytes})
            self.handoffs_ok += 1
            self.handoff_pages_total += len(ks2)
            self._c_handoff_reqs.inc(outcome="ok")
            self._c_handoff_pages.inc(len(ks2))
            self._c_handoff_bytes.inc(nbytes)
            self._h_handoff_s.observe(dt)
            emit_event("kv_handoff", request_id=req.rid,
                       trace_id=req.trace_id, src=src, dst=dst,
                       pages=len(ks2), bytes=nbytes,
                       imported_pages=imported["imported_pages"],
                       skipped_pages=imported["skipped_pages"],
                       seconds=round(dt, 6), outcome="ok")
            if journal_armed[0]:
                # like scale frames: a disaggregated handoff moved KV
                # between replicas, which single-fleet replay cannot
                # re-drive — the frame marks the bundle replay-refused
                journal.note_handoff(rid=req.rid, src=src, dst=dst,
                                     pages=len(ks2), outcome="ok")
            return True
        except Exception as e:  # noqa: BLE001 - per-request fallback
            dt = self._clock() - t0
            self.handoffs_failed += 1
            self._c_handoff_reqs.inc(outcome="failed")
            self._h_handoff_s.observe(dt)
            emit_event("kv_handoff", request_id=req.rid,
                       trace_id=req.trace_id, src=src, dst=dst,
                       pages=0, bytes=0, seconds=round(dt, 6),
                       outcome="failed", error=repr(e))
            if journal_armed[0]:
                journal.note_handoff(rid=req.rid, src=src, dst=dst,
                                     pages=0, outcome="failed")
            if cancelled:
                # src already gave the request up: the standard failover
                # continuation recomputes the prefix somewhere routable
                try:
                    self._route(req)
                except ServingError:
                    pass        # parked; the step loop keeps retrying
            return False

    # -- observability -------------------------------------------------------

    def statusz(self) -> Dict[str, Any]:
        out = super().statusz()
        out["roles"] = {str(rid): self.roles[rid]
                        for rid in sorted(self.roles)}
        out["handoffs"] = {"ok": self.handoffs_ok,
                           "failed": self.handoffs_failed,
                           "pages": self.handoff_pages_total}
        return out
