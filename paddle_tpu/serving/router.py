"""Prefix-aware fleet router: N engine replicas behind one front door.

One ``ContinuousBatchingEngine`` is a single point of failure — a stuck
step or a dead host is a total outage. The :class:`FleetRouter` fronts N
:class:`~.replica.ReplicaHandle` replicas and owns the three concerns a
fleet adds on top of per-replica scheduling:

**Routing.** A router-side radix index (one
:class:`~paddle_tpu.kvcache.radix.RadixTree` per replica, token blocks at
the engine's page size) records which replica served which prompt
prefix. A new request routes to the replica with the longest indexed
prefix overlap — the replica whose prefix cache most likely still holds
those KV pages — but only while that replica's load (``statusz()`` queue
depth + backoff + in-flight, plus a penalty while its SLO monitor is
burning) stays within ``load_band`` of the least-loaded candidate;
outside the band, load wins and the request spills to the least-loaded
replica. The index is a host-side *hint* (capped, LRU-evicted): a stale
entry costs a cache miss, never a wrong answer.

**Failure detection + re-admission.** Each replica's
:class:`~.health.HealthTracker` turns consecutive step failures and
watchdog silence into HEALTHY → SUSPECT → EJECTED transitions; ejection
fails over every live request, auto-dumps a flight-recorder bundle, and
stops all traffic. After a cooldown the breaker half-opens and the
router admits **exactly one** probe request; the probe completing
re-admits the replica (``replica_recovered``), a probe failure re-ejects
it with the cooldown doubled — so a flapping replica converges to
quarantine instead of flapping the fleet.

**Drain + mid-stream failover.** :meth:`drain` stops admissions, hands
the replica's still-queued requests to siblings, and lets in-flight
streams finish. When a replica dies mid-decode, each of its live
requests is resubmitted to a healthy sibling through the scheduler's
retry/backoff path (``submit(defer_s=...)``, exponential per-request
backoff): the resubmission's prompt is the original prompt plus every
token already streamed, with the remaining token budget — greedy decode
is prefix-deterministic, so the continuation is byte-identical to an
uninterrupted run and the consumer's stream just keeps going. Requests
that exhaust ``max_failovers`` fail terminally with a structured
:class:`~.stream.ServingError`; consumers never hang (router streams
also carry a producer-liveness guard for fatal, non-Exception deaths).

**Chaos.** ``fault_injector`` accepts a
:class:`~paddle_tpu.resilience.faults.FaultInjector`; each router step
asks it per replica for ``replica_die`` / ``replica_stall`` /
``replica_slow`` events (one-shot, replica-scoped), mapped onto the
replica chaos surface. With a fake clock, a chaos run is deterministic
and its greedy outputs byte-identical to the fault-free run.

Telemetry: ``paddle_router_requests_total{replica,outcome}``,
``paddle_router_replica_state{replica}`` (0 healthy / 1 suspect /
2 ejected / 3 half-open / 4 draining / 5 drained),
``paddle_router_failovers_total``,
``paddle_router_prefix_affinity_hits_total``; JSONL events
``replica_ejected`` / ``replica_recovered`` / ``failover``;
:meth:`statusz` is the fleet view the diagnostics server mounts
(``DiagServer.attach_router``), and :meth:`make_slo_monitor` builds the
fleet-completion SLO (failover/drain remediation excluded from its own
objective, mirroring the scheduler's "slo"-shed exclusion).
"""

from __future__ import annotations

import time
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

import numpy as np

from ..kvcache.policy import LRUEvictionPolicy
from ..kvcache.radix import RadixTree
from ..observability.events import emit_event
from ..observability.flight import flight_recorder
from ..observability.journal import journal, journal_armed, token_checksum
from ..observability.registry import get_registry
from ..observability.timeseries import history_armed
from ..observability.trace import new_trace_id
from ..profiler.record import emit_span, spans_armed
from .health import STATE_CODE, ReplicaState
from .replica import ReplicaHandle
from .scheduler import RequestState
from .stream import ServingError, TokenStream


@dataclass
class RouterConfig:
    """Routing and failover knobs.

    ``load_band``: prefix affinity may only beat load while the
    preferred replica is within this many requests of the least-loaded
    candidate. ``burn_penalty``: effective-load surcharge while a
    replica's SLO monitor reports degraded/breached. Failover
    resubmissions back off ``failover_backoff_s *
    failover_backoff_multiplier**(n-1)`` and give up (terminal error)
    after ``max_failovers`` per request. ``index_max_nodes`` caps each
    replica's router-side radix index (LRU leaves evicted beyond it).
    ``stall_s``/``slow_s``/``slow_delay_s`` parameterize the injected
    ``replica_stall``/``replica_slow`` chaos events.
    """

    load_band: int = 4
    burn_penalty: float = 8.0
    failover_backoff_s: float = 0.05
    failover_backoff_multiplier: float = 2.0
    max_failovers: int = 3
    index_max_nodes: int = 4096
    stall_s: float = 0.3
    slow_s: float = 0.3
    slow_delay_s: float = 0.05


@dataclass
class RouterRequest:
    """Consumer-facing handle for one fleet request. ``stream`` is the
    consumption surface; it survives failovers (the per-replica streams
    underneath are internal plumbing)."""

    rid: int
    prompt: np.ndarray
    priority: int
    budget: int                        # total new-token budget
    stream: TokenStream = None
    submit_t: float = 0.0
    deadline_t: Optional[float] = None
    state: str = RequestState.QUEUED
    trace_id: str = ""                 # ONE id for the whole fleet path:
    # minted here at router submit, handed to every replica dispatch
    # (failover resubmissions included) so the request assembles into a
    # single span tree across replicas
    sampler: Any = None                # SamplerConfig with seed MATERIAL-
    # IZED at router submit: a failover resubmission must replay the same
    # per-request stream, so the seed cannot be re-derived from the
    # sibling engine's row ids
    grammar: Any = None                # TokenDFA constraint; the dispatch
    # passes the streamed tokens as grammar_prefix so the sibling's DFA
    # resumes mid-string
    _submit_ns: int = field(default=0, repr=False)
    _failover_ns: int = field(default=0, repr=False)  # ejection time of a
    # pending failover; the next dispatch emits the router.failover_gap
    # span from it (the attributed "replica died -> sibling took over"
    # segment) and clears it
    replica_id: Optional[int] = None   # current assignment
    handle: Any = field(default=None, repr=False)  # replica-level request
    failovers: int = 0
    routed_by_affinity: bool = False   # initial routing won on prefix
    pending_failover_from: Optional[int] = field(default=None, repr=False)
    # ^ failover parked with no routable sibling: the resubmission
    # counter fires when a healed replica finally takes the request
    redispatched: bool = field(default=False, repr=False)  # any dispatch
    # after the first is remediation (failover / drain handoff) and is
    # exempt from the sibling scheduler's queue-cap shedding
    _parked_t: float = field(default=0.0, repr=False)  # when the request
    # last parked (no routable replica); the parked-age histogram and
    # the parked_expired shed event observe the wait from it
    first_token_t: Optional[float] = None
    failover_t: Optional[float] = None
    finish_t: Optional[float] = None

    @property
    def done(self) -> bool:
        return self.state in (RequestState.DONE, RequestState.CANCELLED,
                              RequestState.SHED, RequestState.FAILED)

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return (self.first_token_t - self.submit_t) * 1e3


class FleetRouter:
    """See module docstring."""

    def __init__(self, replicas: Sequence[ReplicaHandle],
                 config: Optional[RouterConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 fault_injector=None):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.replicas: Dict[int, ReplicaHandle] = {}
        for r in replicas:
            if r.replica_id in self.replicas:
                raise ValueError(f"duplicate replica id {r.replica_id}")
            self.replicas[r.replica_id] = r
        self.config = config or RouterConfig()
        self._clock = clock
        self._sleep = sleep
        self.injector = fault_injector
        self._next_rid = 0
        self._steps = 0
        # streams' producer-liveness cell: a one-field box (not `self`)
        # so consumer-held streams never pin the whole router — engines,
        # page pools, index — in memory after a fleet teardown
        self._alive = [True]
        self._requests: Dict[int, RouterRequest] = {}   # unresolved only
        self._parked: List[RouterRequest] = []  # no routable replica yet
        self._probe: Dict[int, int] = {}        # replica id -> router rid
        # last health state journaled per replica: the end-of-step diff
        # that turns breaker walks into journal `health` frames
        self._journal_health: Dict[int, str] = {}
        self.slo_monitor = None
        self.signal_bus = None                  # see attach_signal_bus
        # router-side prefix index: one tree per replica, synthetic page
        # ids (the tree wants unique ints; pages here are just node keys)
        self._index: Dict[int, RadixTree] = {
            rid: RadixTree(r.engine.page_size)
            for rid, r in self.replicas.items()}
        self._index_lru = LRUEvictionPolicy()
        self._next_index_page = 0
        # cumulative outcomes (the fleet SLO samples these, and local
        # mirrors keep tests independent of registry resets)
        self.accepted_total = 0
        self.failed_total = 0              # terminal failures only
        self.shed_total = 0
        reg = get_registry()
        self._c_requests = reg.counter(
            "paddle_router_requests_total",
            "terminal request outcomes and failover handoffs per replica",
            labels=("replica", "outcome"))
        self._g_state = reg.gauge(
            "paddle_router_replica_state",
            "replica breaker state: 0 healthy / 1 suspect / 2 ejected / "
            "3 half-open / 4 draining / 5 drained",
            labels=("replica",))
        self._c_failovers = reg.counter(
            "paddle_router_failovers_total",
            "mid-stream failovers (dead replica -> sibling resubmission)")
        self._c_affinity = reg.counter(
            "paddle_router_prefix_affinity_hits_total",
            "requests routed to the replica with the longest cached "
            "prefix overlap")
        self._h_parked_age = reg.histogram(
            "paddle_router_parked_age_seconds",
            "time a request waited parked (no routable replica) before "
            "a dispatch or its deadline shed — the all-down backlog "
            "age the autoscaler's scale-up watches")
        # ejection bundles must be self-contained: the flight recorder
        # embeds this fleet's /statusz view (fleet.json) and the active
        # request timelines (timelines.json) in every debug bundle
        flight_recorder.attach_router(self)

    # -- submission ---------------------------------------------------------

    def submit(self, prompt, priority: int = 0,
               deadline_ms: Optional[float] = None,
               max_new_tokens: Optional[int] = None,
               on_token: Optional[Callable[[int], None]] = None,
               sampler: Any = None,
               grammar: Any = None) -> RouterRequest:
        """Route a request into the fleet. Same contract as
        ``ServingScheduler.submit`` (priority classes, deadline,
        per-request budget, synchronous ``on_token``), plus fleet
        semantics: with no routable replica the request parks and is
        retried each router step until a replica heals or its deadline
        lapses. The returned handle's ``.stream`` survives failovers —
        including sampled ones: an unseeded ``sampler`` gets its seed
        materialized HERE (not per replica), so a failover resubmission
        replays the identical stream on the sibling; a ``grammar``
        constraint likewise survives because each dispatch pre-advances
        the DFA through the already-streamed tokens."""
        prompt = np.asarray(prompt, np.int32)
        rid = self._next_rid
        self._next_rid += 1
        now = self._clock()
        any_replica = next(iter(self.replicas.values()))
        budget = (int(max_new_tokens) if max_new_tokens is not None
                  else any_replica.default_max_new_tokens)
        # infeasibility is a CALLER error, judged here against the fleet
        # (assumed homogeneous) so it can never be mistaken for replica
        # failures and poison the breakers
        eng = any_replica.engine
        total = len(prompt) + budget
        if total > eng.max_seq_len:
            raise ValueError(
                f"prompt of {len(prompt)} tokens + max_new_tokens="
                f"{budget} exceeds the replicas' max_seq_len="
                f"{eng.max_seq_len}")
        if eng.mgr.pages_for(total) > eng.mgr.usable_pages:
            raise ValueError(
                f"request of {total} total tokens needs "
                f"{eng.mgr.pages_for(total)} KV pages but each replica "
                f"pool only holds {eng.mgr.usable_pages}")
        if sampler is not None:
            # pin the seed at the fleet boundary: replica row ids differ
            # across siblings, so any seed derived below this layer
            # would break failover replay
            sampler = sampler.resolved(rid * 1000003 + 7919)
        req = RouterRequest(
            rid=rid, prompt=prompt, priority=int(priority), budget=budget,
            stream=TokenStream(rid, on_token=on_token), submit_t=now,
            deadline_t=None if deadline_ms is None
            else now + deadline_ms / 1e3,
            trace_id=new_trace_id("req"),
            sampler=sampler, grammar=grammar)
        req._submit_ns = time.perf_counter_ns()
        if journal_armed[0]:
            # the arrival frame carries EVERYTHING replay needs to
            # re-submit this request: tokens, budget, priority/deadline,
            # the seed resolved above, the grammar fingerprint
            journal.note_arrival(
                rid=rid, clock=now, prompt=[int(t) for t in prompt],
                prompt_crc=token_checksum(prompt),
                priority=int(priority), deadline_ms=deadline_ms,
                budget=budget,
                sampler=(None if sampler is None else {
                    "temperature": sampler.temperature,
                    "top_k": sampler.top_k, "top_p": sampler.top_p,
                    "seed": sampler.seed}),
                grammar=(None if grammar is None else {
                    "pattern": getattr(grammar, "pattern", None),
                    "fingerprint": getattr(grammar, "fingerprint", None),
                    "eos_token_id": getattr(grammar, "eos_token_id",
                                            None)}))
        # a fatal (non-Exception) router death closes consumer streams
        # via the producer-liveness poll instead of leaving them blocked
        alive = self._alive
        req.stream.attach_producer(lambda: alive[0])
        self._requests[rid] = req
        self.accepted_total += 1
        self._route(req)
        return req

    def cancel(self, rid: int) -> bool:
        """Cancel a routed or parked request; False if unknown/finished."""
        req = self._requests.get(rid)
        if req is None or req.done:
            return False
        if req.handle is not None:
            r = self.replicas.get(req.replica_id)
            if r is not None:
                try:
                    r.cancel(req.handle.rid)
                except Exception:   # a dead replica cannot veto a cancel
                    pass
        else:
            if req in self._parked:
                self._parked.remove(req)
        self._finish(req, RequestState.CANCELLED, "cancelled", None,
                     outcome="cancelled")
        return True

    # -- routing policy -----------------------------------------------------

    def _overlap_tokens(self, replica_id: int, prompt) -> int:
        tree = self._index[replica_id]
        # peek-style match: scoring every candidate must not distort LRU
        return len(tree.match(prompt, touch=False)) * tree.page_size

    def _load(self, r: ReplicaHandle) -> float:
        load = float(r.queue_depth + r.inflight)
        mon = r.slo_monitor
        if mon is not None and mon.health() != "ok":
            load += self.config.burn_penalty
        return load

    def _pick(self, prompt, exclude: Set[int]):
        """Choose a replica: ``(replica_id, affinity_hit, is_probe)`` or
        ``(None, False, False)`` when nothing is routable. Half-open
        replicas take exactly one request (the probe) before anything
        else is considered; EJECTED and draining replicas never
        receive traffic."""
        for rid in sorted(self.replicas):
            r = self.replicas[rid]
            if rid in exclude or r.draining or r.degraded:
                continue
            if (r.health.state == ReplicaState.HALF_OPEN
                    and rid not in self._probe):
                return rid, False, True
        candidates = [
            rid for rid in sorted(self.replicas)
            if rid not in exclude
            and not self.replicas[rid].draining
            and not self.replicas[rid].degraded
            and self.replicas[rid].health.accepting]
        if not candidates:
            return None, False, False
        loads = {rid: self._load(self.replicas[rid])
                 for rid in candidates}
        min_load = min(loads.values())
        best_rid, best_ov = None, 0
        for rid in candidates:
            ov = self._overlap_tokens(rid, prompt)
            if ov > best_ov or (ov == best_ov and best_rid is not None
                                and ov > 0
                                and loads[rid] < loads[best_rid]):
                best_rid, best_ov = rid, ov
        if (best_ov > 0
                and loads[best_rid] - min_load <= self.config.load_band):
            return best_rid, True, False
        # load wins: least-loaded candidate, lowest id as the
        # deterministic tie-break
        rid = min(candidates, key=lambda c: (loads[c], c))
        return rid, False, False

    def _route(self, req: RouterRequest, exclude: Set[int] = frozenset(),
               defer_s: Optional[float] = None) -> None:
        exclude = set(exclude)
        while True:
            rid, affinity, probe = self._pick(req.prompt, exclude)
            if rid is None:
                req.handle = None
                req.replica_id = None
                if req not in self._parked:
                    req._parked_t = self._clock()
                    self._parked.append(req)
                return
            try:
                self._dispatch(req, rid, defer_s)
            except ServingError as e:
                # a replica refusing submissions (degraded under us) is
                # failing: record it and try the next candidate —
                # infeasible-request ValueErrors are caller errors and
                # propagate from submit() instead of landing here
                r = self.replicas[rid]
                r.health.record_failure(f"submit failed: {e!r}")
                if r.health.state == ReplicaState.EJECTED:
                    self._eject(rid, r, f"submit failed: {e!r}")
                exclude.add(rid)
                continue
            if probe:
                self._probe[rid] = req.rid
            if affinity:
                self._c_affinity.inc()
                if req.failovers == 0:
                    req.routed_by_affinity = True
            return

    def _dispatch(self, req: RouterRequest, rid: int,
                  defer_s: Optional[float]) -> None:
        r = self.replicas[rid]
        streamed = req.stream.tokens
        # failover continuation: prompt grows by the already-streamed
        # tokens, budget shrinks by the same count — decode then resumes
        # byte-identically on the sibling (greedy trivially; sampled
        # because the epilogue keys its PRNG by absolute token position
        # from a seed pinned at router submit)
        prompt = (req.prompt if not streamed else
                  np.concatenate([req.prompt,
                                  np.asarray(streamed, np.int32)]))
        budget = req.budget - len(streamed)
        now = self._clock()
        remaining_ms = (None if req.deadline_t is None
                        else max((req.deadline_t - now) * 1e3, 0.0))

        def _on_token(tok: int, req=req) -> None:
            if req.first_token_t is None:
                req.first_token_t = self._clock()
            req.stream.push(tok)

        req.handle = r.submit(prompt, priority=req.priority,
                              deadline_ms=remaining_ms,
                              max_new_tokens=budget, on_token=_on_token,
                              defer_s=defer_s,
                              no_shed=req.redispatched,
                              trace_id=req.trace_id,
                              sampler=req.sampler, grammar=req.grammar,
                              grammar_prefix=(list(streamed)
                                              if req.grammar is not None
                                              and streamed else None))
        if req._failover_ns:
            if spans_armed():
                # the attributed failover segment: replica ejected ->
                # a sibling accepted the resubmission
                emit_span("router.failover_gap", req._failover_ns,
                          time.perf_counter_ns(), trace_id=req.trace_id,
                          args={"request_id": req.rid, "to_replica": rid,
                                "attempt": req.failovers})
            req._failover_ns = 0
        req.redispatched = True
        req.replica_id = rid
        if req in self._parked:
            self._parked.remove(req)
            self._h_parked_age.observe(max(now - req._parked_t, 0.0))
        # index optimistically at dispatch so a burst of same-prefix
        # requests coalesces onto one replica from the first routing
        self._index_insert(rid, [int(t) for t in prompt])

    def _index_insert(self, rid: int, tokens: List[int]) -> None:
        tree = self._index[rid]
        n_blocks = len(tokens) // tree.page_size
        if n_blocks == 0:
            return
        pages = list(range(self._next_index_page,
                           self._next_index_page + n_blocks))
        self._next_index_page += n_blocks
        tree.insert(tokens, pages)
        overflow = len(tree) - self.config.index_max_nodes
        if overflow > 0:
            # the kvcache LRU policy (one leaf scan + heap, children
            # before parents) over synthetic pages: nothing is pinned,
            # so every node is refcount-0 evictable
            for victim in self._index_lru.select(tree, lambda _p: 0,
                                                 overflow):
                tree.remove(victim)

    # -- the fleet loop -----------------------------------------------------

    @property
    def pending(self) -> int:
        """Unresolved router requests (routed + parked)."""
        return len(self._requests)

    @property
    def parked(self) -> int:
        """Requests waiting for ANY routable replica (fleet backlog the
        sensor plane watches: a growing parked count is the clearest
        "scale up" signal there is)."""
        return len(self._parked)

    def step(self, params) -> int:
        """One fleet round: inject scheduled chaos, advance breakers,
        retry parked requests, step every live replica (failures feed
        the breakers; ejections fail over), resolve finished requests,
        refresh gauges, tick the fleet SLO. Returns ``pending``."""
        try:
            self._step_inner(params)
        except BaseException as e:
            if not isinstance(e, Exception):
                # fatal death: let every consumer stream observe it
                # through the producer-liveness guard, then re-raise
                self._alive[0] = False
            raise
        return self.pending

    def _step_inner(self, params) -> None:
        cfg = self.config
        self._steps += 1
        if journal_armed[0]:
            # the injected-clock sample is the replay anchor: pinning a
            # settable clock to it makes deadlines, backoffs and breaker
            # cooldowns land on the same step they did in production
            journal.note_step(self._steps, self._clock())
        # 1. scheduled chaos, replica-scoped and one-shot
        if self.injector is not None:
            for rid, r in self.replicas.items():
                if self.injector.fire("replica_die", self._steps,
                                      replica=rid):
                    r.kill()
                if self.injector.fire("replica_stall", self._steps,
                                      replica=rid):
                    r.stall(cfg.stall_s)
                if self.injector.fire("replica_slow", self._steps,
                                      replica=rid):
                    r.slow(cfg.slow_s, cfg.slow_delay_s)
        # 2. cooldowns: EJECTED -> HALF_OPEN
        for r in self.replicas.values():
            r.health.tick()
        # 3. parked requests: a replica may have healed or half-opened —
        # but a deadline that lapsed while parked sheds FIRST (re-routing
        # it would clamp the remaining deadline to 0 and, under a fake
        # clock, serve a request the contract says is dead)
        if self._parked:
            now = self._clock()
            for req in list(self._parked):
                if req.done:
                    continue
                if req.deadline_t is not None and now > req.deadline_t:
                    self._shed_parked(req)
                    continue
                self._route(req)
                if (req.handle is not None
                        and req.pending_failover_from is not None):
                    # the parked failover finally resubmitted somewhere
                    self._count_failover(req.pending_failover_from)
                    req.pending_failover_from = None
        # 4. step the fleet
        for rid in sorted(self.replicas):
            r = self.replicas[rid]
            state = r.health.state
            if state == ReplicaState.EJECTED:
                continue
            if (state == ReplicaState.HALF_OPEN
                    and self._probe.get(rid) is None):
                continue            # idle half-open: wait for a probe
            busy = r.active > 0
            if r.health.check_watchdog(busy=busy):
                if r.health.state == ReplicaState.EJECTED:
                    self._eject(rid, r, "watchdog timeout")
                    continue
            prev = r.health.state
            mark = r.progress_marker if busy else None
            try:
                r.step(params)
            except Exception as e:
                r.health.record_failure(repr(e))
                if r.health.state == ReplicaState.EJECTED:
                    self._eject(rid, r, repr(e))
                continue
            if r.degraded:
                # the scheduler burned its retry budget and drained
                # itself: unrecoverable without a fresh engine
                r.health.force_eject("scheduler degraded")
                self._eject(rid, r, "scheduler degraded")
                continue
            if busy and r.progress_marker == mark:
                # the step returned but served NOTHING: don't refresh
                # the watchdog window — a wedged-but-returning replica
                # must still trip it (no failure recorded either; the
                # watchdog is the judge of sustained silence)
                continue
            r.health.record_success()
            if (prev == ReplicaState.SUSPECT
                    and r.health.state == ReplicaState.HEALTHY):
                emit_event("replica_recovered", replica=rid, via="healed")
        # 5. resolve finished requests / expire parked deadlines
        self._scan_requests()
        # 6. drained latches + state gauge + fleet SLO
        if journal_armed[0]:
            # end-of-step health diff: one frame per TRANSITION, never
            # per step, so a stable fleet journals nothing here
            for rid in sorted(self.replicas):
                state = self.replicas[rid].health.state
                prev = self._journal_health.get(rid)
                if state != prev:
                    self._journal_health[rid] = state
                    journal.note_health(replica=rid, prev=prev,
                                        state=state)
        for rid, r in self.replicas.items():
            if (r.draining and not r.drained_event_sent
                    and not any(q.replica_id == rid and q.handle is not None
                                for q in self._requests.values())):
                r.drained_event_sent = True
                emit_event("replica_drained", replica=rid)
            self._g_state.set(self._state_code(r), replica=str(rid))
        if self.slo_monitor is not None:
            self.slo_monitor.tick()
        if self.signal_bus is not None and history_armed[0]:
            # sensor plane: decimated inside tick() — the common
            # per-step cost is one clock read + compare
            self.signal_bus.tick()

    def run(self, params, max_steps: Optional[int] = None) -> None:
        """Drive ``step`` until every request resolves."""
        steps = 0
        while self.pending:
            before = self.pending
            self.step(params)
            steps += 1
            if self.pending and max_steps is not None \
                    and steps >= max_steps:
                raise RuntimeError(
                    f"fleet loop exceeded max_steps={max_steps} with "
                    f"{self.pending} requests pending")
            self._backoff_if_stalled(before)

    def _backoff_if_stalled(self, pending_before: int) -> None:
        """Nothing progressable this instant (backoff timers / breaker
        cooldowns pending): let the clock advance. Shared by :meth:`run`
        and the elastic controller's fleet loop so the stall heuristic
        can never drift between the two."""
        if (self.pending == pending_before and self.pending
                and not any(r.active for r in self.replicas.values())):
            self._sleep(self.config.failover_backoff_s / 4)

    # -- failure handling ---------------------------------------------------

    def _eject(self, rid: int, r: ReplicaHandle, reason: str) -> None:
        inflight = [req for req in self._requests.values()
                    if req.replica_id == rid and req.handle is not None
                    and not req.done]
        emit_event("replica_ejected", replica=rid, error=reason,
                   inflight=len(inflight),
                   trace_ids=sorted(req.trace_id for req in inflight),
                   consecutive_failures=r.health.consecutive_failures,
                   cooldown_s=r.health.cooldown_s)
        # postmortem while the replica's torn state is inspectable
        # (no-op unless the flight recorder is armed with a dump dir)
        flight_recorder.auto_dump(f"replica_ejected_{rid}")
        self._probe.pop(rid, None)
        for req in inflight:
            h = req.handle
            if h.state in (RequestState.DONE, RequestState.SHED):
                continue            # terminal at the replica: scan closes it
            try:
                r.cancel(h.rid)     # reclaim slot/pages when still possible
            except Exception:
                pass
            self._failover(req, rid, reason)

    def _failover(self, req: RouterRequest, from_rid: int,
                  reason: str) -> None:
        cfg = self.config
        req.failovers += 1
        req.failover_t = self._clock()
        if not req._failover_ns:        # a parked retry keeps the FIRST
            req._failover_ns = time.perf_counter_ns()   # ejection time
        toks = req.stream.tokens
        streamed = len(toks)
        eos = next(iter(self.replicas.values())).engine.config.eos_token_id
        if streamed >= req.budget or (eos is not None and toks
                                      and toks[-1] == eos):
            # everything was already delivered (budget spent, or the
            # stream already ended on EOS — resubmitting would decode
            # PAST it, since the streamed EOS becomes prompt on the
            # sibling); only the close was lost. Salvage BEFORE the
            # exhaustion check, or a last-permitted failover would FAIL
            # a request the consumer fully holds.
            self._finish(req, RequestState.DONE, "complete", None,
                         outcome="completed")
            return
        if req.failovers > cfg.max_failovers:
            self._finish(req, RequestState.FAILED, "failed",
                         ServingError(
                             "failover_exhausted",
                             f"request {req.rid} failed over "
                             f"{req.failovers} times (last replica "
                             f"{from_rid}: {reason})", rid=req.rid),
                         outcome="failed")
            emit_event("failover", request_id=req.rid,
                       trace_id=req.trace_id,
                       from_replica=from_rid, to_replica=None,
                       streamed=streamed, attempt=req.failovers,
                       exhausted=True)
            return
        defer = (cfg.failover_backoff_s
                 * cfg.failover_backoff_multiplier ** (req.failovers - 1))
        self._route(req, exclude={from_rid}, defer_s=defer)
        # the metric means "sibling resubmissions", not "times a replica
        # lost a request": counted only when the dispatch actually
        # happened — a parked request counts later, when a healed
        # replica finally takes it (see the parked retry in step 3)
        if req.handle is not None:
            self._count_failover(from_rid)
            emit_event("failover", request_id=req.rid,
                       trace_id=req.trace_id,
                       from_replica=from_rid, to_replica=req.replica_id,
                       streamed=streamed, attempt=req.failovers,
                       backoff_s=round(defer, 4))
        else:
            req.pending_failover_from = from_rid
            emit_event("failover", request_id=req.rid,
                       trace_id=req.trace_id,
                       from_replica=from_rid, to_replica=None,
                       streamed=streamed, attempt=req.failovers,
                       parked=True)

    def _count_failover(self, from_rid: int) -> None:
        self._c_failovers.inc()
        self._c_requests.inc(replica=str(from_rid), outcome="failover")

    def _scan_requests(self) -> None:
        now = self._clock()
        for req in list(self._requests.values()):
            if req.done:
                self._requests.pop(req.rid, None)
                continue
            h = req.handle
            if h is None:           # parked: only its deadline moves it
                if req.deadline_t is not None and now > req.deadline_t:
                    self._shed_parked(req)
                continue
            if not h.done:
                continue
            if h.state == RequestState.DONE:
                self._index_insert(
                    req.replica_id,
                    [int(t) for t in req.prompt] + req.stream.tokens)
                self._finish(req, RequestState.DONE, "complete", None,
                             outcome="completed")
            elif h.state == RequestState.SHED:
                self._finish(req, RequestState.SHED,
                             h.stream.finish_reason, h.stream.error,
                             outcome="shed")
            else:
                # FAILED (scheduler degraded under us) or an unexpected
                # replica-side cancel: both mean the replica lost the
                # request — fail it over
                self._failover(req, req.replica_id,
                               f"replica-side {h.state}")

    def _shed_parked(self, req: RouterRequest) -> None:
        age = max(self._clock() - req._parked_t, 0.0)
        if req in self._parked:
            self._parked.remove(req)
            self._h_parked_age.observe(age)
        emit_event("parked_expired", request_id=req.rid,
                   trace_id=req.trace_id, age_s=round(age, 6),
                   deadline_t=req.deadline_t)
        self._finish(req, RequestState.SHED, "shed:deadline",
                     ServingError("shed_deadline",
                                  f"request {req.rid} unroutable past "
                                  "its deadline", rid=req.rid),
                     outcome="shed")

    def _finish(self, req: RouterRequest, state: str, reason: str,
                error: Optional[ServingError], outcome: str) -> None:
        req.state = state
        req.finish_t = self._clock()
        if journal_armed[0]:
            # terminal frame: the stream checksum is what replay diffs
            # to prove byte-identical reproduction; the engine-side crc
            # cross-checks that the stream matched what decode retired
            toks = [int(t) for t in req.stream.tokens]
            journal.note_outcome(
                rid=req.rid, state=state, outcome=outcome,
                replica=req.replica_id, failovers=req.failovers,
                tokens=toks, stream_crc=token_checksum(toks),
                engine_crc=(getattr(req.handle, "token_checksum", None)
                            if req.handle is not None else None))
        if req._submit_ns and spans_armed():
            # the fleet-level request envelope: the timeline collector's
            # root span, spanning router submit -> terminal outcome
            # across every replica attempt
            emit_span("router.request", req._submit_ns,
                      time.perf_counter_ns(), trace_id=req.trace_id,
                      args={"request_id": req.rid, "outcome": outcome,
                            "failovers": req.failovers})
        req.stream.close(reason, error)
        self._c_requests.inc(
            replica=(str(req.replica_id) if req.replica_id is not None
                     else "none"),
            outcome=outcome)
        if outcome == "failed":
            self.failed_total += 1
        elif outcome == "shed":
            self.shed_total += 1
        rid = req.replica_id
        if rid is not None and self._probe.get(rid) == req.rid:
            # the half-open probe resolved: completion closes the
            # circuit; anything else leaves the replica half-open for
            # the next probe (its own step failures re-eject it)
            del self._probe[rid]
            if outcome == "completed":
                self.replicas[rid].health.record_probe_success()
                emit_event("replica_recovered", replica=rid, via="probe")
        self._requests.pop(req.rid, None)

    # -- drain / fleet management -------------------------------------------

    def drain(self, replica_id: int) -> None:
        """Gracefully remove a replica from rotation: no new admissions,
        queued (not yet decoding) requests hand off to siblings now,
        in-flight streams finish where they are."""
        r = self.replicas[replica_id]
        if r.draining:
            return
        r.draining = True
        r.drained_event_sent = False
        # a queued half-open probe hands off with everything else below;
        # drop its bookkeeping or the stale entry would block any future
        # probe (and the replica would sit HALF_OPEN forever)
        self._probe.pop(replica_id, None)
        emit_event("replica_draining", replica=replica_id,
                   inflight=r.inflight, queued=r.queue_depth)
        for req in list(self._requests.values()):
            if (req.replica_id != replica_id or req.handle is None
                    or req.done):
                continue
            if req.handle.state == RequestState.QUEUED:
                try:
                    r.cancel(req.handle.rid)
                except Exception:
                    pass
                self._route(req, exclude={replica_id})
                if req.handle is not None:      # parked handoffs (no
                    # routable sibling) don't count as handoffs
                    self._c_requests.inc(replica=str(replica_id),
                                         outcome="drain_handoff")

    def undrain(self, replica_id: int) -> None:
        """Return a drained replica to rotation."""
        r = self.replicas[replica_id]
        r.draining = False
        r.drained_event_sent = False

    def eject_replica(self, replica_id: int, reason: str) -> None:
        """Operator/controller-initiated hard ejection: force the
        breaker open and run the standard ejection path — flight-
        recorder auto-dump, cancel + byte-identical mid-stream failover
        of every live request to siblings (parking them when none is
        routable, to be re-taken as replicas heal). The elastic resize
        controller calls this when a replica's TP mesh loses a chip:
        the torn mesh must stop serving NOW, exactly like a dead
        engine."""
        r = self.replicas[replica_id]
        r.health.force_eject(reason)
        self._eject(replica_id, r, reason)

    def invalidate_index(self, replica_id: int,
                         page_size: Optional[int] = None) -> None:
        """Drop the router-side prefix index slice for one replica: a
        replaced or mesh-resized replica starts with a COLD pool, so a
        surviving index entry would route affinity traffic to prefixes
        the new pool no longer holds (a stale hit costs a miss, but a
        systematic one defeats the affinity win). Called by
        :meth:`replace_replica` and the elastic resize controller."""
        ps = (page_size if page_size is not None
              else self.replicas[replica_id].engine.page_size)
        self._index[replica_id] = RadixTree(ps)

    def replace_replica(self, handle: ReplicaHandle) -> None:
        """Swap a fresh :class:`ReplicaHandle` (same id, new engine) into
        the fleet — the recovery path for a replica whose scheduler
        degraded, whose process died for real, or whose TP mesh resized
        under it. The router-side prefix index for that id resets (the
        new engine's cache is cold) and the reused id's
        ``paddle_serving_r<id>`` metrics namespace re-registers
        idempotently (the registry sink replaces; regression-tested)."""
        rid = handle.replica_id
        if rid not in self.replicas:
            raise KeyError(f"no replica {rid} in the fleet")
        live = [req for req in self._requests.values()
                if req.replica_id == rid and req.handle is not None
                and not req.done]
        if live:
            raise RuntimeError(
                f"replica {rid} still owns {len(live)} live requests; "
                "drain or eject it first")
        self.replicas[rid] = handle
        self.invalidate_index(rid, page_size=handle.engine.page_size)
        self._probe.pop(rid, None)

    def add_replica(self, handle: ReplicaHandle) -> None:
        """Grow the fleet: register a NEW replica id with a cold prefix
        index — the autoscaler's scale-up actuation. The handle must
        share the fleet's clock; it starts taking traffic on the next
        routing decision."""
        rid = handle.replica_id
        if rid in self.replicas:
            raise ValueError(f"replica {rid} already in the fleet "
                             "(use replace_replica to swap engines)")
        self.replicas[rid] = handle
        self._index[rid] = RadixTree(handle.engine.page_size)
        self._g_state.set(self._state_code(handle), replica=str(rid))

    def remove_replica(self, replica_id: int) -> None:
        """Shrink the fleet: deregister a replica that owns no live
        requests — the autoscaler's scale-down completion, after a
        graceful drain emptied it. Raises while anything is still
        assigned (drain first), and refuses to remove the last
        replica."""
        if replica_id not in self.replicas:
            raise KeyError(f"no replica {replica_id} in the fleet")
        if len(self.replicas) == 1:
            raise RuntimeError("cannot remove the last replica")
        live = [req for req in self._requests.values()
                if req.replica_id == replica_id and req.handle is not None
                and not req.done]
        if live:
            raise RuntimeError(
                f"replica {replica_id} still owns {len(live)} live "
                "requests; drain it first")
        self.replicas.pop(replica_id)
        self._index.pop(replica_id, None)
        self._probe.pop(replica_id, None)

    # -- observability ------------------------------------------------------

    def _state_code(self, r: ReplicaHandle) -> int:
        if r.draining:
            return 5 if r.drained_event_sent else 4
        return STATE_CODE[r.health.state]

    def fleet_health(self) -> str:
        """``ok`` | ``degraded`` | ``breached`` for /healthz: breached
        only when NO replica can take ANY traffic — a half-open replica
        counts, because it can take its probe and recovery REQUIRES that
        probe to be routed (reporting breached would let an upstream
        load balancer starve the probes and turn a recoverable outage
        permanent). Degraded while any replica is not plainly healthy."""
        routable = [r for r in self.replicas.values()
                    if (r.health.accepting
                        or r.health.state == ReplicaState.HALF_OPEN)
                    and not r.draining and not r.degraded]
        if not routable:
            return "breached"
        if any(r.health.state != ReplicaState.HEALTHY or r.draining
               or r.degraded for r in self.replicas.values()):
            return "degraded"
        return "ok"

    def statusz(self) -> Dict[str, Any]:
        """The fleet view for /statusz: per-replica scheduler + breaker
        state, routing counters, parked/probe bookkeeping."""
        out: Dict[str, Any] = {
            "steps": self._steps,
            "health": self.fleet_health(),
            "pending": self.pending,
            "parked": len(self._parked),
            "probes": {str(k): v for k, v in self._probe.items()},
            "counters": {
                "accepted_total": self.accepted_total,
                "failed_total": self.failed_total,
                "shed_total": self.shed_total,
            },
            "replicas": {str(rid): self.replicas[rid].statusz()
                         for rid in sorted(self.replicas)},
            "index_nodes": {str(rid): len(t)
                            for rid, t in self._index.items()},
        }
        if self.slo_monitor is not None:
            out["slo"] = self.slo_monitor.states()
        if self.signal_bus is not None:
            out["signals"] = self.signal_bus.values()
        return out

    def journal_topology(self) -> Dict[str, Any]:
        """The fleet half of a journal head frame: everything
        :mod:`~paddle_tpu.observability.replay` needs to rebuild this
        router — its config plus each replica's engine geometry,
        generation defaults, scheduler and breaker configs. Pure
        configuration, no runtime state: replay reconstructs state by
        re-driving the journaled frames."""
        return {
            "router_kind": type(self).__name__,
            "config": asdict(self.config),
            "replicas": [self.replicas[rid].journal_spec()
                         for rid in sorted(self.replicas)],
        }

    def attach_signal_bus(self, bus=None, **bus_kw):
        """Wire the fleet sensor plane: a :class:`~paddle_tpu.
        observability.signals.SignalBus` carrying fleet pending/parked
        plus per-replica queue depth, SLO burn and speculation
        acceptance, ticked once per router step while armed (see
        ``ServingScheduler.attach_signal_bus``). Re-attach after
        ``replace_replica`` so per-replica signals follow the new
        handle."""
        if bus is None:
            from ..observability.signals import SignalBus
            bus_kw.setdefault("clock", self._clock)
            bus = SignalBus(**bus_kw)
        bus.attach_router(self)
        self.signal_bus = bus
        return bus

    def make_slo_monitor(self, completion_target: float = 0.99,
                         **monitor_kw):
        """Fleet-completion SLO: at least ``completion_target`` of
        accepted requests must resolve without a terminal failure or
        shed. Failover and drain handoffs are remediation, not bad
        events — counting them would let the router's own recovery
        cascade into a breach (same exclusion the scheduler applies to
        its "slo" sheds). Ticks once per router step on the router's
        clock."""
        from ..observability.slo import SLOMonitor, ratio_objective
        monitor_kw.setdefault("clock", self._clock)
        monitor = SLOMonitor([ratio_objective(
            "fleet_completion",
            lambda: self.failed_total + self.shed_total,
            lambda: self.accepted_total,
            target=completion_target,
            description=f"{completion_target:.2%} of accepted requests "
                        "complete (failover remediation excluded)")],
            **monitor_kw)
        self.slo_monitor = monitor
        return monitor
