"""Per-request incremental token delivery.

A :class:`TokenStream` is the consumer-facing half of a serving request:
tokens surface as each engine decode chunk completes (pushed by the
scheduler via the engine's ``token_callback``), not at ``collect()`` time.

Consumption models, all safe to mix:

* **callback** — ``submit(..., on_token=fn)``: ``fn(token)`` fires
  synchronously as the chunk is unpacked (lowest latency, runs on the
  scheduler thread — keep it cheap).
* **polling / same-thread driving** — ``stream.drain()`` returns the
  tokens that arrived since the previous drain; natural when one thread
  alternates ``scheduler.step(params)`` / ``stream.drain()``.
* **blocking iteration** — ``for tok in stream:`` from another thread
  blocks until tokens arrive and stops at end-of-stream.

End of stream carries a reason (``"complete"``, ``"cancelled"``,
``"shed:queue_full"``, ``"shed:deadline"``, ``"failed"``) and, for
failures, a structured :class:`ServingError`.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterator, List, Optional


class ServingError(RuntimeError):
    """Structured serving-layer error (shed / engine failure).

    ``code`` is machine-readable (``"shed_queue_full"``,
    ``"shed_deadline"``, ``"cancelled"``, ``"engine_failure"``); ``rid``
    is the serving request id the error applies to (None for
    scheduler-wide failures)."""

    def __init__(self, code: str, message: str, rid: Optional[int] = None):
        super().__init__(message)
        self.code = code
        self.rid = rid

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"ServingError(code={self.code!r}, rid={self.rid}, " \
               f"message={self.args[0]!r})"


class TokenStream:
    """Thread-safe incremental token channel for one request."""

    def __init__(self, rid: int,
                 on_token: Optional[Callable[[int], None]] = None):
        self.rid = rid
        self._on_token = on_token
        self._cond = threading.Condition()
        self._tokens: List[int] = []      # everything delivered so far
        self._cursor = 0                  # drain()/iterator position
        self.finished = False
        self.finish_reason: Optional[str] = None
        self.error: Optional[ServingError] = None

    # -- producer side (scheduler) ------------------------------------------

    def push(self, token: int) -> None:
        with self._cond:
            if self.finished:
                return
            self._tokens.append(token)
            self._cond.notify_all()
        if self._on_token is not None:
            self._on_token(token)

    def close(self, reason: str, error: Optional[ServingError] = None
              ) -> None:
        with self._cond:
            if self.finished:
                return
            self.finished = True
            self.finish_reason = reason
            self.error = error
            self._cond.notify_all()

    # -- consumer side ------------------------------------------------------

    @property
    def tokens(self) -> List[int]:
        """Snapshot of every token delivered so far."""
        with self._cond:
            return list(self._tokens)

    def drain(self) -> List[int]:
        """Non-blocking: tokens that arrived since the previous drain."""
        with self._cond:
            new = self._tokens[self._cursor:]
            self._cursor = len(self._tokens)
            return new

    def get(self, timeout: Optional[float] = None) -> Optional[int]:
        """Blocking: next undrained token, or None at end-of-stream (or
        on timeout)."""
        with self._cond:
            while self._cursor >= len(self._tokens):
                if self.finished:
                    return None
                if not self._cond.wait(timeout):
                    return None
            tok = self._tokens[self._cursor]
            self._cursor += 1
            return tok

    def __iter__(self) -> Iterator[int]:
        while True:
            tok = self.get()
            if tok is None:
                if self.finished:
                    return
                continue  # pragma: no cover - spurious wakeup only
            yield tok

    def result(self) -> List[int]:
        """All tokens, raising the stream's ServingError if it failed.
        Non-blocking — call after the scheduler has drained."""
        if self.error is not None:
            raise self.error
        return self.tokens
