"""Per-request incremental token delivery.

A :class:`TokenStream` is the consumer-facing half of a serving request:
tokens surface as each engine decode chunk completes (pushed by the
scheduler via the engine's ``token_callback``), not at ``collect()`` time.

Consumption models, all safe to mix:

* **callback** — ``submit(..., on_token=fn)``: ``fn(token)`` fires
  synchronously as the chunk is unpacked (lowest latency, runs on the
  scheduler thread — keep it cheap).
* **polling / same-thread driving** — ``stream.drain()`` returns the
  tokens that arrived since the previous drain; natural when one thread
  alternates ``scheduler.step(params)`` / ``stream.drain()``.
* **blocking iteration** — ``for tok in stream:`` from another thread
  blocks until tokens arrive and stops at end-of-stream.

End of stream carries a reason (``"complete"``, ``"cancelled"``,
``"shed:queue_full"``, ``"shed:deadline"``, ``"failed"``) and, for
failures, a structured :class:`ServingError`.

A consumer blocked in ``get()``/iteration must never hang forever on a
producer that died without closing the stream (an engine crash that
skips the finish callback, a router torn down by a fatal error).
:meth:`TokenStream.attach_producer` binds a liveness predicate: blocking
waits poll it, and the moment it reports the producer dead the stream
self-closes with a terminal ``ServingError("producer_dead")`` instead of
blocking indefinitely — no consumer-side timeout needed.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterator, List, Optional


class ServingError(RuntimeError):
    """Structured serving-layer error (shed / engine failure).

    ``code`` is machine-readable (``"shed_queue_full"``,
    ``"shed_deadline"``, ``"cancelled"``, ``"engine_failure"``); ``rid``
    is the serving request id the error applies to (None for
    scheduler-wide failures)."""

    def __init__(self, code: str, message: str, rid: Optional[int] = None):
        super().__init__(message)
        self.code = code
        self.rid = rid

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"ServingError(code={self.code!r}, rid={self.rid}, " \
               f"message={self.args[0]!r})"


class TokenStream:
    """Thread-safe incremental token channel for one request."""

    def __init__(self, rid: int,
                 on_token: Optional[Callable[[int], None]] = None):
        self.rid = rid
        self._on_token = on_token
        self._cond = threading.Condition()
        self._tokens: List[int] = []      # everything delivered so far
        self._cursor = 0                  # drain()/iterator position
        self.finished = False
        self.finish_reason: Optional[str] = None
        self.error: Optional[ServingError] = None
        self._alive_fn: Optional[Callable[[], bool]] = None
        self._poll_s = 0.05

    def attach_producer(self, alive_fn: Callable[[], bool],
                        poll_s: float = 0.05) -> None:
        """Bind a producer-liveness predicate (see module docstring):
        while it returns True, blocking consumers wait normally; once it
        returns False and the stream is still open, the next blocked (or
        blocking) consumer closes it with a terminal
        ``ServingError("producer_dead")`` and unblocks everyone."""
        with self._cond:
            self._alive_fn = alive_fn
            self._poll_s = float(poll_s)
            self._cond.notify_all()

    # -- producer side (scheduler) ------------------------------------------

    def push(self, token: int) -> None:
        with self._cond:
            if self.finished:
                return
            self._tokens.append(token)
            self._cond.notify_all()
        if self._on_token is not None:
            self._on_token(token)

    def close(self, reason: str, error: Optional[ServingError] = None
              ) -> None:
        with self._cond:
            self._close_locked(reason, error)

    def _close_locked(self, reason: str,
                      error: Optional[ServingError]) -> None:
        if self.finished:
            return
        self.finished = True
        self.finish_reason = reason
        self.error = error
        self._cond.notify_all()

    def _producer_died_locked(self) -> bool:
        """Under the lock: terminally close an open stream whose bound
        producer reports dead. Returns True when the stream is (now)
        closed because of it."""
        if self._alive_fn is None or self.finished:
            return False
        try:
            alive = self._alive_fn()
        except Exception:
            alive = False            # a torn liveness probe IS death
        if alive:
            return False
        self._close_locked(
            "failed",
            ServingError("producer_dead",
                         f"producer for request {self.rid} died without "
                         "finishing the stream", rid=self.rid))
        return True

    # -- consumer side ------------------------------------------------------

    @property
    def tokens(self) -> List[int]:
        """Snapshot of every token delivered so far."""
        with self._cond:
            return list(self._tokens)

    def drain(self) -> List[int]:
        """Non-blocking: tokens that arrived since the previous drain."""
        with self._cond:
            new = self._tokens[self._cursor:]
            self._cursor = len(self._tokens)
            return new

    def get(self, timeout: Optional[float] = None) -> Optional[int]:
        """Blocking: next undrained token, or None at end-of-stream (or
        on timeout, or when a bound producer died — the stream then
        carries a terminal ``producer_dead`` error)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while self._cursor >= len(self._tokens):
                if self.finished:
                    return None
                if self._producer_died_locked():
                    return None
                if deadline is None:
                    wait_t = self._poll_s if self._alive_fn is not None \
                        else None
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    wait_t = (remaining if self._alive_fn is None
                              else min(remaining, self._poll_s))
                self._cond.wait(wait_t)
            tok = self._tokens[self._cursor]
            self._cursor += 1
            return tok

    def __iter__(self) -> Iterator[int]:
        while True:
            tok = self.get()
            if tok is None:
                if self.finished:
                    return
                continue  # pragma: no cover - spurious wakeup only
            yield tok

    def result(self) -> List[int]:
        """All tokens, raising the stream's ServingError if it failed.
        Non-blocking — call after the scheduler has drained."""
        if self.error is not None:
            raise self.error
        return self.tokens
