"""Elastic mesh resize: TP-sharded serving replicas that survive chip
loss.

A multi-chip replica (``ContinuousBatchingEngine(mesh=...)``) is one
failure domain per CHIP, not per host: lose one chip of an mp=4 mesh and
the other three still hold 3/4 of the weights and 3/4 of every KV page —
useless alone (each holds only its GQA groups), but the host-side state
that DEFINES the replica (prompts, streamed tokens, page tables,
allocator books) is chip-agnostic. So a chip loss is survivable by
construction: checkpoint the live request state, re-shard onto the
surviving mesh, and replay — which is exactly the resilience layer's
elastic-restart shape (``launch.watch`` restarts dead peers in place;
``ResilientTrainer`` resumes from host state), applied to serving.

:class:`ElasticServingController` owns that arc for every replica of a
:class:`~.router.FleetRouter`. Two paths, one state machine::

    chip_die       (crash path — the chip is GONE mid-decode)
      chip_lost -> checkpoint flights -> eject (siblings absorb the
      flights via the byte-identical mid-stream failover; with no
      routable sibling they park) -> re-shard -> replace_replica ->
      rejoined (HEALTHY, routable)

    chip_degraded  (graceful path — the chip must be retired but still
      answers; ICI flaps, ECC pressure)
      chip_lost -> drain (queued requests hand off now, in-flight
      streams finish in place) -> drained -> re-shard ->
      replace_replica -> undrain -> rejoined

Re-sharding is a REBUILD, not a migration: the new engine's weights are
placed fresh on the surviving mesh (``models.llama.shard_params_tp``
from the same host params the serving loop passes every step) and its
KV pool starts cold — the router's prefix-index slice for the replica is
invalidated (``FleetRouter.invalidate_index``) so affinity can never
route to prefixes the new pool no longer holds. Because greedy decode is
prefix-deterministic, every absorbed flight's continuation is
byte-identical to an uninterrupted run, so a whole chip-loss storm ends
byte-identical to the fault-free run (the chaos acceptance suite asserts
it).

Chaos: a :class:`~paddle_tpu.resilience.faults.FaultInjector` schedules
one-shot ``chip_die`` / ``chip_degraded`` events with (replica, chip)
addressing (``FaultInjector.seeded_chips``); :meth:`step` polls them
before each router round, so the whole die → re-shard → rejoin arc is
deterministic and replayable from a seed.

Telemetry: ``paddle_mesh_chips{replica}`` (current TP degree),
``paddle_mesh_resizes_total{replica}``,
``paddle_mesh_chip_faults_total{replica,kind}``; JSONL events
``chip_lost`` / ``mesh_resized``; every resize appends a
:class:`ResizeRecord` (phase timeline + checkpointed flight state)
served by :meth:`timeline_snapshot` and embedded as ``elastic.json`` in
every flight-recorder bundle — the chip-loss postmortem carries its own
resize timeline.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..observability.events import emit_event
from ..observability.flight import flight_recorder
from ..observability.registry import get_registry
from ..parallel.mesh import shrink_serving_mesh
from .replica import ReplicaHandle
from .router import FleetRouter

#: resize records kept in memory (oldest dropped; bundles persist them)
MAX_RESIZES = 64

#: process-global arc counter: the flight recorder dedupes auto_dump
#: reasons once-per-process, so bundle names must never collide even
#: across controllers (a later controller replaces an earlier one)
_ARC_SEQ = itertools.count(1)


@dataclass
class FlightSnapshot:
    """One live request's state, checkpointed at the moment of chip
    loss: the prompt, every token already streamed to the consumer and
    the page metadata its sequence held. This is the continuation basis
    the failover path resubmits (prompt + streamed, remaining budget) —
    recorded here so the resize timeline documents exactly what state
    survived the chip."""

    router_rid: int
    trace_id: str
    prompt: List[int]
    streamed: List[int]
    pages: int
    engine_rid: Optional[int] = None
    #: the request's RESOLVED SamplerConfig fields (seed already pinned
    #: by ``FleetRouter.submit``) — without them a migrated sampled
    #: stream would resume under a different PRNG lane and diverge
    sampler: Optional[Dict[str, Any]] = None
    #: tokens the grammar's DFA already consumed (== streamed at
    #: checkpoint time); the resume path fast-forwards the automaton
    #: through these so the constraint continues mid-match
    grammar_prefix: Optional[List[int]] = None

    def as_dict(self) -> Dict[str, Any]:
        return {"router_rid": self.router_rid, "trace_id": self.trace_id,
                "prompt_tokens": len(self.prompt),
                "streamed_tokens": len(self.streamed),
                "pages": self.pages, "engine_rid": self.engine_rid,
                "sampler": self.sampler,
                "grammar_prefix_tokens": (len(self.grammar_prefix)
                                          if self.grammar_prefix is not None
                                          else None)}


@dataclass
class ResizeRecord:
    """One chip-loss → rejoin arc (the resize state machine's log)."""

    replica: int
    chip: int
    kind: str                       # "die" | "degraded"
    from_chips: int
    to_chips: int = 0               # filled at re-shard
    step: int = 0                   # controller step the fault fired at
    phases: List[tuple] = field(default_factory=list)   # (phase, t)
    flights: List[FlightSnapshot] = field(default_factory=list)

    def phase(self, name: str, t: float) -> None:
        self.phases.append((name, float(t)))

    @property
    def done(self) -> bool:
        return bool(self.phases) and self.phases[-1][0] == "rejoined"

    def as_dict(self) -> Dict[str, Any]:
        return {"replica": self.replica, "chip": self.chip,
                "kind": self.kind, "from_chips": self.from_chips,
                "to_chips": self.to_chips, "step": self.step,
                "phases": [{"phase": p, "t": t} for p, t in self.phases],
                "flights": [f.as_dict() for f in self.flights]}


class ElasticServingController:
    """See module docstring.

    ``engine_factory(mesh)`` builds a fresh
    ``ContinuousBatchingEngine`` sharded over ``mesh`` (None = a
    single-chip engine — a 1-chip replica losing its only chip rebuilds
    in place, the "replacement chip arrived" story);
    ``handle_factory(replica_id, engine)`` wraps it into the
    :class:`~.replica.ReplicaHandle` the router owns (reusing the
    replica id — the ``paddle_serving_r<id>`` namespace re-registers
    idempotently). Both factories are the SAME ones that built the
    original fleet, so a resized replica differs from its predecessor
    only in mesh degree."""

    def __init__(self, router: FleetRouter,
                 engine_factory: Callable[[Optional[Any]], Any],
                 handle_factory: Callable[[int, Any], ReplicaHandle],
                 fault_injector=None,
                 clock: Callable[[], float] = time.monotonic):
        self.router = router
        self.engine_factory = engine_factory
        self.handle_factory = handle_factory
        self.injector = fault_injector
        self._clock = clock
        self._steps = 0
        #: graceful resizes waiting for their drain to complete
        self._graceful: Dict[int, ResizeRecord] = {}
        #: completed + in-progress resize records, oldest first. Each
        #: crash-path record checkpoints its flights' token state, so
        #: the log is bounded (oldest arcs dropped past MAX_RESIZES) —
        #: a long-lived fleet must not accumulate dead token lists.
        self.resizes: List[ResizeRecord] = []
        reg = get_registry()
        self._g_chips = reg.gauge(
            "paddle_mesh_chips",
            "current TP mesh degree per serving replica",
            labels=("replica",))
        self._c_resizes = reg.counter(
            "paddle_mesh_resizes_total",
            "elastic mesh resizes completed per replica "
            "(chip loss -> re-shard -> rejoin)",
            labels=("replica",))
        self._c_faults = reg.counter(
            "paddle_mesh_chip_faults_total",
            "chip-level faults handled per replica by kind "
            "(die = crash path, degraded = graceful drain path)",
            labels=("replica", "kind"))
        for rid, r in router.replicas.items():
            self._g_chips.set(r.engine.num_chips, replica=str(rid))
        # chip-loss postmortem bundles embed elastic.json (the resize
        # timeline + checkpointed flight state)
        flight_recorder.attach_elastic(self)

    # -- the fleet loop (wraps FleetRouter.step) -----------------------------

    def step(self, params) -> int:
        """One elastic fleet round: poll scheduled chip chaos, advance
        pending graceful drains to their re-shard, then run the router
        round. Returns the router's ``pending``. Step numbering is
        1-based and locksteps with the router's (this calls
        ``router.step`` exactly once), so ``FaultInjector.seeded_chips``
        schedules land on the same rounds as replica-scoped faults."""
        self._steps += 1
        if self.injector is not None:
            for rid in sorted(self.router.replicas):
                chip = self.injector.fire_chip("chip_die", self._steps,
                                               replica=rid)
                if chip is not None:
                    self.lose_chip(rid, chip)
                chip = self.injector.fire_chip("chip_degraded",
                                               self._steps, replica=rid)
                if chip is not None:
                    self.retire_chip(rid, chip)
        self._advance_graceful()
        return self.router.step(params)

    @property
    def resizing(self) -> bool:
        """True while any graceful resize is waiting out its drain
        (crash-path resizes complete synchronously inside :meth:`step`).
        The fleet-loop exit condition is
        ``not router.pending and not ctl.resizing``."""
        return bool(self._graceful)

    def run(self, params, max_steps: Optional[int] = None) -> None:
        """Drive :meth:`step` until every router request resolves AND
        every pending graceful resize has rejoined."""
        steps = 0
        while self.router.pending or self._graceful:
            before = self.router.pending
            self.step(params)
            steps += 1
            if max_steps is not None and steps >= max_steps and (
                    self.router.pending or self._graceful):
                raise RuntimeError(
                    f"elastic fleet loop exceeded max_steps={max_steps} "
                    f"with {self.router.pending} pending, "
                    f"{len(self._graceful)} resizes draining")
            self.router._backoff_if_stalled(before)

    # -- the two fault paths -------------------------------------------------

    def lose_chip(self, replica_id: int, chip: int) -> ResizeRecord:
        """Crash path: the chip is gone mid-decode. Checkpoint the live
        request state, hard-eject the replica (the router cancels +
        fails over every flight — siblings absorb them with
        byte-identical continuations, or they park until the rebuilt
        replica rejoins), then re-shard onto the surviving mesh and
        rejoin through ``replace_replica``. Synchronous: the replica is
        HEALTHY on the smaller mesh when this returns."""
        r = self.router.replicas[replica_id]
        now = self._clock()
        chip = self._clamp_chip(r, chip)
        stale = self._graceful.pop(replica_id, None)
        if stale is not None:
            # the crash supersedes a pending graceful drain: the rebuilt
            # replica gets a fresh, re-indexed mesh that already excludes
            # the dead chip, so the old record's chip address is void —
            # completing it would re-shard the new replica a second time
            # with a chip index from the old, larger mesh
            stale.phase("superseded", now)
        rec = ResizeRecord(replica=replica_id, chip=int(chip), kind="die",
                           from_chips=r.engine.num_chips,
                           step=self._steps)
        rec.phase("chip_lost", now)
        rec.flights = self._snapshot_flights(replica_id)
        rec.phase("checkpointed", self._clock())
        self.resizes.append(rec)
        self._c_faults.inc(replica=str(replica_id), kind="die")
        emit_event("chip_lost", replica=replica_id, chip=int(chip),
                   cause="die", chips=rec.from_chips,
                   inflight=len(rec.flights),
                   trace_ids=sorted(f.trace_id for f in rec.flights))
        # the torn mesh must stop serving NOW: any stray step raises,
        # exactly like a dead engine (deterministic-chaos surface)
        r.kill()
        self.router.eject_replica(replica_id,
                                  f"chip {int(chip)} lost (mesh torn)")
        rec.phase("ejected", self._clock())
        self._reshard(replica_id, rec)
        return rec

    def retire_chip(self, replica_id: int, chip: int) -> ResizeRecord:
        """Graceful path: the chip must be retired but still answers.
        Drain the replica (queued requests hand off to siblings now,
        in-flight streams finish in place — no failovers, no replayed
        tokens), then re-shard + undrain once the drain completes
        (:meth:`step` advances it)."""
        r = self.router.replicas[replica_id]
        chip = self._clamp_chip(r, chip)
        pending = self._graceful.get(replica_id)
        if pending is not None:
            # a drain is already waiting out its in-flight streams: chip
            # indices address the mesh that existed when the FIRST fault
            # fired, and the re-shard rebuilds the replica on a fresh,
            # re-indexed mesh — a second retirement cannot be resolved
            # against either mesh. Count the fault, annotate the pending
            # arc, and leave its record intact; the retirement must be
            # re-issued against the rebuilt mesh once this arc rejoins.
            pending.phase("coalesced", self._clock())
            self._c_faults.inc(replica=str(replica_id), kind="degraded")
            emit_event("chip_lost", replica=replica_id, chip=int(chip),
                       cause="degraded", chips=r.engine.num_chips,
                       inflight=r.inflight, coalesced=True, trace_ids=[])
            return pending
        rec = ResizeRecord(replica=replica_id, chip=int(chip),
                           kind="degraded",
                           from_chips=r.engine.num_chips,
                           step=self._steps)
        rec.phase("chip_lost", self._clock())
        self.resizes.append(rec)
        self._c_faults.inc(replica=str(replica_id), kind="degraded")
        emit_event("chip_lost", replica=replica_id, chip=int(chip),
                   cause="degraded", chips=rec.from_chips,
                   inflight=r.inflight, trace_ids=[])
        self.router.drain(replica_id)
        rec.phase("draining", self._clock())
        self._graceful[replica_id] = rec
        return rec

    @staticmethod
    def _clamp_chip(r: ReplicaHandle, chip) -> int:
        """Clamp a scheduled chip index into the replica's ACTUAL mesh
        degree. Chaos schedules address (replica, chip) approximately —
        a ``seeded_chips(num_chips=4)`` fault may land on a replica
        already resized to mp=2 — and an out-of-range index must hit a
        real chip (``shrink_serving_mesh`` rejects it otherwise, which
        would crash the controller instead of the chaos drill)."""
        return max(0, min(int(chip), r.engine.num_chips - 1))

    def _advance_graceful(self) -> None:
        for rid, rec in list(self._graceful.items()):
            r = self.router.replicas[rid]
            if r.pending:
                continue            # in-flight streams still finishing
            self._graceful.pop(rid)
            rec.phase("drained", self._clock())
            self._reshard(rid, rec)

    # -- re-shard + rejoin ---------------------------------------------------

    def _reshard(self, replica_id: int, rec: ResizeRecord) -> None:
        old = self.router.replicas[replica_id]
        was_draining = old.draining
        mesh = old.engine.mesh
        if mesh is not None and old.engine.num_chips > 1:
            nkv = old.engine.model_config.num_key_value_heads
            new_mesh = shrink_serving_mesh(mesh, rec.chip, nkv)
        else:
            # single-chip replica (mesh-less, or already resized down
            # to its degree-1 affinity mesh): no surviving mesh —
            # rebuild in place (the "replacement chip arrived" story)
            new_mesh = mesh
        engine = self.engine_factory(new_mesh)
        handle = self.handle_factory(replica_id, engine)
        self.router.replace_replica(handle)
        if was_draining:
            self.router.undrain(replica_id)
        rec.to_chips = engine.num_chips
        rec.phase("resharded", self._clock())
        self._g_chips.set(rec.to_chips, replica=str(replica_id))
        self._c_resizes.inc(replica=str(replica_id))
        emit_event("mesh_resized", replica=replica_id,
                   from_chips=rec.from_chips, to_chips=rec.to_chips,
                   cause=rec.kind, flights=len(rec.flights))
        rec.phase("rejoined", self._clock())
        del self.resizes[:-MAX_RESIZES]
        # the resize postmortem: one bundle per (replica, arc) embedding
        # elastic.json with this record (no-op unless armed w/ dump dir)
        flight_recorder.auto_dump(
            f"mesh_resized_r{replica_id}_{next(_ARC_SEQ)}")

    # -- views ---------------------------------------------------------------

    def _snapshot_flights(self, replica_id: int) -> List[FlightSnapshot]:
        out: List[FlightSnapshot] = []
        # same-package access to the router's live-request table (the
        # checkpoint must see requests BEFORE ejection tears them down)
        for req in self.router._requests.values():
            if (req.replica_id != replica_id or req.handle is None
                    or req.done):
                continue
            eng = self.router.replicas[replica_id].engine
            erid = req.handle.engine_rid
            pages = 0
            if erid is not None:
                pages = len(eng.mgr._tables.get(erid, ()))
            streamed = list(req.stream.tokens)
            samp = None
            if req.sampler is not None:
                samp = {"temperature": req.sampler.temperature,
                        "top_k": req.sampler.top_k,
                        "top_p": req.sampler.top_p,
                        "seed": req.sampler.seed}
            out.append(FlightSnapshot(
                router_rid=req.rid, trace_id=req.trace_id,
                prompt=[int(t) for t in req.prompt],
                streamed=streamed,
                pages=pages, engine_rid=erid,
                sampler=samp,
                grammar_prefix=(list(streamed)
                                if req.grammar is not None else None)))
        return out

    def timeline_snapshot(self) -> Dict[str, Any]:
        """The resize state machine's full log (``elastic.json`` in
        every flight bundle; mount on a DiagServer via
        ``srv.register("elastic", ctl.timeline_snapshot)`` if it has a
        provider registry, or read it off the bundle)."""
        return {
            "steps": self._steps,
            "chips": {str(rid): r.engine.num_chips
                      for rid, r in sorted(self.router.replicas.items())},
            "draining": sorted(self._graceful),
            "resizes": [rec.as_dict() for rec in self.resizes],
        }
