"""``paddle_tpu.autograd`` — public autograd surface.

Parity with python/paddle/autograd/ of the reference (backward, grad, PyLayer
— SURVEY.md §2.1 eager autograd row).
"""

from ..core.autograd import backward, grad, no_grad, enable_grad, set_grad_enabled  # noqa: F401
from ..core.dispatch import apply as _apply
from ..core.tensor import Tensor


def jacobian(ys, xs, batch_axis=None):
    """Dense jacobian of computed tensor(s) ``ys`` w.r.t. leaf tensor(s)
    ``xs`` (reference paddle.autograd.jacobian, python/paddle/autograd/
    autograd.py:§0). Tape-based: one seeded backward pass per ys element
    (per non-batch element with ``batch_axis=0``, the reference's
    batch-diagonal assumption). Returns the materialized Tensor (the
    reference's lazy Jacobian object materializes on first index; jax
    arrays are cheap to slice, so laziness buys nothing here).

    Shapes: ys (M…), xs (N…) -> (M_flat, N_flat); with batch_axis=0,
    ys (B, M…), xs (B, N…) -> (B, M_flat, N_flat).
    For a purely functional route (composes to any order, jittable), use
    paddle.incubate.autograd.Jacobian(func, xs).
    """
    import jax.numpy as jnp
    import numpy as np

    if batch_axis not in (None, 0):
        raise ValueError("batch_axis must be None or 0")
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    if isinstance(ys, (list, tuple)):
        return [jacobian(y, xs, batch_axis=batch_axis) for y in ys]

    y_shape = tuple(ys.shape)
    if batch_axis == 0:
        b = y_shape[0]
        m = int(np.prod(y_shape[1:], dtype=np.int64)) if len(y_shape) > 1 else 1
    else:
        m = int(np.prod(y_shape, dtype=np.int64)) if y_shape else 1

    rows = []  # m entries, each a list over xs of (…N) or (B, …N) grads
    for j in range(m):
        if batch_axis == 0:
            seed = jnp.zeros((b, m), ys.dtype).at[:, j].set(1).reshape(y_shape)
        else:
            seed = jnp.zeros((m,), ys.dtype).at[j].set(1).reshape(y_shape)
        gs = grad([ys], xs_list, grad_outputs=[Tensor(seed)],
                  retain_graph=True, allow_unused=True)
        rows.append([None if g is None else g._value for g in gs])

    outs = []
    for i, x in enumerate(xs_list):
        x_shape = tuple(x.shape)
        if batch_axis == 0:
            n = int(np.prod(x_shape[1:], dtype=np.int64)) if len(x_shape) > 1 else 1
            cols = [jnp.zeros(x_shape, ys.dtype).reshape(b, n)
                    if r[i] is None else r[i].reshape(b, n) for r in rows]
            outs.append(Tensor(jnp.stack(cols, axis=1)))   # (B, M, N)
        else:
            n = int(np.prod(x_shape, dtype=np.int64)) if x_shape else 1
            cols = [jnp.zeros((n,), ys.dtype) if r[i] is None
                    else r[i].reshape(n) for r in rows]
            outs.append(Tensor(jnp.stack(cols, axis=0)))   # (M, N)
    if isinstance(xs, (list, tuple)):
        return outs
    return outs[0]


def hessian(ys, xs, batch_axis=None):
    """Reference paddle.autograd.hessian. The tape records first-order
    vjps only (grad-of-grad would need the backward pass re-recorded);
    the exact equivalent here is the functional transform — point users
    at it rather than silently approximating."""
    raise NotImplementedError(
        "tape-based hessian needs double-grad, which the vjp tape does "
        "not record; use paddle.incubate.autograd.Hessian(func, xs) "
        "(jax.hessian underneath — exact, jittable, composes to any "
        "order)")


class PyLayerContext:
    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayer:
    """Custom differentiable op, parity with paddle.autograd.PyLayer.

    Subclasses define ``forward(ctx, *args)`` and ``backward(ctx, *grads)``
    operating on Tensors. Implemented over jax.custom_vjp-free tape nodes:
    the backward is recorded directly as a GradNode.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core import autograd as ag
        import jax.numpy as jnp

        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        outs_t = (outs,) if single else tuple(outs)

        needs_grad = ag.is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)
        if not needs_grad:
            return outs if single else list(outs_t)

        import jax
        avals = [jax.ShapeDtypeStruct(tuple(o.shape), o.dtype) for o in outs_t]

        def vjp_fn(cots):
            gs = cls.backward(ctx, *[Tensor(c) for c in cots])
            gs = (gs,) if isinstance(gs, Tensor) else tuple(gs)
            out = []
            gi = 0
            for a in args:
                if isinstance(a, Tensor):
                    g = gs[gi] if gi < len(gs) else None
                    gi += 1
                    out.append(None if g is None else g._value)
            return tuple(out)

        node = ag.GradNode(vjp_fn, tensor_inputs, avals, name=cls.__name__)
        wrapped = tuple(
            Tensor(o._value, stop_gradient=False, _grad_node=node, _out_index=i)
            for i, o in enumerate(outs_t))
        return wrapped[0] if single else list(wrapped)
