"""Profile-guided fusion pass: megakernel-ize profiled hot chains.

ROADMAP item 1, closing the loop the observability plane opened:
``DispatchChainProfiler`` (observability/profiling.py) exports a ranked
producer→consumer hot-chain artifact (``paddle_tpu.hot_chains``) whose
ops resolve to ``ProjectIndex`` symbols — and this module is the
consumer. :class:`FusionPass` reads the artifact, maps ranked chains to
declared **fusable regions**, and rewrites them into single jitted
megaregions (PAPERS.md: MPK "Mega-Kernelizing Tensor Programs", Neptune
operator fusion): the unified ragged step's decode tail on the serving
side, and the grad-transform → optimizer-update chain on the training
side.

Admission discipline (the hard gates, enforced by
``benchmarks/bench_fusion.py`` + ``tests/test_fusion.py``):

* **byte-identical** outputs fused vs. unfused — the decode tail keeps
  the exact compute graph of the unfused program (only host plumbing
  and epilogue placement change), and the optimizer megaregion replays
  the optimizer's own ``_update``/grad-clip code through the
  **eager-granularity stager** below, so fusing never changes a single
  bit of training state;
* **recompile-count-neutral** — fused programs have shape-invariant
  signatures like their unfused twins (the O(1)-recompile invariant
  from the unified-step PR);
* **measured ABBA win** recorded in BASELINE.md before a fusion ships
  enabled.

Degradation contract: a stale artifact (symbols renamed/moved since the
capture, or an incompatible schema) produces structured
``fusion_skipped`` events — one deduped event per chain per process —
and ``paddle_fusion_skipped_total{reason}`` counts, never an exception.

Eager-granularity staging (the bit-exactness mechanism)
-------------------------------------------------------

Fusing an eager op chain into one XLA program normally changes numerics:
inside a fused loop LLVM contracts ``a*b + c`` into an FMA, and the XLA
algebraic simplifier rewrites chained divisions — bit drift the eager
per-op execution never sees. :func:`stage_eager` re-emits a traced
function's jaxpr with a **contraction fence** after every floating-point
equation: ``min(x, lim)`` where ``lim`` is a *runtime* input valued
``+inf`` (a constant bound would be folded away). Every intermediate is
forced to its eagerly-rounded value, NaN/±inf/-0.0 pass through
untouched, and the megaregion stays one dispatch — the win is the
eliminated per-op host overhead, which is exactly what the profiler's
hot chains measure.

Layering: this module consumes *symbols and injected callables*, never
the serving/inference stack — tpu-lint's ``layer-deps`` STRICT contract
bans those imports at any scope. Region installation is duck-typed
(``engine.enable_fused_tail()``), and the decode-tail program builders
receive the model step function as an argument.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability.events import emit_event
from ..observability.profiling import (PROFILE_VERSION, chain_armed,
                                       dispatch_sites, note_chain)
from ..observability.registry import get_registry
from ..observability.runtime import recompiles

try:  # jax >= 0.4.16 keeps the stable alias in jax.extend
    from jax.extend.core import Literal as _JaxprLiteral
except ImportError:  # pragma: no cover - older jax
    from jax.core import Literal as _JaxprLiteral

#: the artifact this pass consumes (DispatchChainProfiler.export)
ARTIFACT_KIND = "paddle_tpu.hot_chains"

_reg = get_registry()
_admitted_total = _reg.counter(
    "paddle_fusion_admitted_total",
    "hot chains admitted and installed as fused megaregions, by region",
    labels=("region",))
_skipped_total = _reg.counter(
    "paddle_fusion_skipped_total",
    "hot chains the fusion pass skipped (stale artifact, schema "
    "mismatch, no declared region), by reason",
    labels=("reason",))
_active = _reg.gauge(
    "paddle_fusion_active",
    "1 while a fused megaregion is installed for the region",
    labels=("region",))

#: (chain ops tuple, reason) pairs already reported — the skip event is
#: emitted once per chain per process, the counter counts every skip
_skip_noted: set = set()

#: region name -> weakly-referenced installed targets; the active gauge
#: reflects whether any install target is still ALIVE, re-evaluated on
#: every plan()/apply() (a dropped fused engine must not report an
#: active megaregion forever — same liveness discipline as the memory
#: ledger's pool table)
_installed_targets: Dict[str, Any] = {}


def _refresh_active_gauges() -> None:
    for region, refs in _installed_targets.items():
        alive = [r for r in refs if r() is not None]
        _installed_targets[region] = alive
        _active.set(1.0 if alive else 0.0, region=region)


def _note_install(region: str, target: Any) -> None:
    import weakref
    try:
        ref = weakref.ref(target)
    except TypeError:               # unweakrefable target: pin forever
        ref = (lambda t=target: t)
    _installed_targets.setdefault(region, []).append(ref)


# ---------------------------------------------------------------------------
# Fusable regions
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class FusionRegion:
    """A declared fusable region: a named rewrite this tree knows how to
    install, matched against hot chains by op signature. ``signatures``
    are contiguous op subsequences as they appear in the artifact;
    ``target`` names the keyword :meth:`FusionPlan.apply` installs on."""

    name: str
    signatures: Tuple[Tuple[str, ...], ...]
    target: str                     # "engine" | "optimizer"
    doc: str = ""

    def match(self, ops: Sequence[str]) -> Optional[Tuple[str, ...]]:
        """The first signature appearing contiguously in ``ops``."""
        ops = tuple(ops)
        for sig in self.signatures:
            n = len(sig)
            if any(ops[i:i + n] == sig
                   for i in range(len(ops) - n + 1)):
                return sig
        return None


#: built-in regions (a test/bench may register more via REGIONS)
REGIONS: Dict[str, FusionRegion] = {
    "decode_tail": FusionRegion(
        name="decode_tail",
        signatures=(("cbe.unified_step", "cbe.decode_tail"),
                    ("cbe.plan_step", "cbe.unified_step"),
                    ("cbe.spec_step", "cbe.decode_tail")),
        target="engine",
        doc="unified ragged step's decode tail: packed plan upload, "
            "fused greedy/verify epilogue, vectorized steady-state "
            "planning (ContinuousBatchingEngine.enable_fused_tail)"),
    "optimizer_chain": FusionRegion(
        name="optimizer_chain",
        signatures=(("grad_clip", "optimizer_update"),
                    ("optimizer_update", "optimizer_update"),
                    ("optimizer_update",)),
        target="optimizer",
        doc="eager grad transform -> per-param optimizer update chain "
            "fused into ONE bit-exact jitted megaregion "
            "(FusedOptimizerStep)"),
    "sampling_epilogue": FusionRegion(
        name="sampling_epilogue",
        signatures=(("cbe.unified_step", "cbe.sample_epilogue"),
                    ("cbe.sample_epilogue", "cbe.decode_tail"),
                    ("cbe.spec_step", "cbe.sample_epilogue")),
        target="engine",
        doc="the distribution-faithful sampling epilogue (grammar mask "
            "-> temperature/top-k/top-p -> counter-keyed categorical / "
            "rejection-sampling verify) fused into the same decode-tail "
            "program as the ragged step — mixed greedy/sampled/"
            "constrained rows in ONE dispatch "
            "(ContinuousBatchingEngine.enable_fused_tail)"),
}


@dataclass
class FusionCandidate:
    region: FusionRegion
    ops: Tuple[str, ...]
    matched: Tuple[str, ...]
    count: int = 0
    est_us: float = 0.0


@dataclass
class FusionPlan:
    """The pass output: chains mapped to installable regions plus the
    structured skips. ``apply`` installs each candidate on the matching
    duck-typed target and reports what it did."""

    candidates: List[FusionCandidate] = field(default_factory=list)
    skipped: List[Dict[str, Any]] = field(default_factory=list)

    def candidate(self, region_name: str) -> Optional[FusionCandidate]:
        for c in self.candidates:
            if c.region.name == region_name:
                return c
        return None

    def apply(self, engine=None, optimizer=None) -> Dict[str, Any]:
        """Install every planned region whose target was passed.
        Returns ``{region name: installed object}``; regions whose
        target is absent (or lacks the install surface) are skipped
        with reason ``target-unsupported`` — never an exception."""
        installed: Dict[str, Any] = {}
        for cand in self.candidates:
            name = cand.region.name
            if name in installed:
                continue
            target = {"engine": engine,
                      "optimizer": optimizer}.get(cand.region.target)
            if target is None:
                continue
            # idempotence: re-applying over an already-installed region
            # must not re-count the admission or re-emit the event
            if cand.region.target == "engine":
                already = bool(getattr(target, "_fused_tail", False))
            else:
                already = isinstance(getattr(target, "_fused_step", None),
                                     FusedOptimizerStep)
            try:
                if cand.region.target == "engine":
                    target.enable_fused_tail()
                    installed[name] = target
                else:
                    installed[name] = install_optimizer_fusion(target)
            except Exception as exc:
                # the degradation contract covers installation too: a
                # target without the surface (AttributeError) or one
                # that rejects it (e.g. a non-unified engine's
                # ValueError) becomes a structured skip, never a raise
                _note_skip(cand.ops, "target-unsupported", region=name,
                           error=f"{type(exc).__name__}: {exc}")
                continue
            _note_install(name, installed[name] if
                          cand.region.target == "optimizer" else target)
            if already:
                continue
            _admitted_total.inc(region=name)
            emit_event("fusion_applied", region=name,
                       chain="->".join(cand.ops),
                       est_us=cand.est_us, count=cand.count)
        _refresh_active_gauges()
        return installed


def _note_skip(ops: Sequence[str], reason: str, **extra) -> None:
    """Count every skip; emit the structured event once per (chain,
    reason) per process so a pass re-run cannot flood the event log."""
    _skipped_total.inc(reason=reason)
    key = (tuple(ops), reason)
    if key in _skip_noted:
        return
    _skip_noted.add(key)
    emit_event("fusion_skipped", chain="->".join(ops), reason=reason,
               **extra)


class FusionPass:
    """Maps a ``paddle_tpu.hot_chains`` artifact to installable fused
    regions. ``resolver`` (op name -> current symbol) defaults to the
    analysis ProjectIndex view (:func:`profiling.dispatch_sites`); the
    pass trusts op names only as far as they still resolve in the
    CURRENT tree, so a stale artifact degrades to structured skips."""

    def __init__(self, regions: Optional[Dict[str, FusionRegion]] = None,
                 resolver: Optional[Callable[[], Dict[str, str]]] = None):
        self.regions = dict(regions if regions is not None else REGIONS)
        self._resolver = resolver or dispatch_sites

    # -- artifact intake ----------------------------------------------------

    @staticmethod
    def load(path: str) -> Dict[str, Any]:
        with open(path) as f:
            return json.load(f)

    def plan(self, artifact: Any) -> FusionPlan:
        """Rank-order walk over the artifact's chains. Never raises on
        artifact problems: schema mismatches and unresolvable symbols
        become ``fusion_skipped`` entries."""
        _refresh_active_gauges()
        plan = FusionPlan()
        if not isinstance(artifact, dict) \
                or artifact.get("kind") != ARTIFACT_KIND \
                or artifact.get("schema_version",
                                artifact.get("version")) != PROFILE_VERSION:
            got = None
            if isinstance(artifact, dict):
                got = (artifact.get("kind"),
                       artifact.get("schema_version",
                                    artifact.get("version")))
            _note_skip(("<artifact>",), "schema-mismatch", got=repr(got),
                       want=f"{ARTIFACT_KIND} v{PROFILE_VERSION}")
            plan.skipped.append({"chain": ("<artifact>",),
                                 "reason": "schema-mismatch"})
            return plan
        sites = self._resolver()
        claimed = artifact.get("symbols") or {}
        for chain in artifact.get("chains", []):
            ops = tuple(chain.get("ops", ()))
            if not ops:
                continue
            # staleness first: an op the ARTIFACT resolved to a symbol
            # that no longer resolves in the current ProjectIndex means
            # the capture predates a refactor — never rewrite against it
            stale = [op for op in ops if claimed.get(op)
                     and op not in sites]
            if stale:
                _note_skip(ops, "symbol-missing",
                           missing=",".join(stale))
                plan.skipped.append({"chain": ops,
                                     "reason": "symbol-missing",
                                     "missing": stale})
                continue
            matched_region = None
            matched_sig = None
            for region in self.regions.values():
                sig = region.match(ops)
                if sig is not None:
                    matched_region, matched_sig = region, sig
                    break
            if matched_region is None:
                _note_skip(ops, "no-region")
                plan.skipped.append({"chain": ops, "reason": "no-region"})
                continue
            missing = [op for op in matched_sig if op not in sites]
            if missing:
                # the region's own taps are gone from the tree (the
                # artifact predates a rename of the fusable code)
                _note_skip(ops, "symbol-missing", region=matched_region.name,
                           missing=",".join(missing))
                plan.skipped.append({"chain": ops,
                                     "reason": "symbol-missing",
                                     "missing": missing})
                continue
            plan.candidates.append(FusionCandidate(
                region=matched_region, ops=ops, matched=matched_sig,
                count=int(chain.get("count", 0)),
                est_us=float(chain.get("est_us", 0.0))))
        return plan


# ---------------------------------------------------------------------------
# Eager-granularity staging (bit-exact megaregions)
# ---------------------------------------------------------------------------
class _Stager:
    """Records host-scalar materialisations during trace and replays
    their f64 evaluations per call (see :class:`HostScalar`)."""

    def __init__(self):
        self.slots: List[Callable[[Dict[str, float]], float]] = []
        self.traced = None          # traced scalar-vector during trace
        self.env: Dict[str, float] = {}

    def leaf(self, name: str) -> "HostScalar":
        return HostScalar(self, lambda env, n=name: env[n])

    def slot(self, ev):
        j = len(self.slots)
        self.slots.append(ev)
        return self.traced[j]

    def values(self) -> np.ndarray:
        return np.asarray([np.float32(ev(self.env)) for ev in self.slots],
                          np.float32)


class HostScalar:
    """A lazily-evaluated host (float64) scalar expression.

    Passed where eager code passes a Python float (``lr``, ``step``):
    scalar-scalar arithmetic stays on the host at full f64 precision
    exactly like the eager interpreter, and the moment an expression
    meets a traced array it materialises as one f32 input slot — the
    same single rounding the eager op's weak-typed scalar takes. The
    traced program therefore never bakes a step-dependent constant
    (no per-step recompiles) and never computes scalar math in f32
    (no bit drift vs. eager)."""

    __array_priority__ = 200        # win dunder dispatch vs np/jnp arrays

    def __init__(self, stager: _Stager, ev):
        self._st = stager
        self._ev = ev

    # -- composition --------------------------------------------------------
    def _lift(self, other):
        if isinstance(other, HostScalar):
            return other._ev
        if isinstance(other, (int, float)) and not isinstance(other, bool):
            return lambda env, v=other: v
        return None

    def _binop(self, other, op, rev: bool):
        oe = self._lift(other)
        if oe is None:              # traced-array operand: materialise
            t = self._st.slot(self._ev)
            return op(other, t) if rev else op(t, other)
        me = self._ev
        if rev:
            return HostScalar(self._st, lambda env: op(oe(env), me(env)))
        return HostScalar(self._st, lambda env: op(me(env), oe(env)))

    def __mul__(self, o): return self._binop(o, lambda a, b: a * b, False)
    def __rmul__(self, o): return self._binop(o, lambda a, b: a * b, True)
    def __add__(self, o): return self._binop(o, lambda a, b: a + b, False)
    def __radd__(self, o): return self._binop(o, lambda a, b: a + b, True)
    def __sub__(self, o): return self._binop(o, lambda a, b: a - b, False)
    def __rsub__(self, o): return self._binop(o, lambda a, b: a - b, True)
    def __truediv__(self, o):
        return self._binop(o, lambda a, b: a / b, False)
    def __rtruediv__(self, o):
        return self._binop(o, lambda a, b: a / b, True)
    def __pow__(self, o): return self._binop(o, lambda a, b: a ** b, False)
    def __rpow__(self, o): return self._binop(o, lambda a, b: a ** b, True)
    def __neg__(self):
        return HostScalar(self._st, lambda env: -self._ev(env))


def _eval_guarded(jaxpr, consts, lim, *args):
    """Re-emit a jaxpr with a contraction fence (``min(x, lim)``, lim a
    runtime +inf) after every floating-point equation output — each
    intermediate is pinned to its eagerly-rounded value, so XLA's
    cross-op FMA contraction and division re-association cannot change
    a bit (module docstring).

    Float *literals* are fenced too: under jit a Python-scalar operand
    becomes a compile-time constant that XLA rewrites (``x / c`` turns
    into ``x * (1/c)``), while the eager interpreter ships it as a
    runtime buffer and divides for real. Routing each float literal
    through the fence makes it runtime again — dtype-exact (the jaxpr
    already recorded the weak-type promotion), value-identical."""
    env: Dict[Any, Any] = {}

    def read(v):
        if isinstance(v, _JaxprLiteral):
            val = v.val
            aval = v.aval
            if (getattr(aval, "dtype", None) is not None
                    and jnp.issubdtype(aval.dtype, jnp.floating)):
                return jnp.minimum(jnp.asarray(val, aval.dtype),
                                   lim.astype(aval.dtype))
            return val
        return env[v]

    for v, c in zip(jaxpr.constvars, consts):
        env[v] = c
    for v, a in zip(jaxpr.invars, args):
        env[v] = a
    for eqn in jaxpr.eqns:
        invals = [read(v) for v in eqn.invars]
        outs = eqn.primitive.bind(*invals, **eqn.params)
        if not eqn.primitive.multiple_results:
            outs = [outs]
        for v, o in zip(eqn.outvars, outs):
            if hasattr(o, "dtype") and jnp.issubdtype(o.dtype,
                                                      jnp.floating):
                # dtype-matched fence: a bare minimum(bf16, f32) would
                # silently promote the intermediate
                o = jnp.minimum(o, lim.astype(o.dtype))
            env[v] = o
    return [read(v) for v in jaxpr.outvars]


def stage_eager(fn: Callable, *example_args):
    """Trace ``fn`` once over ``example_args`` (shape/dtype only) and
    return ``staged(lim, *args)`` evaluating it with per-op contraction
    fences — the callable a megaregion jits to stay bit-identical to
    the eager chain it replaces."""
    closed = jax.make_jaxpr(fn)(*example_args)

    def staged(lim, *args):
        flat, treedef = jax.tree_util.tree_flatten(args)
        del treedef  # the jaxpr's invars ARE the flat order
        outs = _eval_guarded(closed.jaxpr, closed.consts, lim, *flat)
        return outs
    return staged, closed


# ---------------------------------------------------------------------------
# Region: optimizer_chain — the fused grad-transform/update megaregion
# ---------------------------------------------------------------------------
class _ClipParam:
    """need_clip stand-in handed to grad-clip transforms under trace
    (same device as jit.TrainStep's compiled path)."""

    __slots__ = ("need_clip",)

    def __init__(self, nc: bool):
        self.need_clip = bool(nc)


class FusedOptimizerStep:
    """ONE jitted dispatch for the whole eager optimizer chain: grad
    transform (the optimizer's own ``_grad_clip``) + every parameter's
    ``_update`` + host metric taps, replayed through the
    eager-granularity stager so committed params/accumulators are
    byte-identical to ``Optimizer.step()`` — verified per optimizer
    family by ``tests/test_fusion.py`` and gated by
    ``benchmarks/bench_fusion.py``.

    Installed via :func:`install_optimizer_fusion` (the pass's
    ``optimizer_chain`` region): ``optimizer.step()`` then delegates
    here. The compiled program's signature depends only on parameter
    shapes/dtypes, state slots and static per-param attributes — the
    step counter and LR enter as host-staged scalar inputs, so a
    training loop never recompiles it. Buffers are NOT donated: the
    eager step leaves previous arrays valid for outside holders
    (checkpoint refs), and the fused step keeps that contract."""

    def __init__(self, optimizer):
        self._opt = optimizer
        self._compiled: Dict[Tuple, Tuple] = {}
        self.steps_fused = 0

    # -- build (one program per parameter-set signature) --------------------

    def _hyper_signature(self) -> Tuple:
        """Every scalar hyperparameter the traced program bakes in as a
        constant (betas, eps, momentum, weight decay, the grad-clip
        bound, ...). Mutating one after install MUST rebuild — eager
        ``step()`` honours the new value immediately, and the fused
        step promises bit-identity with eager. ``_step_count`` and
        ``_learning_rate`` are excluded: both enter as host-staged
        runtime inputs, never as constants."""
        opt = self._opt
        skip = {"_step_count", "_learning_rate"}

        def scalars(obj):
            return tuple(sorted(
                (k, bool(v) if isinstance(v, bool) else float(v))
                for k, v in vars(obj).items()
                if k not in skip and isinstance(v, (int, float, bool))))

        clip = opt._grad_clip
        csig = (() if clip is None
                else (type(clip).__name__,) + scalars(clip))
        return scalars(opt) + (csig,)

    def _signature(self, params) -> Tuple:
        opt = self._opt
        sig = [self._hyper_signature()]
        for p in params:
            st = opt._state_of(p)
            sig.append((
                tuple(p._value.shape), str(p._value.dtype),
                tuple(p._grad_value.shape), str(p._grad_value.dtype),
                tuple(sorted((k, tuple(v.shape), str(v.dtype))
                             for k, v in st.items())),
                bool(opt._decay_enabled(p)),
                float(p.optimize_attr.get("learning_rate", 1.0)),
                bool(getattr(p, "need_clip", True)),
            ))
        return tuple(sig)

    def _build(self, params):
        opt = self._opt
        stager = _Stager()
        wd_on = [opt._decay_enabled(p) for p in params]
        mults = [p.optimize_attr.get("learning_rate", 1.0) for p in params]
        clip_objs = [_ClipParam(getattr(p, "need_clip", True))
                     for p in params]

        def whole(scal, pvals, gvals, svals):
            stager.traced = scal
            lr = stager.leaf("lr")
            step = stager.leaf("step")
            grads = list(gvals)
            if opt._grad_clip is not None:
                pairs = opt._grad_clip(list(zip(clip_objs, grads)))
                grads = [g for _, g in pairs]
            saved_wd = opt._weight_decay
            new_p, new_s = [], []
            try:
                for i in range(len(pvals)):
                    opt._weight_decay = saved_wd if wd_on[i] else 0.0
                    nv, ns = opt._update(pvals[i], grads[i],
                                         dict(svals[i]), lr * mults[i],
                                         step)
                    new_p.append(nv)
                    new_s.append(ns)
            finally:
                opt._weight_decay = saved_wd
            return new_p, new_s

        pv = [p._value for p in params]
        gv = [p._grad_value for p in params]
        sv = [dict(opt._state_of(p)) for p in params]
        # generous fixed slot vector: sized from a dry trace would need
        # two passes; 4 slots/param + 8 covers every shipped optimizer
        scal_dim = 4 * len(params) + 8
        dummy = jnp.zeros((scal_dim,), jnp.float32)
        staged, _ = stage_eager(whole, dummy, pv, gv, sv)
        if len(stager.slots) > scal_dim:     # pragma: no cover - guard
            raise RuntimeError(
                f"optimizer staged {len(stager.slots)} host scalars > "
                f"slot vector {scal_dim}")
        out_tree = jax.tree_util.tree_structure((pv, sv))
        jitted = jax.jit(staged)
        return jitted, stager, scal_dim, out_tree

    # -- the service surface (Optimizer.step delegates here) ----------------

    def step(self) -> None:
        opt = self._opt
        armed = chain_armed[0]
        t0 = time.perf_counter_ns() if armed else 0
        opt._step_count += 1
        params = [p for p in opt._parameter_list
                  if p._grad_value is not None and p.trainable]
        if not params:
            return
        key = self._signature(params)
        entry = self._compiled.get(key)
        if entry is None:
            recompiles.record_miss("fusion.optimizer_chain",
                                   ("params", len(params)))
            entry = self._compiled[key] = self._build(params)
        jitted, stager, scal_dim, out_tree = entry
        stager.env = {"lr": opt.get_lr(), "step": opt._step_count}
        scal = np.zeros((scal_dim,), np.float32)
        vals = stager.values()
        scal[:len(vals)] = vals
        pv = [p._value for p in params]
        gv = [p._grad_value for p in params]
        sv = [dict(opt._state_of(p)) for p in params]
        outs = jitted(jnp.float32(np.inf), jnp.asarray(scal), pv, gv, sv)
        new_p, new_s = jax.tree_util.tree_unflatten(out_tree, outs)
        for p, nv, ns in zip(params, new_p, new_s):
            p._value = nv
            opt._accumulators[id(p)] = ns
        self.steps_fused += 1
        if armed:
            note_chain(op_name="fused_optimizer_step",
                       dur_ns=time.perf_counter_ns() - t0)


def install_optimizer_fusion(optimizer) -> FusedOptimizerStep:
    """Install the ``optimizer_chain`` megaregion: ``optimizer.step()``
    delegates to the fused step from now on (idempotent)."""
    fused = getattr(optimizer, "_fused_step", None)
    if isinstance(fused, FusedOptimizerStep):
        return fused
    fused = FusedOptimizerStep(optimizer)
    optimizer._fused_step = fused
    return fused


# ---------------------------------------------------------------------------
# Region: decode_tail — fused unified/spec step program builders
# ---------------------------------------------------------------------------
def pack_plan(ids, use_carry, token_row, positions, kv_lens, last_idx,
              sample_mask):
    """Pack one unified-step plan into two int32 uploads: the token-axis
    group (4, K, step_tokens) and the row-axis group (3, K, rows) — two
    host→device transfers per step instead of seven."""
    plan_tt = np.stack([ids, use_carry.astype(np.int32), token_row,
                        positions]).astype(np.int32)
    plan_tr = np.stack([kv_lens, last_idx,
                        sample_mask.astype(np.int32)]).astype(np.int32)
    return plan_tt, plan_tr


def build_fused_unified_step(model_step: Callable, sample_fn: Callable,
                             num_rows: int):
    """The fused decode-tail twin of the engine's unified ragged step:
    same compute graph (``model_step`` per micro-round, the sampling
    epilogue, the carry select) — byte-identical tokens by construction
    — fed from the packed plan of :func:`pack_plan`.

    ``model_step(params, ids, token_row, positions, kv_lens, last_idx,
    k_pages, v_pages, bt, gstate, gtable) -> (logits, k_pages,
    v_pages)`` (the grammar state rides into the model's logits
    epilogue hook so masking happens before the sampler);
    ``sample_fn(logits, pos_next, samp, gstate, gtable) ->
    ((rows,) int32 tokens, (rows,) int32 grammar states)`` — the
    counter-based epilogue needs no key input, so no PRNG state
    threads through the scan carry.
    """

    def run(params, plan_tt, plan_tr, tok, gstate, samp, gtable,
            k_pages, v_pages, bt):
        ids = plan_tt[0]
        use_carry = plan_tt[1].astype(bool)
        token_row = plan_tt[2]
        positions = plan_tt[3]
        kv_lens = plan_tr[0]
        last_idx = plan_tr[1]
        sample_mask = plan_tr[2].astype(bool)

        def micro(carry, xs):
            tok, gst, kp, vp = carry
            ids_k, uc_k, tr_k, pos_k, kvl_k, li_k, sm_k = xs
            row_c = jnp.clip(tr_k, 0, num_rows - 1)
            ids_eff = jnp.where(uc_k, jnp.take(tok, row_c), ids_k)
            logits, kp, vp = model_step(params, ids_eff, tr_k, pos_k,
                                        kvl_k, li_k, kp, vp, bt,
                                        gst, gtable)
            nxt, ngst = sample_fn(logits, kvl_k, samp, gst, gtable)
            emit = tok
            tok = jnp.where(sm_k, nxt, tok)
            gst = jnp.where(sm_k, ngst, gst)
            return (tok, gst, kp, vp), emit

        (tok, gstate, k_pages, v_pages), toks = jax.lax.scan(
            micro, (tok, gstate, k_pages, v_pages),
            (ids, use_carry, token_row, positions, kv_lens, last_idx,
             sample_mask))
        return toks, tok, gstate, k_pages, v_pages

    return jax.jit(run, donate_argnums=(7, 8))


def build_fused_spec_step(model_step: Callable, spec_sample_fn: Callable,
                          spec_k: int, num_rows: int):
    """The fused decode-tail twin of the speculative step: the same
    single ragged dispatch plus the **verify epilogue in-program** — a
    vectorized accepted-prefix count (greedy rows) / rejection-sampling
    accept-and-residual-resample (sampled rows) per row replaces the
    host's per-token compare loop. Greedy candidate tokens (and
    therefore every greedy committed token) stay byte-identical to the
    unfused program.

    ``spec_sample_fn(logits (rows, k+1, V), drafts, draft_len,
    pos_base, samp, gstate, gtable) -> (toks (rows, k+1), accepted
    (rows,), gstate')``. ``sampled (rows,) bool`` gates which rows
    really committed a token this round — only those advance their
    grammar state (a mid-prefill constrained row must not advance on a
    garbage candidate).
    """
    k1 = spec_k + 1

    def run(params, ids, token_row, positions, kv_lens, cand_idx,
            drafts, draft_len, sampled, gstate, samp, gtable,
            k_pages, v_pages, bt):
        logits, kp, vp = model_step(params, ids, token_row, positions,
                                    kv_lens, cand_idx, k_pages, v_pages,
                                    bt)
        lg = logits.reshape(num_rows, k1, -1)
        pos_base = jnp.take(positions,
                            cand_idx.reshape(num_rows, k1)[:, 0])
        toks, accepted, ngst = spec_sample_fn(lg, drafts, draft_len,
                                              pos_base, samp, gstate,
                                              gtable)
        gstate = jnp.where(sampled, ngst, gstate)
        return toks, accepted, gstate, kp, vp

    return jax.jit(run, donate_argnums=(12, 13))
