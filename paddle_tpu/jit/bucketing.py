"""Dynamic-shape bucketing: pad ragged inputs to a small set of bucket
shapes so jit caches stay warm.

This is the survey's named CINN-replacement policy for dynamic shapes
(SURVEY.md §2.5 CINN row): XLA compiles per concrete shape, so a stream of
ragged batches (variable sequence lengths, variable image sizes, ragged
detection counts) recompiles per step unless inputs are padded to buckets.
:class:`ShapeBucketer` rounds each dynamic dim up to the next bucket and
returns a validity mask; CompileGuard (jit/__init__.py) then sees at most
``len(buckets)`` signatures instead of one per shape.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from ..core.tensor import Tensor

__all__ = ["next_bucket", "pad_to_bucket", "ShapeBucketer"]


def next_bucket(n: int, buckets: Optional[Sequence[int]] = None,
                multiple: int = 64) -> int:
    """Smallest bucket >= n. With an explicit ``buckets`` list, pick from
    it (the last bucket caps — larger inputs raise); otherwise round up to
    ``multiple`` (TPU-friendly default 64: keeps padded dims lane-aligned
    for the MXU/VPU)."""
    if buckets:
        for b in sorted(buckets):
            if n <= b:
                return int(b)
        raise ValueError(
            f"size {n} exceeds the largest bucket {max(buckets)}; add a "
            "larger bucket or pre-truncate")
    return int(-(-n // multiple) * multiple)


def pad_to_bucket(x, axis: int = 0, buckets: Optional[Sequence[int]] = None,
                  multiple: int = 64, pad_value=0):
    """Pad ``x`` along ``axis`` up to the next bucket.

    Returns ``(padded, valid_len)`` — valid_len is the ORIGINAL extent, for
    masking downstream (losses, NMS, pooling).
    """
    arr = x._value if isinstance(x, Tensor) else np.asarray(x)
    n = arr.shape[axis]
    target = next_bucket(n, buckets, multiple)
    if target == n:
        return (x if isinstance(x, Tensor) else arr), n
    import jax.numpy as jnp
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, target - n)
    out = jnp.pad(arr, widths, constant_values=pad_value)
    return (Tensor(out) if isinstance(x, Tensor) else out), n


class ShapeBucketer:
    """Pads a batch of arrays to shared bucket shapes before a compiled
    call. Tracks how many distinct bucket signatures it has produced; a
    production loop can assert this stays small.

    Example (ragged detection eval)::

        bucketer = ShapeBucketer(axes={0: (64, 128, 256)})
        padded, valid = bucketer(boxes)      # (128, 4), valid == {0: 87}
        scores = compiled_fn(padded)[:valid[0]]
    """

    def __init__(self, axes: dict, multiple: int = 64, pad_value=0):
        #: axes: {axis: buckets tuple or None (round to ``multiple``)}
        self.axes = dict(axes)
        self.multiple = multiple
        self.pad_value = pad_value
        self.signatures: set = set()

    def __call__(self, x) -> Tuple[object, dict]:
        """Pad every configured axis; returns (padded, {axis: valid_len})."""
        valid = {}
        for axis, buckets in sorted(self.axes.items()):
            x, n = pad_to_bucket(x, axis=axis, buckets=buckets,
                                 multiple=self.multiple,
                                 pad_value=self.pad_value)
            valid[axis] = n
        shape = tuple(np.shape(x._value if isinstance(x, Tensor) else x))
        self.signatures.add(shape)
        return x, valid

    @property
    def num_signatures(self) -> int:
        return len(self.signatures)
