"""``paddle_tpu.jit`` — dy2static equivalent.

The reference compiles imperative code via AST transforms + SOT bytecode
tracing (python/paddle/jit/, SURVEY.md §2.5 dy2static row). Here jax.jit IS
the tracer: ``to_static`` lifts a Layer's parameters/buffers into traced
arguments and jit-compiles the forward; ``TrainStep`` compiles the full
forward+backward+optimizer update into ONE XLA program (the equivalent of the
reference's whole-graph executor path, with XLA doing the stream scheduling).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from ..core import autograd as _ag
from ..nn.layer import Layer
from ..optimizer.optimizer import Optimizer
from .. import random as _random
from .functional import bind, param_arrays, buffer_arrays, tree_unwrap, tree_wrap


class RecompileWarning(UserWarning):
    """A compiled function saw a new input signature and recompiled."""


class CompileGuard:
    """Input-signature guard for jit boundaries — the SOT-guard equivalent
    (reference: python/paddle/jit/sot/ bytecode guards, SURVEY.md §2.5
    dy2static row / §7 hard-part #3).

    jax.jit retraces silently on any shape/dtype/pytree change; this guard
    makes every such cache miss VISIBLE: ``recompile_count`` counts misses
    after the first compile and each miss emits a :class:`RecompileWarning`
    naming the signature drift, so a shape leak in a training loop cannot
    silently recompile per step.
    """

    def __init__(self, name: str):
        self.name = name
        self._sigs: set = set()
        self.recompile_count = 0

    @staticmethod
    def signature(*trees):
        import jax as _jax

        leaves, treedef = _jax.tree_util.tree_flatten(trees)
        return (treedef,) + tuple(
            (getattr(v, "shape", ()), str(getattr(v, "dtype", type(v).__name__)))
            for v in leaves)

    def check(self, *trees) -> bool:
        """Record the call signature; returns True when it misses the cache
        (first call does not count as a recompile)."""
        import warnings

        sig = self.signature(*trees)
        if sig in self._sigs:
            return False
        miss = bool(self._sigs)
        self._sigs.add(sig)
        # every cache miss (first compile included) lands in the global
        # trace-cache-miss counter + event log with the shape signature.
        # record_miss, not note: self._sigs already dedupes per instance,
        # and two same-named guards (e.g. two models' "forward") must each
        # count their own real recompiles
        from ..observability.runtime import recompiles
        recompiles.record_miss(f"jit.{self.name}", sig)
        if miss:
            self.recompile_count += 1
            warnings.warn(
                f"{self.name}: input signature changed (seen "
                f"{len(self._sigs)} distinct signatures) -> XLA recompile "
                f"#{self.recompile_count}. Pad/bucket inputs to stable "
                "shapes to avoid per-step compilation.",
                RecompileWarning, stacklevel=3)
        return miss


class StaticFunction:
    """jit-compiled forward (inference/eval) over an imperative fn/Layer.

    The wrapped fn passes through the dy2static AST rewrite first
    (jit/dy2static.py), so data-dependent Python ``if``/``while`` over
    Tensors lower to lax.cond / lax.while_loop instead of failing at trace
    time — the SOT-conversion analog. When tracing still fails
    (ConversionError or an untraceable predicate) and
    ``FLAGS_dy2static_fallback`` is on (default), the call falls back to
    the EAGER path with a warning and stays eager — the reference SOT's
    graceful-fallback behaviour; ``FLAGS_dy2static_fallback=0`` restores
    the strict raise.
    """

    def __init__(self, fn: Callable, layer: Optional[Layer] = None,
                 donate_params: bool = False):
        from .dy2static import convert_control_flow
        self._orig_fn = fn
        self._fn = convert_control_flow(fn)
        self._layer = layer
        self._jitted = None
        self._fallback = False
        self.guard = CompileGuard(getattr(fn, "__name__", "to_static"))

    def _build(self):
        layer = self._layer

        def pure(params, buffers, key, args, kwargs):
            with _random.traced_key_scope(key):
                wargs = tree_wrap(args)
                wkwargs = tree_wrap(kwargs)
                if layer is not None:
                    with bind(layer, params, buffers):
                        out = self._fn(*wargs, **wkwargs)
                else:
                    out = self._fn(*wargs, **wkwargs)
                return tree_unwrap(out)

        self._jitted = jax.jit(pure)

    def __call__(self, *args, **kwargs):
        if self._fallback:
            return self._orig_fn(*args, **kwargs)
        if self._jitted is None:
            self._build()
        params = param_arrays(self._layer) if self._layer else {}
        buffers = buffer_arrays(self._layer) if self._layer else {}
        key = _random.next_key()
        uargs, ukwargs = tree_unwrap(args), tree_unwrap(kwargs)
        self.guard.check(uargs, ukwargs)
        from .dy2static import ConversionError
        from ..core.tensor import TracedIterationError
        try:
            out = self._jitted(params, buffers, key, uargs, ukwargs)
        except (ConversionError, TracedIterationError,
                jax.errors.ConcretizationTypeError) as e:
            from ..flags import flag_value
            if not flag_value("dy2static_fallback"):
                raise
            import warnings
            warnings.warn(
                f"{self.guard.name}: tracing failed "
                f"({type(e).__name__}: {str(e).splitlines()[0]}); falling "
                "back to the EAGER path for this and future calls — the "
                "function will not be compiled "
                "(FLAGS_dy2static_fallback=0 restores the strict raise)",
                stacklevel=2)
            self._fallback = True
            return self._orig_fn(*args, **kwargs)
        return tree_wrap(out)

    @property
    def recompile_count(self) -> int:
        return self.guard.recompile_count


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              **kwargs):
    """Parity with paddle.jit.to_static (decorator or call form)."""

    def decorate(fn):
        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, layer=fn)
            fn.forward = sf
            return fn
        layer = getattr(fn, "__self__", None)
        if isinstance(layer, Layer):
            return StaticFunction(fn, layer=layer)
        return StaticFunction(fn, layer=None)

    if function is not None:
        return decorate(function)
    return decorate


class TrainStep:
    """One fully-compiled training step: forward + tape backward + clip +
    optimizer update + buffer (e.g. BN stats) update, as a single XLA program
    with donated parameter/optimizer buffers.

    Equivalent of the reference's static-graph hot loop (SURVEY.md §3.4), but
    derived automatically from imperative code.
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer: Optimizer,
                 in_shardings=None, donate: bool = True):
        self.model = model
        self.loss_fn = loss_fn  # (model, *batch) -> scalar Tensor
        self.optimizer = optimizer
        self._donate = donate
        self._jitted = None
        self.guard = CompileGuard(type(self).__name__)
        # materialise optimizer state for every trainable param now
        self._trainable = [
            (name, p) for name, p in model.named_parameters() if p.trainable
        ]
        for _, p in self._trainable:
            optimizer._state_of(p)

    # -- pytree helpers -----------------------------------------------------
    def _opt_state_tree(self):
        return {name: dict(self.optimizer._accumulators[id(p)])
                for name, p in self._trainable}

    def _write_back(self, params, opt_state, buffers):
        by_name = dict(self.model.named_parameters())
        for name, v in params.items():
            by_name[name]._value = v
        for name, p in self._trainable:
            self.optimizer._accumulators[id(p)] = dict(opt_state[name])
        buf_objs = {n: b for n, b in self.model.named_buffers() if b is not None}
        for name, v in buffers.items():
            if name in buf_objs:
                buf_objs[name]._value = v

    # -- build --------------------------------------------------------------
    def _build(self):
        donate = (0, 1, 2) if self._donate else ()
        self._jitted = jax.jit(self._make_step_fn(), donate_argnums=donate)

    def _make_step_fn(self):
        model = self.model
        opt = self.optimizer
        loss_fn = self.loss_fn
        trainable_names = [n for n, _ in self._trainable]
        lr_mults = {n: p.optimize_attr.get("learning_rate", 1.0)
                    for n, p in self._trainable}
        need_clip = {n: getattr(p, "need_clip", True) for n, p in self._trainable}
        # honour per-param decay exclusion (AdamW.apply_decay_param_fun,
        # Lamb.exclude_from_weight_decay_fn) in the compiled path too
        wd_on = {n: opt._decay_enabled(p) for n, p in self._trainable}

        def step(params, opt_state, buffers, batch, lr, step_i, key):
            with _random.traced_key_scope(key):
                with bind(model, params, buffers) as mutated_buffers:
                    for _, p in model.named_parameters():
                        p._grad_value = None
                    wbatch = tree_wrap(batch)
                    loss = loss_fn(model, *wbatch)
                    with _ag.enable_grad():
                        loss.backward()
                    pobjs = dict(model.named_parameters())
                    grads = {n: pobjs[n]._grad_value for n in trainable_names}
                # clip (outside bind: pure arrays now)
                if opt._grad_clip is not None:
                    class _P:  # lightweight stand-in carrying need_clip
                        __slots__ = ("need_clip",)
                        def __init__(self, nc):
                            self.need_clip = nc
                    pairs = [(_P(need_clip[n]), grads[n]) for n in trainable_names]
                    pairs = opt._grad_clip(pairs)
                    grads = {n: g for n, (_, g) in zip(trainable_names, pairs)}
                new_params = dict(params)
                new_state = {}
                saved_wd = opt._weight_decay
                for n in trainable_names:
                    g = grads[n]
                    if g is None:
                        new_state[n] = opt_state[n]
                        continue
                    opt._weight_decay = saved_wd if wd_on[n] else 0.0
                    nv, ns = opt._update(params[n], g, dict(opt_state[n]),
                                         lr * lr_mults[n], step_i)
                    new_params[n] = nv
                    new_state[n] = ns
                opt._weight_decay = saved_wd
                return tree_unwrap(loss), new_params, new_state, mutated_buffers

        return step

    def __call__(self, *batch):
        if self._jitted is None:
            self._build()
        self.guard.check(tree_unwrap(batch))
        opt = self.optimizer
        opt._step_count += 1
        params = param_arrays(self.model)
        opt_state = self._opt_state_tree()
        buffers = buffer_arrays(self.model)
        lr = opt.get_lr()
        key = _random.next_key()
        loss, new_params, new_state, new_buffers = self._jitted(
            params, opt_state, buffers, tree_unwrap(batch),
            jnp.asarray(lr, jnp.float32), jnp.asarray(opt._step_count, jnp.int32), key)
        self._write_back(new_params, new_state, new_buffers)
        return Tensor(loss)

    def multi_step(self, k: int):
        """Compile ``k`` optimizer steps into ONE dispatch.

        Returns a callable with the same batch signature as the step,
        except every batch array carries a leading ``k`` axis (one slice
        per inner step). One ``lax.scan`` with the (params, opt-state,
        buffers) carry donated — one host round-trip per k steps instead
        of per step. On the axon tunnel each dispatch costs ~11 ms of
        host plumbing; this lever measured 51.9→52.9% MFU on the 7B
        flagship, 45.8→50.5% on packed BERT, 36.2→39.1% on MoE
        (BASELINE.md, round 5).

        The LR is sampled once per dispatch (an LRScheduler advances k
        counts but the k inner steps share one value); the returned loss
        is the LAST inner step's. Each inner step draws its own PRNG key,
        so dropout masks differ per step as in the sequential loop.
        """
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        # one compiled runner per k: calling multi_step(k) in a loop must
        # not re-jit the largest program in the module every iteration
        cache = self.__dict__.setdefault("_multi_step_cache", {})
        if k in cache:
            return cache[k]
        inner = self._make_step_fn()

        def multi(params, opt_state, buffers, batch, lr, step_i, keys):
            leaves, treedef = jax.tree_util.tree_flatten(batch)

            def body(carry, inp):
                p, o, b, si = carry
                step_batch = jax.tree_util.tree_unflatten(
                    treedef, inp[:-1])
                loss, p, o, b = inner(p, o, b, step_batch, lr, si,
                                      inp[-1])
                return (p, o, b, si + 1), loss

            (p, o, b, _), losses = jax.lax.scan(
                body, (params, opt_state, buffers, step_i),
                tuple(leaves) + (keys,))
            return losses[-1], p, o, b

        donate = (0, 1, 2) if self._donate else ()
        multi_jit = jax.jit(multi, donate_argnums=donate)
        opt = self.optimizer
        guard = CompileGuard(f"TrainStep.multi_step[{k}]")

        def run(*batch):
            vals = tree_unwrap(batch)
            for leaf in jax.tree_util.tree_leaves(vals):
                if jnp.ndim(leaf) == 0 or jnp.shape(leaf)[0] != k:
                    raise ValueError(
                        f"multi_step({k}) batch arrays need a leading "
                        f"{k} axis; got shape {jnp.shape(leaf)}")
            guard.check(vals)  # surface silent k-scan recompiles
            base_step = opt._step_count + 1
            opt._step_count += k
            params = param_arrays(self.model)
            opt_state = self._opt_state_tree()
            buffers = buffer_arrays(self.model)
            keys = jax.random.split(_random.next_key(), k)
            loss, new_params, new_state, new_buffers = multi_jit(
                params, opt_state, buffers, vals,
                jnp.asarray(opt.get_lr(), jnp.float32),
                jnp.asarray(base_step, jnp.int32), keys)
            self._write_back(new_params, new_state, new_buffers)
            return Tensor(loss)

        cache[k] = run
        return run


def not_to_static(fn):
    return fn


def enable_to_static(flag: bool):
    pass


from .save_load import save, load, TranslatedLayer  # noqa: E402,F401
from .bucketing import ShapeBucketer, pad_to_bucket, next_bucket  # noqa: E402,F401
from .dy2static import ConversionError, convert_control_flow  # noqa: E402,F401
from .fusion import (FusionCandidate, FusionPass, FusionPlan,  # noqa: E402,F401
                     FusionRegion, FusedOptimizerStep,
                     install_optimizer_fusion, stage_eager)
from .fusion import REGIONS as FUSION_REGIONS  # noqa: E402,F401
