"""dy2static control-flow conversion — the SOT analog.

Reference: python/paddle/jit/sot/ + python/paddle/jit/dy2static/ (SURVEY.md
§2.5 dy2static row). The reference rewrites bytecode/AST so data-dependent
Python ``if``/``while`` over Tensors become graph ops (cond/while); here the
same AST rewrite targets ``lax.cond`` / ``lax.while_loop``:

* every ``if``/``while`` statement is rewritten into a call to
  :func:`convert_ifelse` / :func:`convert_while`,
* at RUNTIME those helpers dispatch: a plain Python/concrete-bool predicate
  executes the branch normally (zero behavioural change outside tracing); a
  traced Tensor predicate becomes ``lax.cond`` / ``lax.while_loop`` so the
  function compiles ONCE instead of failing with TracerBoolConversionError,
* anything outside the convertible subset fails with a
  :class:`ConversionError` naming the source line and the rule it broke —
  the actionable-diagnostic half of the contract.

Convertible subset (documented limits, mirroring the reference's supported
cases): branch bodies that assign variables and/or both-return; loop bodies
that assign carried variables. ``break``/``continue``/``return`` inside a
converted-while and single-branch ``return`` raise ConversionError.
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
import warnings
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ConversionError", "convert_ifelse", "convert_while",
           "convert_control_flow"]


# A `for` over a traced tensor unrolls shape[0] copies of its body into the
# jaxpr; past this many rows the compile cost dwarfs any convenience, so the
# conversion raises the actionable error (or falls back to eager) instead.
# One constant with Tensor.__iter__'s guard (which covers wrapped iteration
# — enumerate/zip/reversed — that never reaches check_iterable).
from paddle_tpu.core.tensor import (  # noqa: E402
    TRACED_ITER_UNROLL_LIMIT as _TENSOR_FOR_UNROLL_LIMIT)


class ConversionError(RuntimeError):
    """Data-dependent control flow that cannot be converted; the message
    names the offending source location and what to change."""


def _is_traced(v) -> bool:
    if isinstance(v, Tensor):
        v = v._value
    return isinstance(v, jax.core.Tracer)


def _unwrap(tree):
    return jax.tree_util.tree_map(
        lambda v: v._value if isinstance(v, Tensor) else v, tree,
        is_leaf=lambda v: isinstance(v, Tensor))


def _wrap_like(tree, template):
    t_leaves = jax.tree_util.tree_leaves(
        template, is_leaf=lambda v: isinstance(v, Tensor))
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = [Tensor(v) if isinstance(t, Tensor) else v
           for v, t in zip(leaves, t_leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


class _Undefined:
    """Placeholder for a name not bound before the branch assigned it."""

    def __repr__(self):
        return "<undefined>"


UNDEFINED = _Undefined()


def check_unconvertible(pred, loc: str, reason: str):
    """Guard for control flow left in Python form: concrete predicates pass
    through (original behaviour); traced ones get the actionable error."""
    p = pred._value if isinstance(pred, Tensor) else pred
    if isinstance(p, jax.core.Tracer):
        raise ConversionError(f"{loc}: {reason}")
    return bool(p)


def convert_ifelse(pred, true_fn: Callable, false_fn: Callable, loc: str = "",
                   names=None):
    """Runtime dispatch for a rewritten ``if`` statement.

    Both branch fns take no arguments (they close over the local scope) and
    return the tuple of names assigned in either branch. ``names`` (when
    provided by the rewriter) labels that tuple position-by-position so
    synthetic conversion temporaries can be recognised at runtime.
    """
    p = pred._value if isinstance(pred, Tensor) else pred
    if not isinstance(p, jax.core.Tracer):
        # concrete: behave exactly like the original Python if
        return true_fn() if bool(p) else false_fn()
    pb = jnp.asarray(p)
    if pb.shape != ():
        raise ConversionError(
            f"{loc}: tensor predicate of a converted `if` must be a scalar, "
            f"got shape {tuple(pb.shape)}; reduce it (e.g. .all()/.any()) "
            "first")
    try:
        t_out = true_fn()
        f_out = false_fn()
    except NameError as e:
        raise ConversionError(
            f"{loc}: {e} while tracing both branches of a data-dependent "
            "`if` — a variable assigned in only one branch must be "
            "initialised before the `if`") from e
    if names is not None and isinstance(t_out, tuple) \
            and isinstance(f_out, tuple):
        # A synthetic __dy2st_* temporary (a nested conversion's range
        # triple / index var / inner escape flag) that one branch binds
        # and the other leaves UNDEFINED is branch-LOCAL: it is re-
        # initialised before any later use, so its post-if value is dead.
        # Mirror the bound side across instead of demanding both branches
        # bind it. User names keep the strict same-structure error below.
        t_out, f_out = list(t_out), list(f_out)
        for i, name in enumerate(names):
            if not name.startswith("__dy2st_"):
                continue
            if isinstance(t_out[i], _Undefined) and \
                    not isinstance(f_out[i], _Undefined):
                t_out[i] = f_out[i]
            elif isinstance(f_out[i], _Undefined) and \
                    not isinstance(t_out[i], _Undefined):
                f_out[i] = t_out[i]
        t_out, f_out = tuple(t_out), tuple(f_out)
    tu, fu = _unwrap(t_out), _unwrap(f_out)
    t_struct = jax.tree_util.tree_structure(tu)
    f_struct = jax.tree_util.tree_structure(fu)
    if t_struct != f_struct:
        raise ConversionError(
            f"{loc}: the two branches of a converted `if` produced "
            f"different variable structures ({t_struct} vs {f_struct}); "
            "assign the same variables (with the same nesting) in both "
            "branches")
    t_leaves, treedef = jax.tree_util.tree_flatten(tu)
    f_leaves = jax.tree_util.tree_leaves(fu)
    for i, (a, b) in enumerate(zip(t_leaves, f_leaves)):
        if isinstance(a, _Undefined) or isinstance(b, _Undefined):
            raise ConversionError(
                f"{loc}: a variable assigned in only one branch of a "
                "data-dependent `if` is undefined in the other; initialise "
                "it before the `if`")
        sa = getattr(a, "shape", None)
        sb = getattr(b, "shape", None)
        # one branch assigned a Python scalar (bool/int/float) while the
        # other carries a traced () array — e.g. the break-lowering's
        # `brk = True` against a carried flag; promote the Python side
        if sa is None and sb == ():
            t_leaves[i] = jnp.asarray(a, getattr(b, "dtype", None))
            continue
        if sb == None and sa == ():  # noqa: E711  (symmetric case)
            f_leaves[i] = jnp.asarray(b, getattr(a, "dtype", None))
            continue
        if sa != sb:
            raise ConversionError(
                f"{loc}: branch outputs disagree on shape ({sa} vs {sb}); "
                "lax.cond requires both branches to produce identical "
                "shapes/dtypes")
    tu = jax.tree_util.tree_unflatten(treedef, t_leaves)
    fu = jax.tree_util.tree_unflatten(treedef, f_leaves)
    out = jax.lax.cond(pb.astype(bool), lambda: tu, lambda: fu)
    return _wrap_like(out, t_out)


def convert_bool_op(op: str, loc: str, *thunks):
    """Runtime dispatch for ``a and b`` / ``a or b``.

    Operands arrive as thunks so concrete values keep Python's exact
    short-circuit semantics (including returning the operand itself, not a
    bool). The first TRACED operand ends short-circuiting: the remaining
    operands are evaluated and folded with logical_and/or into a boolean
    tensor (the reference SOT's behaviour for tensor predicates).

    DOCUMENTED DIVERGENCE: once an operand is traced, every later operand
    is evaluated eagerly — a guard like ``t_cond and x / y > 0`` divides
    even when ``t_cond`` would be false, so side effects/exceptions fire
    where Python's short-circuit would have skipped them. Exceptions from
    a post-trace operand are annotated with the conversion location."""
    val = thunks[0]()
    for i, t in enumerate(thunks[1:], 1):
        raw = val._value if isinstance(val, Tensor) else val
        if not isinstance(raw, jax.core.Tracer):
            if op == "and":
                if not raw:
                    return val
            else:
                if raw:
                    return val
            val = t()
            continue
        acc = jnp.asarray(raw).astype(bool)
        for t2 in thunks[i:]:
            try:
                v2 = t2()
            except Exception as e:
                note = (
                    f"dy2static {loc}: an earlier operand of this "
                    f"`{op}` is a traced tensor, so short-circuit "
                    "evaluation does not apply — later operands run "
                    "unconditionally under tracing. Guard the "
                    "failing operand (e.g. hoist it above the "
                    "bool-op) if it must be skipped.")
                if hasattr(e, "add_note"):
                    e.add_note(note)
                else:  # PEP 678 shim for Python < 3.11
                    e.__notes__ = getattr(e, "__notes__", []) + [note]
                raise
            v2 = v2._value if isinstance(v2, Tensor) else v2
            nxt = jnp.asarray(v2).astype(bool)
            acc = (jnp.logical_and(acc, nxt) if op == "and"
                   else jnp.logical_or(acc, nxt))
        return Tensor(acc)
    return val


def convert_not(value, loc: str = ""):
    """``not x``: Python semantics for concrete x, logical_not for traced."""
    raw = value._value if isinstance(value, Tensor) else value
    if isinstance(raw, jax.core.Tracer):
        return Tensor(jnp.logical_not(jnp.asarray(raw).astype(bool)))
    return not raw


def convert_range_args(loc: str, *args):
    """Normalize range(...) arguments to a (start, stop, step) triple."""
    vals = [a._value if isinstance(a, Tensor) else a for a in args]
    if len(vals) == 1:
        start, stop, step = 0, vals[0], 1
    elif len(vals) == 2:
        start, stop, step = vals[0], vals[1], 1
    elif len(vals) == 3:
        start, stop, step = vals
    else:
        raise ConversionError(f"{loc}: range() takes 1-3 arguments")
    if not isinstance(step, jax.core.Tracer):
        try:
            if int(step) == 0:
                raise ValueError("range() arg 3 must not be zero")
        except TypeError:
            pass
    return start, stop, step


def convert_range_cont(i, stop, step):
    """The for-range continuation predicate: direction-aware i-vs-stop."""
    vals = [v._value if isinstance(v, Tensor) else v
            for v in (i, stop, step)]
    if any(isinstance(v, jax.core.Tracer) for v in vals):
        iv, ev, sv = (jnp.asarray(v) for v in vals)
        return Tensor(jnp.where(sv > 0, iv < ev, iv > ev))
    iv, ev, sv = vals
    return (sv > 0 and iv < ev) or (sv < 0 and iv > ev)


def check_iterable(it, loc: str):
    """Dispatch for a ``for`` over a non-range iterable.

    Concrete iterables run the plain Python loop. Traced tensors iterate
    their leading axis with the STATIC trip count ``shape[0]`` (shapes are
    always static under a jax trace), unrolling the body once per row —
    the same semantics jax itself gives ``for row in traced_array`` and
    the reference SOT gives tensor iteration (``paddle/jit/sot``:§0,
    VERDICT r4's last named dy2static gap). 0-d tensors raise the
    actionable error (Python cannot iterate a scalar either)."""
    raw = it._value if isinstance(it, Tensor) else it
    if isinstance(raw, jax.core.Tracer):
        if not raw.shape:
            raise ConversionError(
                f"{loc}: iterating a 0-d traced tensor in a `for` loop; "
                "loops need a leading axis (or use a tensor op)")
        n = raw.shape[0]
        if n > _TENSOR_FOR_UNROLL_LIMIT:
            raise ConversionError(
                f"{loc}: iterating a traced tensor with leading axis {n} "
                f"would unroll {n} copies of the loop body (limit "
                f"{_TENSOR_FOR_UNROLL_LIMIT}); loop over `range(n)` and "
                "index, or use a tensor op (scan/vmap)")
        # Tensor indexing preserves the wrapper; raw aliases `it` otherwise.
        return [it[i] for i in range(n)]
    return it


def convert_ret_select(loc, default_fn, *sites):
    """Single-exit return selector planted by the return-in-loop lowering.

    ``sites`` are ``(flag, value_thunk)`` pairs, one per lowered ``return``
    statement, in source order. The guards the lowering plants make the
    flags mutually exclusive (once a return fires, every later flag's code
    is skipped/broken out of), so fold order is irrelevant. Concrete flags
    reproduce Python exactly (only the fired site's thunk runs); any traced
    flag evaluates every thunk and selects via lax.cond."""
    if not any(_is_traced(f) for f, _ in sites):
        for f, th in sites:
            raw = f._value if isinstance(f, Tensor) else f
            if bool(raw):
                return th()
        return default_fn()
    out = default_fn()
    for f, th in sites:
        val = th()
        out = convert_ifelse(f, lambda v=val: v, lambda o=out: o, loc)
    return out


def convert_while(cond_fn: Callable, body_fn: Callable, carry, loc: str = ""):
    """Runtime dispatch for a rewritten ``while``.

    cond_fn(carry) -> predicate; body_fn(carry) -> new carry (same
    structure). Concrete predicates run the plain Python loop; traced ones
    lower to ``lax.while_loop`` (one compile, data-dependent trip count).
    """
    first = cond_fn(carry)
    if not _is_traced(first) and not any(
            _is_traced(v) for v in jax.tree_util.tree_leaves(
                _unwrap(carry))):
        while bool(first._value if isinstance(first, Tensor) else first):
            carry = body_fn(carry)
            first = cond_fn(carry)
        return carry
    ucarry = _unwrap(carry)
    init_leaves, treedef = jax.tree_util.tree_flatten(ucarry)
    if any(isinstance(v, _Undefined) for v in init_leaves):
        # Names assigned in the body but unbound before the loop (a nested
        # loop's per-iteration locals, e.g. `for ...: acc = 0; ...`).
        # Their init value is DEAD — the body assigns before reading — so
        # probe-trace the body once to learn each slot's shape/dtype and
        # seed it with zeros. A name still UNDEFINED in the probe output
        # was never assigned-before-read: that is the real user error.
        probe = jax.tree_util.tree_leaves(_unwrap(body_fn(carry)))
        for i, v in enumerate(init_leaves):
            if not isinstance(v, _Undefined):
                continue
            p = probe[i]
            if isinstance(p, _Undefined):
                raise ConversionError(
                    f"{loc}: a loop-carried variable is undefined before a "
                    "data-dependent `while` and the body reads it before "
                    "assigning; initialise it before the loop")
            # probe values may be plain Python scalars (a nested concrete
            # loop's counter) — jnp.asarray gives them an aval too
            init_leaves[i] = jnp.zeros_like(jnp.asarray(p))
        ucarry = jax.tree_util.tree_unflatten(treedef, init_leaves)
        carry = _wrap_like(ucarry, carry)

    def cond(u):
        p = _unwrap(cond_fn(_wrap_like(u, carry)))
        return jnp.asarray(p).astype(bool).reshape(())

    def body(u):
        new = _unwrap(body_fn(_wrap_like(u, carry)))
        ns = jax.tree_util.tree_structure(new)
        os = jax.tree_util.tree_structure(ucarry)
        if ns != os:
            raise ConversionError(
                f"{loc}: converted `while` body changed the carried "
                f"variable structure ({os} -> {ns}); a compiled loop needs "
                "a fixed set of variables")
        return new

    try:
        out = jax.lax.while_loop(cond, body, ucarry)
    except TypeError as e:
        raise ConversionError(
            f"{loc}: lax.while_loop rejected the loop ({e}); carried "
            "shapes/dtypes must be identical every iteration — pad or "
            "bucket growing tensors (paddle_tpu.jit.pad_to_bucket)") from e
    return _wrap_like(out, carry)


# ===========================================================================
# AST rewrite
# ===========================================================================
def _store_names(nodes) -> set:
    """VARIABLE names bound by assignment/augassign/for-targets within
    ``nodes``. Does not descend into nested function/class definitions and
    does NOT include def/class names — function/class objects cannot ride a
    lax.cond/while carry (this also excludes the __dy2st_* helper defs a
    nested rewrite plants)."""
    found = set()

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):  # do not descend, do not carry
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

        def visit_Name(self, node):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                found.add(node.id)

        def visit_Subscript(self, node):
            # `out[t] = v` / `out[t] += v` rebinds out's VALUE: the base
            # name must ride the carry or the in-place write inside the
            # converted body leaks a tracer into the closed-over object
            if isinstance(node.ctx, (ast.Store, ast.Del)) and \
                    isinstance(node.value, ast.Name):
                found.add(node.value.id)
            self.generic_visit(node)

    for n in nodes:
        V().visit(n)
    return found


def _load_names(node, prune_defs: bool = False) -> set:
    """Names read within ``node``. With ``prune_defs`` nested
    function/class bodies are skipped — a nested def's closure reads of
    __dy2st_* names always follow their assignment in the same iteration
    (the rewriter emits assigns before the defs that read them). Lambdas
    are NEVER pruned: the bool-op conversion hides predicate reads (e.g.
    a loop's break flag) inside thunk lambdas, and those are real reads
    at statement execution time."""
    found = set()

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Load):
                found.add(node.id)

        if prune_defs:
            def visit_FunctionDef(self, node):  # prune
                pass

            visit_AsyncFunctionDef = visit_FunctionDef
            visit_ClassDef = visit_FunctionDef

    V().visit(node)
    return found


def _has(nodes, kinds, prune_loops: bool = False) -> ast.AST:
    """First node of any of ``kinds`` inside ``nodes``, PRUNING nested
    function/class subtrees (a Return inside a nested def — including the
    __dy2st_* branch helpers an inner rewrite plants — does not belong to
    the enclosing statement). ``prune_loops`` additionally skips nested
    While/For subtrees — a Break/Continue inside an inner loop belongs to
    THAT loop (a Return, by contrast, escapes every loop, so Return
    searches must not prune)."""
    hit = []

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):  # prune
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

        if prune_loops:
            def visit_While(self, node):  # inner escapes are theirs
                pass

            visit_For = visit_While
            visit_AsyncFor = visit_While

        def generic_visit(self, node):
            if not hit and isinstance(node, kinds):
                hit.append(node)
            if not hit:
                super().generic_visit(node)

    for n in nodes:
        V().visit(n)
        if hit:
            return hit[0]
    return None


class _ReturnLowering:
    """Single-exit rewrite for ``return`` inside loops — the reference
    dy2static ReturnTransformer's role (python/paddle/jit/dy2static/
    transformers/return_transformer.py:§0), built on the same flag
    machinery as break/continue lowering.

    Every ``return expr`` whose nearest enclosing construct chain reaches
    a While/For becomes ``__ret_flag_N = True; break`` — the break rides
    the existing escape lowering — and ``expr`` is RECORDED, not
    evaluated: because the break exits immediately, the loop-carried
    state at the post-loop program point equals the state at the return
    site, so the expr evaluates identically there (every name a loop
    body assigns is loop-carried by the while conversion). Spine
    statements after a flagging loop are wrapped in ``if not (flags):``
    guards, and the function gains a single trailing
    ``return __dy2st_ret_select(...)`` that picks the fired site's value
    (or the original fall-through return) — see
    :func:`convert_ret_select`.

    Returns inside ``try``/``match`` blocks of a converted loop raise
    ConversionError (→ eager fallback when enabled)."""

    def __init__(self, filename: str):
        self.filename = filename
        self.n = 0

    def _loc(self, node) -> str:
        return f"{self.filename}:{getattr(node, 'lineno', '?')}"

    @staticmethod
    def _flags_or(flags):
        if not flags:
            raise ConversionError(
                "internal: return-lowering produced an empty flag set")
        names = [ast.Name(id=f, ctx=ast.Load()) for f in flags]
        return names[0] if len(names) == 1 else \
            ast.BoolOp(op=ast.Or(), values=names)

    @staticmethod
    def _loop_has_return(stmts) -> bool:
        """Is there a Return nested inside any While/For (pruning defs)?"""
        found = []

        class V(ast.NodeVisitor):
            def visit_FunctionDef(self, node):  # prune
                pass

            visit_AsyncFunctionDef = visit_FunctionDef
            visit_ClassDef = visit_FunctionDef
            visit_Lambda = visit_FunctionDef

            def visit_While(self, node):
                found.append(node)
                self.generic_visit(node)

            visit_For = visit_While
            visit_AsyncFor = visit_While

        for s in stmts:
            V().visit(s)
        return any(_has(lp.body, ast.Return) is not None for lp in found)

    @staticmethod
    def _thunk(expr):
        return ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=expr)

    def run(self, fdef) -> bool:
        """Apply in place; True when the function was rewritten."""
        if not self._loop_has_return(fdef.body):
            return False
        fdef.body = self._transform_block(list(fdef.body), fdef.lineno)
        ast.fix_missing_locations(fdef)
        return True

    def _transform_block(self, stmts, lineno):
        """Bring a function-level block into single-exit form: the first
        statement whose loops carry a lowered ``return`` splits the
        block — everything after it moves into a ``__dy2st_tail_N``
        closure (evaluated only when no lowered return fired, so names
        FIRST bound after the loop stay ordinary locals of the tail) and
        the block ends with ``return __dy2st_ret_select(...)``. Blocks
        with no return-carrying loop are returned unchanged."""
        for i, st in enumerate(stmts):
            sites: list = []
            if isinstance(st, (ast.While, ast.For)) and \
                    _has(st.body, ast.Return) is not None:
                st.body = self._lower_in_loop(st.body, sites) or [ast.Pass()]
            elif isinstance(st, ast.If) and self._loop_has_return([st]):
                # a loop-with-return nested in an if branch: flags set
                # inside propagate out of the converted if (plain stored
                # names); statements after the loop within the branch are
                # guarded by _lower_branch
                st.body = self._lower_branch(list(st.body), sites)
                st.orelse = self._lower_branch(list(st.orelse), sites)
            if not sites:
                continue
            inits = [ast.copy_location(ast.Assign(
                targets=[ast.Name(id=f, ctx=ast.Store())],
                value=ast.Constant(value=False)), st)
                for f, _ in sites]
            remainder = stmts[i + 1:]
            return (stmts[:i] + inits + [st]
                    + self._make_tail_return(remainder, sites, st))
        return stmts

    def _make_tail_return(self, remainder, sites, anchor):
        """Build ``[<preamble>, def __dy2st_tail_N(...), return
        __dy2st_ret_select(loc, tail, *sites)]``. The tail closure holds
        the whole post-loop remainder (recursively transformed), so its
        natural ``return`` stays inside it and new names bind as tail
        locals; stores that shadow pre-loop names are snapshot as default
        arguments (the _make_call pattern) to dodge UnboundLocalError."""
        loc = self._loc(anchor)
        out = []
        if remainder:
            stores = sorted(_store_names(remainder))
            tail_name = f"__dy2st_tail_{self.n}"
            self.n += 1
            tail_body = self._transform_block(remainder, anchor.lineno) \
                or [ast.Pass()]
            tail_def = ast.FunctionDef(
                name=tail_name,
                args=ast.arguments(
                    posonlyargs=[],
                    args=[ast.arg(arg=a) for a in stores],
                    kwonlyargs=[], kw_defaults=[],
                    defaults=[ast.Name(id=a, ctx=ast.Load())
                              for a in stores]),
                body=tail_body, decorator_list=[], type_params=[])
            out += _RewriteControlFlow._undef_preamble(stores)
            out.append(tail_def)
            default = ast.Name(id=tail_name, ctx=ast.Load())
        else:
            default = self._thunk(ast.Constant(value=None))
        sel = ast.Return(value=ast.Call(
            func=ast.Name(id="__dy2st_ret_select", ctx=ast.Load()),
            args=[ast.Constant(value=loc), default]
            + [ast.Tuple(elts=[ast.Name(id=f, ctx=ast.Load()),
                               self._thunk(e)], ctx=ast.Load())
               for f, e in sites],
            keywords=[]))
        out.append(sel)
        return [ast.copy_location(s, anchor) for s in out]

    def _lower_branch(self, block, sites):
        """Inside an if-branch on the spine (no ``break`` available, no
        early block exit): lower return-carrying loops; statements after
        one are wrapped in ``if not (<its flags>):`` so they are skipped
        once a return fired. Plain direct Returns stay (the branch
        conversion or the eager fallback owns them)."""
        for i, st in enumerate(block):
            local: list = []
            if isinstance(st, (ast.While, ast.For)) and \
                    _has(st.body, ast.Return) is not None:
                st.body = self._lower_in_loop(st.body, local) or [ast.Pass()]
            elif isinstance(st, ast.If) and self._loop_has_return([st]):
                st.body = self._lower_branch(list(st.body), local)
                st.orelse = self._lower_branch(list(st.orelse), local)
            if not local:
                continue
            sites.extend(local)
            rest = self._lower_branch(block[i + 1:], sites)
            out = block[:i + 1]
            if rest:
                guard = ast.If(
                    test=ast.UnaryOp(
                        op=ast.Not(),
                        operand=self._flags_or([f for f, _ in local])),
                    body=rest, orelse=[])
                out.append(ast.copy_location(guard, st))
            return out
        return block

    def _lower_in_loop(self, block, sites):
        """Inside a loop body: Return -> flag + break (dead code after a
        return in the same block is dropped; the later escape lowering
        guards cross-statement paths)."""
        out = []
        for st in block:
            if isinstance(st, ast.Return):
                flag = f"__ret_flag_{self.n}"
                self.n += 1
                expr = st.value if st.value is not None \
                    else ast.Constant(value=None)
                sites.append((flag, expr))
                out.append(ast.copy_location(ast.Assign(
                    targets=[ast.Name(id=flag, ctx=ast.Store())],
                    value=ast.Constant(value=True)), st))
                out.append(ast.copy_location(ast.Break(), st))
                return out
            if isinstance(st, ast.If) and _has([st], ast.Return) is not None:
                st.body = self._lower_in_loop(st.body, sites) or [ast.Pass()]
                st.orelse = self._lower_in_loop(st.orelse, sites)
                out.append(st)
                continue
            if isinstance(st, (ast.While, ast.For)) and \
                    _has(st.body, ast.Return) is not None:
                inner: list = []
                st.body = self._lower_in_loop(st.body, inner) or [ast.Pass()]
                out.append(st)
                if inner:
                    sites.extend(inner)
                    # the fired return must escape THIS loop too
                    out.append(ast.copy_location(ast.If(
                        test=self._flags_or([f for f, _ in inner]),
                        body=[ast.Break()], orelse=[]), st))
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)) and \
                    _has([st], ast.Return) is not None:
                st.body = self._lower_in_loop(st.body, sites) or [ast.Pass()]
                out.append(st)
                continue
            if isinstance(st, (ast.Try, ast.Match)) and \
                    _has([st], ast.Return) is not None:
                raise ConversionError(
                    f"{self._loc(st)}: `return` inside a "
                    f"{type(st).__name__.lower()} block of a converted "
                    "loop is not supported; move the return out of the "
                    "block")
            out.append(st)
        return out


class _RewriteControlFlow(ast.NodeTransformer):
    """Rewrite If/While statements into convert_ifelse/convert_while calls."""

    def __init__(self, filename: str):
        self.filename = filename
        self.counter = 0

    def _loc(self, node) -> str:
        return f"{self.filename}:{node.lineno}"

    @staticmethod
    def _undef_preamble(names):
        """`try: name / except NameError: name = UNDEFINED` per name, so a
        name bound in only one branch/iteration traces as an UNDEFINED leaf
        instead of crashing with NameError inside the branch closure."""
        out = []
        for a in names:
            out.append(ast.Try(
                body=[ast.Expr(value=ast.Name(id=a, ctx=ast.Load()))],
                handlers=[ast.ExceptHandler(
                    type=ast.Name(id="NameError", ctx=ast.Load()),
                    name=None,
                    body=[ast.Assign(
                        targets=[ast.Name(id=a, ctx=ast.Store())],
                        value=ast.Name(id="__dy2st_UNDEFINED",
                                       ctx=ast.Load()))])],
                orelse=[], finalbody=[]))
        return out

    @staticmethod
    def _undef_cleanup(names):
        """`if name is UNDEFINED: del name` per name — restores the exact
        unbound-variable behaviour after the concrete path leaves a
        placeholder in a variable its taken branch never assigned."""
        out = []
        for a in names:
            out.append(ast.If(
                test=ast.Compare(
                    left=ast.Name(id=a, ctx=ast.Load()),
                    ops=[ast.Is()],
                    comparators=[ast.Name(id="__dy2st_UNDEFINED",
                                          ctx=ast.Load())]),
                body=[ast.Delete(
                    targets=[ast.Name(id=a, ctx=ast.Del())])],
                orelse=[]))
        return out

    def _guard_test(self, node, reason: str):
        """Leave the statement in Python form, but wrap its test so a
        TRACED predicate raises the actionable ConversionError while
        concrete predicates behave exactly as before."""
        node.test = ast.Call(
            func=ast.Name(id="__dy2st_check_unconvertible", ctx=ast.Load()),
            args=[self._convert_bool_expr(node.test, self._loc(node)),
                  ast.Constant(value=self._loc(node)),
                  ast.Constant(value=reason)],
            keywords=[])
        ast.copy_location(node.test, node)
        return node

    # -- if ------------------------------------------------------------------
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        body, orelse = node.body, node.orelse
        esc = _has(body + orelse, (ast.Break, ast.Continue),
                   prune_loops=True)
        if esc is not None:
            # cannot pull a loop-escape statement into a branch function;
            # keep Python form, diagnose only if the predicate is traced
            return self._guard_test(
                node,
                f"`{type(esc).__name__.lower()}` inside a data-dependent "
                "`if` is not convertible; fold the condition into the "
                "enclosing loop predicate")
        rets = (_has(body, ast.Return), _has(orelse, ast.Return))
        loc = self._loc(node)
        n = self.counter
        self.counter += 1
        if rets[0] or rets[1]:
            # supported: BOTH branches are a single `return <expr>`
            if (len(body) == 1 and isinstance(body[0], ast.Return)
                    and len(orelse) == 1 and isinstance(orelse[0], ast.Return)
                    and body[0].value is not None
                    and orelse[0].value is not None):
                defs, call = self._make_call(
                    node, n, [ast.Return(value=body[0].value)],
                    [ast.Return(value=orelse[0].value)], returning=True)
                return [ast.copy_location(s, node)
                        for s in defs + [ast.Return(value=call)]]
            return self._guard_test(
                node,
                "`return` inside a data-dependent `if` is convertible only "
                "as `if p: return a` + `else: return b` (both branches a "
                "single return); restructure, or compute the value with "
                "paddle.where")
        assigned = sorted((_store_names(body) | _store_names(orelse)))
        defs, call = self._make_call(node, n, body, orelse, names=assigned)
        if assigned:
            target = ast.Tuple(
                elts=[ast.Name(id=a, ctx=ast.Store()) for a in assigned],
                ctx=ast.Store())
            stmts = (self._undef_preamble(assigned) + defs
                     + [ast.Assign(targets=[target], value=call)]
                     + self._undef_cleanup(assigned))
        else:
            stmts = defs + [ast.Expr(value=call)]
        return [ast.copy_location(s, node) for s in stmts]

    def _make_call(self, node, n, body, orelse, names=None, returning=False):
        """Build __dy2st_true_N/__dy2st_false_N defs + the convert call."""
        def branch(name, stmts):
            stmts = list(stmts) or [ast.Pass()]
            params, defaults = [], []
            if not returning:
                tup = ast.Tuple(
                    elts=[ast.Name(id=a, ctx=ast.Load()) for a in names],
                    ctx=ast.Load())
                stmts = stmts + [ast.Return(value=tup)]
                # read+assign of the same name inside the branch closure
                # (e.g. `s = s + x`) would shadow the enclosing binding and
                # hit UnboundLocalError; snapshot the pre-if values as
                # default arguments instead (evaluated at def time, after
                # the UNDEFINED preamble, so always bound)
                params = [ast.arg(arg=a) for a in names]
                defaults = [ast.Name(id=a, ctx=ast.Load()) for a in names]
            return ast.FunctionDef(
                name=name, args=ast.arguments(
                    posonlyargs=[], args=params, kwonlyargs=[],
                    kw_defaults=[], defaults=defaults),
                body=stmts, decorator_list=[], type_params=[])

        tfn = branch(f"__dy2st_true_{n}", body)
        ffn = branch(f"__dy2st_false_{n}", orelse)
        kw = []
        if not returning and names:
            kw.append(ast.keyword(
                arg="names",
                value=ast.Tuple(elts=[ast.Constant(value=a) for a in names],
                                ctx=ast.Load())))
        call = ast.Call(
            func=ast.Name(id="__dy2st_convert_ifelse", ctx=ast.Load()),
            args=[self._convert_bool_expr(node.test, self._loc(node)),
                  ast.Name(id=tfn.name, ctx=ast.Load()),
                  ast.Name(id=ffn.name, ctx=ast.Load()),
                  ast.Constant(value=self._loc(node))],
            keywords=kw)
        return [tfn, ffn], call

    # -- break/continue flag lowering ---------------------------------------
    @staticmethod
    def _lower_escapes(stmts, brk: str, cont: str):
        """Rewrite ``break``/``continue`` in a loop body into flag
        assignments (``brk``/``cont`` = True), guarding every statement
        that follows a potential escape with ``if not (brk or cont):``
        (the reference dy2static's break_continue_transformer). Does not
        descend into nested loops or function defs (their escapes are
        theirs). Returns the rewritten statement list."""
        def set_flag(name):
            return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                              value=ast.Constant(value=True))

        def has_escape(nodes):
            return _has(nodes, (ast.Break, ast.Continue),
                        prune_loops=True) is not None

        def guard(rest):
            """if not (brk or cont): <rest>"""
            test = ast.UnaryOp(
                op=ast.Not(),
                operand=ast.BoolOp(op=ast.Or(), values=[
                    ast.Name(id=brk, ctx=ast.Load()),
                    ast.Name(id=cont, ctx=ast.Load())]))
            return ast.If(test=test, body=rest, orelse=[])

        def rewrite(block):
            out = []
            for i, st in enumerate(block):
                if isinstance(st, ast.Break):
                    out.append(set_flag(brk))
                    return out                     # rest of block is dead
                if isinstance(st, ast.Continue):
                    out.append(set_flag(cont))
                    return out
                if isinstance(st, ast.If) and has_escape([st]):
                    new_if = ast.If(test=st.test,
                                    body=rewrite(st.body) or [ast.Pass()],
                                    orelse=rewrite(st.orelse))
                    ast.copy_location(new_if, st)
                    out.append(new_if)
                    rest = rewrite(block[i + 1:])
                    if rest:
                        out.append(ast.copy_location(guard(rest), st))
                    return out
                if isinstance(st, (ast.While, ast.For, ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                    out.append(st)                 # inner escapes are theirs
                    continue
                if isinstance(st, (ast.With, ast.AsyncWith)) and \
                        has_escape([st]):
                    # the context manager exits before the escape takes
                    # effect outside it: rewrite the with-body and guard
                    # the statements that follow
                    new_with = type(st)(items=st.items,
                                        body=rewrite(st.body) or [ast.Pass()])
                    ast.copy_location(new_with, st)
                    out.append(new_with)
                    rest = rewrite(block[i + 1:])
                    if rest:
                        out.append(ast.copy_location(guard(rest), st))
                    return out
                if isinstance(st, (ast.Try, ast.Match)) and has_escape([st]):
                    raise ConversionError(
                        f"break/continue inside {type(st).__name__.lower()} "
                        "blocks of a converted loop are not supported")
                out.append(st)
            return out

        return rewrite(list(stmts))

    def _lower_loop_escapes(self, node):
        """If the loop body breaks/continues, lower the escapes to flags,
        fold ``not brk`` into the loop test, and return
        (node, pre_stmts); otherwise (node, []). The synthetic
        ``__dy2st_brk/cont`` flags stay bound after an eager loop — a
        namespaced, harmless residue."""
        esc = _has(node.body, (ast.Break, ast.Continue), prune_loops=True)
        if esc is None:
            return node, []
        n = self.counter
        self.counter += 1
        brk = f"__dy2st_brk_{n}"
        cont = f"__dy2st_cont_{n}"
        body = self._lower_escapes(node.body, brk, cont)
        reset_cont = ast.Assign(
            targets=[ast.Name(id=cont, ctx=ast.Store())],
            value=ast.Constant(value=False))
        node.body = [reset_cont] + body
        node.test = ast.BoolOp(op=ast.And(), values=[
            node.test,
            ast.UnaryOp(op=ast.Not(),
                        operand=ast.Name(id=brk, ctx=ast.Load()))])
        # both flags init False BEFORE the loop: they ride the carry, and
        # a traced while rejects undefined carried variables
        pre = [ast.Assign(
            targets=[ast.Name(id=name, ctx=ast.Store())],
            value=ast.Constant(value=False))
            for name in (brk, cont)]
        for s in pre + [node]:
            ast.copy_location(s, node)
        # synthetic subtrees (flag tests, guards, assigns) need locations
        # before any further visiting reads node.lineno
        ast.fix_missing_locations(node)
        for s in pre:
            ast.fix_missing_locations(s)
        return node, pre

    # -- while ---------------------------------------------------------------
    def visit_While(self, node: ast.While):
        if node.orelse:
            self.generic_visit(node)
            return self._guard_test(
                node, "`while ... else` is not convertible")
        node, pre = self._lower_loop_escapes(node)
        self.generic_visit(node)
        loc = self._loc(node)
        bad = _has(node.body, ast.Return)
        if bad is not None:
            guarded = self._guard_test(
                node,
                f"`return` (line {bad.lineno}) inside a data-dependent "
                "`while` is not convertible to lax.while_loop; fold the "
                "exit condition into the loop predicate")
            return pre + [guarded] if pre else guarded
        # carry = names the body assigns; loop-invariant reads (modules,
        # helper fns, constants) stay closure-captured. Synthetic
        # __dy2st_* names (nested-loop temporaries, escape flags) are
        # carried ONLY when their value actually crosses iterations —
        # i.e. they are read in the test or read before being assigned
        # within one pass over the body; everything else (an inner loop's
        # range triple, index var, flags — re-initialised every
        # iteration) stays body-local, since carrying them would demand
        # pre-loop definitions that do not exist.
        stores = _store_names(node.body)
        user = {a for a in stores if not a.startswith("__dy2st_")}
        synth = stores - user
        need = _load_names(node.test, prune_defs=True) & synth
        definite: set = set()
        for st in node.body:
            need |= (_load_names(st, prune_defs=True) & synth) - definite
            definite |= _store_names([st])
        carried = sorted(user | need)
        n = self.counter
        self.counter += 1

        def loads():
            return ast.Tuple(
                elts=[ast.Name(id=a, ctx=ast.Load()) for a in carried],
                ctx=ast.Load())

        carry_tuple_s = ast.Tuple(
            elts=[ast.Name(id=a, ctx=ast.Store()) for a in carried],
            ctx=ast.Store())
        def arg():
            return ast.arguments(
                posonlyargs=[], args=[ast.arg(arg="__dy2st_carry")],
                kwonlyargs=[], kw_defaults=[], defaults=[])

        def unpack():
            return ast.Assign(
                targets=[ast.Tuple(
                    elts=[ast.Name(id=a, ctx=ast.Store()) for a in carried],
                    ctx=ast.Store())],
                value=ast.Name(id="__dy2st_carry", ctx=ast.Load()))

        cond_fn = ast.FunctionDef(
            name=f"__dy2st_cond_{n}", args=arg(),
            body=[unpack(), ast.Return(value=self._convert_bool_expr(
                node.test, loc))],
            decorator_list=[], type_params=[])
        body_fn = ast.FunctionDef(
            name=f"__dy2st_body_{n}", args=arg(),
            body=[unpack()] + list(node.body)
            + [ast.Return(value=loads())],
            decorator_list=[], type_params=[])
        call = ast.Call(
            func=ast.Name(id="__dy2st_convert_while", ctx=ast.Load()),
            args=[ast.Name(id=cond_fn.name, ctx=ast.Load()),
                  ast.Name(id=body_fn.name, ctx=ast.Load()),
                  loads(),
                  ast.Constant(value=loc)],
            keywords=[])
        assign = ast.Assign(targets=[carry_tuple_s], value=call)
        return [ast.copy_location(s, node)
                for s in (pre + self._undef_preamble(carried)
                          + [cond_fn, body_fn, assign]
                          + self._undef_cleanup(carried))]

    # -- for -----------------------------------------------------------------
    def visit_For(self, node: ast.For):
        """``for <target> in range(...)`` desugars to the while form (the
        loop variable advances at body start, so break/continue lowering
        cannot skip the increment) and rides the existing while
        conversion. Non-range iterables stay Python loops with a runtime
        guard that raises the actionable error on traced tensors."""
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and not node.iter.keywords)
        if not is_range or node.orelse or not isinstance(node.target,
                                                         ast.Name):
            self.generic_visit(node)
            guard = ast.Call(
                func=ast.Name(id="__dy2st_check_iterable", ctx=ast.Load()),
                args=[node.iter, ast.Constant(value=self._loc(node))],
                keywords=[])
            node.iter = ast.copy_location(guard, node.iter)
            return node
        n = self.counter
        self.counter += 1
        start, stop, step = (f"__dy2st_start_{n}", f"__dy2st_stop_{n}",
                             f"__dy2st_step_{n}")
        ivar = f"__dy2st_i_{n}"
        unpack = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=v, ctx=ast.Store())
                      for v in (start, stop, step)], ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id="__dy2st_range_args", ctx=ast.Load()),
                args=[ast.Constant(value=self._loc(node))]
                + list(node.iter.args), keywords=[]))
        init = ast.Assign(targets=[ast.Name(id=ivar, ctx=ast.Store())],
                          value=ast.Name(id=start, ctx=ast.Load()))
        # pre-bind the loop target (it rides the while carry; Python leaves
        # it unbound on zero trips — here it holds `start`, documented)
        init_target = ast.Assign(
            targets=[ast.Name(id=node.target.id, ctx=ast.Store())],
            value=ast.Name(id=start, ctx=ast.Load()))
        set_target = ast.Assign(
            targets=[ast.Name(id=node.target.id, ctx=ast.Store())],
            value=ast.Name(id=ivar, ctx=ast.Load()))
        advance = ast.Assign(
            targets=[ast.Name(id=ivar, ctx=ast.Store())],
            value=ast.BinOp(left=ast.Name(id=ivar, ctx=ast.Load()),
                            op=ast.Add(),
                            right=ast.Name(id=step, ctx=ast.Load())))
        test = ast.Call(
            func=ast.Name(id="__dy2st_range_cont", ctx=ast.Load()),
            args=[ast.Name(id=ivar, ctx=ast.Load()),
                  ast.Name(id=stop, ctx=ast.Load()),
                  ast.Name(id=step, ctx=ast.Load())],
            keywords=[])
        while_node = ast.While(
            test=test, body=[set_target, advance] + list(node.body),
            orelse=[])
        for s in (unpack, init, init_target, while_node):
            ast.copy_location(s, node)
        ast.fix_missing_locations(while_node)
        converted = self.visit(while_node)
        if not isinstance(converted, list):
            converted = [converted]
        return [unpack, init, init_target] + converted

    # -- boolean operators in PREDICATE position -----------------------------
    def _convert_bool_expr(self, expr, loc: str):
        """Rewrite and/or/not in a test expression. Only boolean CONTEXT
        propagates the rewrite: recursion descends through BoolOp operands
        and Not operands, never into arbitrary sub-expressions — a
        value-position `x or default` keeps exact Python semantics (and
        fails loudly on tracers), because convert_bool_op collapses traced
        operands to a boolean tensor."""
        if isinstance(expr, ast.BoolOp):
            op = "and" if isinstance(expr.op, ast.And) else "or"
            thunks = [ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=self._convert_bool_expr(v, loc)) for v in expr.values]
            call = ast.Call(
                func=ast.Name(id="__dy2st_bool_op", ctx=ast.Load()),
                args=[ast.Constant(value=op), ast.Constant(value=loc)]
                + thunks,
                keywords=[])
            return ast.copy_location(call, expr)
        if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Not):
            call = ast.Call(
                func=ast.Name(id="__dy2st_not", ctx=ast.Load()),
                args=[self._convert_bool_expr(expr.operand, loc),
                      ast.Constant(value=loc)],
                keywords=[])
            return ast.copy_location(call, expr)
        return expr


def convert_control_flow(fn: Callable) -> Callable:
    """AST-rewrite ``fn`` so tensor-predicated if/while lower to
    lax.cond/lax.while_loop at trace time (and run unchanged eagerly).

    Returns ``fn`` unmodified (with a warning) when its source is
    unavailable (builtins, C extensions, REPL-defined lambdas).
    """
    if inspect.ismethod(fn):
        conv = convert_control_flow(fn.__func__)
        if conv is fn.__func__:
            return fn
        return types.MethodType(conv, fn.__self__)
    # operate on the innermost function of a wraps-style decorator chain:
    # its source carries the decorator lines, and its closure/globals are
    # the ones the rewritten body must see (ADVICE r3 #5)
    orig = fn
    fn = inspect.unwrap(fn)
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        filename = inspect.getsourcefile(fn) or "<dy2static>"
    except (OSError, TypeError):
        warnings.warn(
            f"dy2static: source of {getattr(fn, '__name__', fn)!r} is "
            "unavailable; data-dependent control flow will fail under jit",
            stacklevel=2)
        return orig
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return orig
    if _has(fdef.body, (ast.If, ast.While, ast.For, ast.BoolOp)) is None:
        return orig  # nothing to rewrite
    # Decorators are NEVER re-executed (re-exec'ing decorator source would
    # re-run registration side effects, recurse through aliased to_static,
    # and NameError on def-time-local arguments). Wrapper behavior from
    # decorators BELOW the conversion entry is preserved instead by
    # re-binding the live wrapper chain's closure cell onto the converted
    # function after the rewrite — see the `orig is not fn` tail
    # (ADVICE r3 #5).
    fdef.decorator_list = []
    try:
        _ReturnLowering(filename).run(fdef)
        new_tree = _RewriteControlFlow(filename).visit(tree)
    except ConversionError as e:
        from ..flags import flag_value
        if flag_value("dy2static_fallback"):
            warnings.warn(
                f"dy2static: conversion of "
                f"{getattr(fn, '__name__', fn)!r} failed ({e}); falling "
                "back to the eager path (set FLAGS_dy2static_fallback=0 "
                "for the strict raise)", stacklevel=2)
            return orig
        raise
    ast.fix_missing_locations(new_tree)
    glb = dict(fn.__globals__)
    glb["__dy2st_convert_ifelse"] = convert_ifelse
    glb["__dy2st_convert_while"] = convert_while
    glb["__dy2st_check_unconvertible"] = check_unconvertible
    glb["__dy2st_UNDEFINED"] = UNDEFINED
    glb["__dy2st_bool_op"] = convert_bool_op
    glb["__dy2st_not"] = convert_not
    glb["__dy2st_range_args"] = convert_range_args
    glb["__dy2st_range_cont"] = convert_range_cont
    glb["__dy2st_check_iterable"] = check_iterable
    glb["__dy2st_ret_select"] = convert_ret_select
    freevars = fn.__code__.co_freevars
    if freevars:
        # re-bind the original closure: wrap the rewritten def in a factory
        # taking the free variables as parameters (their CURRENT cell values
        # are snapshotted at conversion time)
        factory = ast.FunctionDef(
            name="__dy2st_factory",
            args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=v) for v in freevars],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[new_tree.body[0],
                  ast.Return(value=ast.Name(id=fdef.name, ctx=ast.Load()))],
            decorator_list=[], type_params=[])
        new_tree = ast.Module(body=[factory], type_ignores=[])
        ast.fix_missing_locations(new_tree)
        code = compile(new_tree, filename, "exec")
        loc: dict = {}
        exec(code, glb, loc)
        cells = [c.cell_contents for c in (fn.__closure__ or ())]
        new_fn = loc["__dy2st_factory"](*cells)
    else:
        code = compile(new_tree, filename, "exec")
        loc = {}
        exec(code, glb, loc)
        new_fn = loc[fdef.name]
    new_fn = functools.wraps(fn)(new_fn)
    new_fn.__dy2st_source__ = fn
    if orig is not fn:
        # ``orig`` is a live wraps-style wrapper chain around ``fn`` (user
        # decorators below the conversion entry). Preserve their per-call
        # behavior by pointing the wrapper that calls ``fn`` at the
        # converted function: find its closure cell holding ``fn`` and
        # re-bind it. The converted body is semantically identical eagerly,
        # so mutating the shared cell is safe — but NOTE the rebind is
        # PROCESS-WIDE: every other call site of the shared wrapper object
        # switches to the converted body too (including its zero-trip-loop
        # target binding and bool-op eager-eval deviations). Gate:
        # FLAGS_dy2static_rebind_wrappers=0 keeps the wrapper untouched and
        # returns the converted function bare (the wrapper's per-call
        # behavior then only runs on the unconverted object). If no cell
        # holding ``fn`` exists (the decorator stashed it somewhere
        # opaque), warn — never drop silently (ADVICE r3 #5, r4 #2).
        from ..flags import flag_value
        if not flag_value("dy2static_rebind_wrappers"):
            warnings.warn(
                f"dy2static: FLAGS_dy2static_rebind_wrappers=0 — the "
                f"decorator wrapping {getattr(orig, '__name__', orig)!r} "
                "is left untouched and its per-call behavior is dropped "
                "from the converted path", stacklevel=2)
            return new_fn
        import logging
        logging.getLogger(__name__).debug(
            "dy2static: re-binding the wrapper chain of %r onto the "
            "converted function (process-wide effect on the shared "
            "wrapper; FLAGS_dy2static_rebind_wrappers=0 disables)",
            getattr(orig, "__name__", orig))
        link = orig
        while link is not None and link is not fn:
            for cell in (getattr(link, "__closure__", None) or ()):
                try:
                    held = cell.cell_contents
                except ValueError:   # empty cell
                    continue
                # match the raw fn OR a previous conversion of it, so
                # converting the same decorated function twice stays
                # idempotent instead of spuriously warning
                if held is fn or getattr(held, "__dy2st_source__",
                                         None) is fn:
                    cell.cell_contents = new_fn
                    return orig
            link = getattr(link, "__wrapped__", None)
        warnings.warn(
            f"dy2static: {getattr(orig, '__name__', orig)!r} is wrapped by "
            "a decorator whose reference to the original function cannot "
            "be re-bound; the decorator's per-call behavior is dropped "
            "from the converted path (the original object keeps it)",
            stacklevel=2)
    return new_fn
