"""dy2static control-flow conversion — the SOT analog.

Reference: python/paddle/jit/sot/ + python/paddle/jit/dy2static/ (SURVEY.md
§2.5 dy2static row). The reference rewrites bytecode/AST so data-dependent
Python ``if``/``while`` over Tensors become graph ops (cond/while); here the
same AST rewrite targets ``lax.cond`` / ``lax.while_loop``:

* every ``if``/``while`` statement is rewritten into a call to
  :func:`convert_ifelse` / :func:`convert_while`,
* at RUNTIME those helpers dispatch: a plain Python/concrete-bool predicate
  executes the branch normally (zero behavioural change outside tracing); a
  traced Tensor predicate becomes ``lax.cond`` / ``lax.while_loop`` so the
  function compiles ONCE instead of failing with TracerBoolConversionError,
* anything outside the convertible subset fails with a
  :class:`ConversionError` naming the source line and the rule it broke —
  the actionable-diagnostic half of the contract.

Convertible subset (documented limits, mirroring the reference's supported
cases): branch bodies that assign variables and/or both-return; loop bodies
that assign carried variables. ``break``/``continue``/``return`` inside a
converted-while and single-branch ``return`` raise ConversionError.
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
import warnings
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ConversionError", "convert_ifelse", "convert_while",
           "convert_control_flow"]


class ConversionError(RuntimeError):
    """Data-dependent control flow that cannot be converted; the message
    names the offending source location and what to change."""


def _is_traced(v) -> bool:
    if isinstance(v, Tensor):
        v = v._value
    return isinstance(v, jax.core.Tracer)


def _unwrap(tree):
    return jax.tree_util.tree_map(
        lambda v: v._value if isinstance(v, Tensor) else v, tree,
        is_leaf=lambda v: isinstance(v, Tensor))


def _wrap_like(tree, template):
    t_leaves = jax.tree_util.tree_leaves(
        template, is_leaf=lambda v: isinstance(v, Tensor))
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = [Tensor(v) if isinstance(t, Tensor) else v
           for v, t in zip(leaves, t_leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


class _Undefined:
    """Placeholder for a name not bound before the branch assigned it."""

    def __repr__(self):
        return "<undefined>"


UNDEFINED = _Undefined()


def check_unconvertible(pred, loc: str, reason: str):
    """Guard for control flow left in Python form: concrete predicates pass
    through (original behaviour); traced ones get the actionable error."""
    p = pred._value if isinstance(pred, Tensor) else pred
    if isinstance(p, jax.core.Tracer):
        raise ConversionError(f"{loc}: {reason}")
    return bool(p)


def convert_ifelse(pred, true_fn: Callable, false_fn: Callable, loc: str = ""):
    """Runtime dispatch for a rewritten ``if`` statement.

    Both branch fns take no arguments (they close over the local scope) and
    return the tuple of names assigned in either branch.
    """
    p = pred._value if isinstance(pred, Tensor) else pred
    if not isinstance(p, jax.core.Tracer):
        # concrete: behave exactly like the original Python if
        return true_fn() if bool(p) else false_fn()
    pb = jnp.asarray(p)
    if pb.shape != ():
        raise ConversionError(
            f"{loc}: tensor predicate of a converted `if` must be a scalar, "
            f"got shape {tuple(pb.shape)}; reduce it (e.g. .all()/.any()) "
            "first")
    try:
        t_out = true_fn()
        f_out = false_fn()
    except NameError as e:
        raise ConversionError(
            f"{loc}: {e} while tracing both branches of a data-dependent "
            "`if` — a variable assigned in only one branch must be "
            "initialised before the `if`") from e
    tu, fu = _unwrap(t_out), _unwrap(f_out)
    t_struct = jax.tree_util.tree_structure(tu)
    f_struct = jax.tree_util.tree_structure(fu)
    if t_struct != f_struct:
        raise ConversionError(
            f"{loc}: the two branches of a converted `if` produced "
            f"different variable structures ({t_struct} vs {f_struct}); "
            "assign the same variables (with the same nesting) in both "
            "branches")
    for a, b in zip(jax.tree_util.tree_leaves(tu),
                    jax.tree_util.tree_leaves(fu)):
        if isinstance(a, _Undefined) or isinstance(b, _Undefined):
            raise ConversionError(
                f"{loc}: a variable assigned in only one branch of a "
                "data-dependent `if` is undefined in the other; initialise "
                "it before the `if`")
        sa = getattr(a, "shape", None)
        sb = getattr(b, "shape", None)
        if sa != sb:
            raise ConversionError(
                f"{loc}: branch outputs disagree on shape ({sa} vs {sb}); "
                "lax.cond requires both branches to produce identical "
                "shapes/dtypes")
    out = jax.lax.cond(pb.astype(bool), lambda: tu, lambda: fu)
    return _wrap_like(out, t_out)


def convert_while(cond_fn: Callable, body_fn: Callable, carry, loc: str = ""):
    """Runtime dispatch for a rewritten ``while``.

    cond_fn(carry) -> predicate; body_fn(carry) -> new carry (same
    structure). Concrete predicates run the plain Python loop; traced ones
    lower to ``lax.while_loop`` (one compile, data-dependent trip count).
    """
    first = cond_fn(carry)
    if not _is_traced(first) and not any(
            _is_traced(v) for v in jax.tree_util.tree_leaves(
                _unwrap(carry))):
        while bool(first._value if isinstance(first, Tensor) else first):
            carry = body_fn(carry)
            first = cond_fn(carry)
        return carry
    for v in jax.tree_util.tree_leaves(_unwrap(carry)):
        if isinstance(v, _Undefined):
            raise ConversionError(
                f"{loc}: a loop-carried variable is undefined before a "
                "data-dependent `while`; initialise every variable the "
                "loop assigns")
    ucarry = _unwrap(carry)

    def cond(u):
        p = _unwrap(cond_fn(_wrap_like(u, carry)))
        return jnp.asarray(p).astype(bool).reshape(())

    def body(u):
        new = _unwrap(body_fn(_wrap_like(u, carry)))
        ns = jax.tree_util.tree_structure(new)
        os = jax.tree_util.tree_structure(ucarry)
        if ns != os:
            raise ConversionError(
                f"{loc}: converted `while` body changed the carried "
                f"variable structure ({os} -> {ns}); a compiled loop needs "
                "a fixed set of variables")
        return new

    try:
        out = jax.lax.while_loop(cond, body, ucarry)
    except TypeError as e:
        raise ConversionError(
            f"{loc}: lax.while_loop rejected the loop ({e}); carried "
            "shapes/dtypes must be identical every iteration — pad or "
            "bucket growing tensors (paddle_tpu.jit.pad_to_bucket)") from e
    return _wrap_like(out, carry)


# ===========================================================================
# AST rewrite
# ===========================================================================
def _store_names(nodes) -> set:
    """VARIABLE names bound by assignment/augassign/for-targets within
    ``nodes``. Does not descend into nested function/class definitions and
    does NOT include def/class names — function/class objects cannot ride a
    lax.cond/while carry (this also excludes the __dy2st_* helper defs a
    nested rewrite plants)."""
    found = set()

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):  # do not descend, do not carry
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

        def visit_Name(self, node):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                found.add(node.id)

    for n in nodes:
        V().visit(n)
    return found


def _load_names(node) -> set:
    found = set()

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Load):
                found.add(node.id)

    V().visit(node)
    return found


def _has(nodes, kinds) -> ast.AST:
    """First node of any of ``kinds`` inside ``nodes``, PRUNING nested
    function/class subtrees (a Return inside a nested def — including the
    __dy2st_* branch helpers an inner rewrite plants — does not belong to
    the enclosing statement)."""
    hit = []

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):  # prune
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

        def generic_visit(self, node):
            if not hit and isinstance(node, kinds):
                hit.append(node)
            if not hit:
                super().generic_visit(node)

    for n in nodes:
        V().visit(n)
        if hit:
            return hit[0]
    return None


class _RewriteControlFlow(ast.NodeTransformer):
    """Rewrite If/While statements into convert_ifelse/convert_while calls."""

    def __init__(self, filename: str):
        self.filename = filename
        self.counter = 0

    def _loc(self, node) -> str:
        return f"{self.filename}:{node.lineno}"

    @staticmethod
    def _undef_preamble(names):
        """`try: name / except NameError: name = UNDEFINED` per name, so a
        name bound in only one branch/iteration traces as an UNDEFINED leaf
        instead of crashing with NameError inside the branch closure."""
        out = []
        for a in names:
            out.append(ast.Try(
                body=[ast.Expr(value=ast.Name(id=a, ctx=ast.Load()))],
                handlers=[ast.ExceptHandler(
                    type=ast.Name(id="NameError", ctx=ast.Load()),
                    name=None,
                    body=[ast.Assign(
                        targets=[ast.Name(id=a, ctx=ast.Store())],
                        value=ast.Name(id="__dy2st_UNDEFINED",
                                       ctx=ast.Load()))])],
                orelse=[], finalbody=[]))
        return out

    @staticmethod
    def _undef_cleanup(names):
        """`if name is UNDEFINED: del name` per name — restores the exact
        unbound-variable behaviour after the concrete path leaves a
        placeholder in a variable its taken branch never assigned."""
        out = []
        for a in names:
            out.append(ast.If(
                test=ast.Compare(
                    left=ast.Name(id=a, ctx=ast.Load()),
                    ops=[ast.Is()],
                    comparators=[ast.Name(id="__dy2st_UNDEFINED",
                                          ctx=ast.Load())]),
                body=[ast.Delete(
                    targets=[ast.Name(id=a, ctx=ast.Del())])],
                orelse=[]))
        return out

    def _guard_test(self, node, reason: str):
        """Leave the statement in Python form, but wrap its test so a
        TRACED predicate raises the actionable ConversionError while
        concrete predicates behave exactly as before."""
        node.test = ast.Call(
            func=ast.Name(id="__dy2st_check_unconvertible", ctx=ast.Load()),
            args=[node.test, ast.Constant(value=self._loc(node)),
                  ast.Constant(value=reason)],
            keywords=[])
        ast.copy_location(node.test, node)
        return node

    # -- if ------------------------------------------------------------------
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        body, orelse = node.body, node.orelse
        esc = _has(body + orelse, (ast.Break, ast.Continue))
        if esc is not None:
            # cannot pull a loop-escape statement into a branch function;
            # keep Python form, diagnose only if the predicate is traced
            return self._guard_test(
                node,
                f"`{type(esc).__name__.lower()}` inside a data-dependent "
                "`if` is not convertible; fold the condition into the "
                "enclosing loop predicate")
        rets = (_has(body, ast.Return), _has(orelse, ast.Return))
        loc = self._loc(node)
        n = self.counter
        self.counter += 1
        if rets[0] or rets[1]:
            # supported: BOTH branches are a single `return <expr>`
            if (len(body) == 1 and isinstance(body[0], ast.Return)
                    and len(orelse) == 1 and isinstance(orelse[0], ast.Return)
                    and body[0].value is not None
                    and orelse[0].value is not None):
                defs, call = self._make_call(
                    node, n, [ast.Return(value=body[0].value)],
                    [ast.Return(value=orelse[0].value)], returning=True)
                return [ast.copy_location(s, node)
                        for s in defs + [ast.Return(value=call)]]
            return self._guard_test(
                node,
                "`return` inside a data-dependent `if` is convertible only "
                "as `if p: return a` + `else: return b` (both branches a "
                "single return); restructure, or compute the value with "
                "paddle.where")
        assigned = sorted((_store_names(body) | _store_names(orelse)))
        defs, call = self._make_call(node, n, body, orelse, names=assigned)
        if assigned:
            target = ast.Tuple(
                elts=[ast.Name(id=a, ctx=ast.Store()) for a in assigned],
                ctx=ast.Store())
            stmts = (self._undef_preamble(assigned) + defs
                     + [ast.Assign(targets=[target], value=call)]
                     + self._undef_cleanup(assigned))
        else:
            stmts = defs + [ast.Expr(value=call)]
        return [ast.copy_location(s, node) for s in stmts]

    def _make_call(self, node, n, body, orelse, names=None, returning=False):
        """Build __dy2st_true_N/__dy2st_false_N defs + the convert call."""
        def branch(name, stmts):
            stmts = list(stmts) or [ast.Pass()]
            params, defaults = [], []
            if not returning:
                tup = ast.Tuple(
                    elts=[ast.Name(id=a, ctx=ast.Load()) for a in names],
                    ctx=ast.Load())
                stmts = stmts + [ast.Return(value=tup)]
                # read+assign of the same name inside the branch closure
                # (e.g. `s = s + x`) would shadow the enclosing binding and
                # hit UnboundLocalError; snapshot the pre-if values as
                # default arguments instead (evaluated at def time, after
                # the UNDEFINED preamble, so always bound)
                params = [ast.arg(arg=a) for a in names]
                defaults = [ast.Name(id=a, ctx=ast.Load()) for a in names]
            return ast.FunctionDef(
                name=name, args=ast.arguments(
                    posonlyargs=[], args=params, kwonlyargs=[],
                    kw_defaults=[], defaults=defaults),
                body=stmts, decorator_list=[], type_params=[])

        tfn = branch(f"__dy2st_true_{n}", body)
        ffn = branch(f"__dy2st_false_{n}", orelse)
        call = ast.Call(
            func=ast.Name(id="__dy2st_convert_ifelse", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=tfn.name, ctx=ast.Load()),
                  ast.Name(id=ffn.name, ctx=ast.Load()),
                  ast.Constant(value=self._loc(node))],
            keywords=[])
        return [tfn, ffn], call

    # -- while ---------------------------------------------------------------
    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        loc = self._loc(node)
        bad = _has(node.body, (ast.Break, ast.Continue, ast.Return))
        if bad is not None:
            kind = type(bad).__name__.lower()
            return self._guard_test(
                node,
                f"`{kind}` (line {bad.lineno}) inside a data-dependent "
                "`while` is not convertible to lax.while_loop; fold the "
                "exit condition into the loop predicate")
        if node.orelse:
            return self._guard_test(
                node, "`while ... else` is not convertible")
        # carry = names the body assigns; loop-invariant reads (modules,
        # helper fns, constants) stay closure-captured
        carried = sorted(_store_names(node.body))
        n = self.counter
        self.counter += 1

        def loads():
            return ast.Tuple(
                elts=[ast.Name(id=a, ctx=ast.Load()) for a in carried],
                ctx=ast.Load())

        carry_tuple_s = ast.Tuple(
            elts=[ast.Name(id=a, ctx=ast.Store()) for a in carried],
            ctx=ast.Store())
        def arg():
            return ast.arguments(
                posonlyargs=[], args=[ast.arg(arg="__dy2st_carry")],
                kwonlyargs=[], kw_defaults=[], defaults=[])

        def unpack():
            return ast.Assign(
                targets=[ast.Tuple(
                    elts=[ast.Name(id=a, ctx=ast.Store()) for a in carried],
                    ctx=ast.Store())],
                value=ast.Name(id="__dy2st_carry", ctx=ast.Load()))

        cond_fn = ast.FunctionDef(
            name=f"__dy2st_cond_{n}", args=arg(),
            body=[unpack(), ast.Return(value=node.test)],
            decorator_list=[], type_params=[])
        body_fn = ast.FunctionDef(
            name=f"__dy2st_body_{n}", args=arg(),
            body=[unpack()] + list(node.body)
            + [ast.Return(value=loads())],
            decorator_list=[], type_params=[])
        call = ast.Call(
            func=ast.Name(id="__dy2st_convert_while", ctx=ast.Load()),
            args=[ast.Name(id=cond_fn.name, ctx=ast.Load()),
                  ast.Name(id=body_fn.name, ctx=ast.Load()),
                  loads(),
                  ast.Constant(value=loc)],
            keywords=[])
        assign = ast.Assign(targets=[carry_tuple_s], value=call)
        return [ast.copy_location(s, node)
                for s in (self._undef_preamble(carried)
                          + [cond_fn, body_fn, assign]
                          + self._undef_cleanup(carried))]


def convert_control_flow(fn: Callable) -> Callable:
    """AST-rewrite ``fn`` so tensor-predicated if/while lower to
    lax.cond/lax.while_loop at trace time (and run unchanged eagerly).

    Returns ``fn`` unmodified (with a warning) when its source is
    unavailable (builtins, C extensions, REPL-defined lambdas).
    """
    if inspect.ismethod(fn):
        conv = convert_control_flow(fn.__func__)
        if conv is fn.__func__:
            return fn
        return types.MethodType(conv, fn.__self__)
    # operate on the innermost function of a wraps-style decorator chain:
    # its source carries the decorator lines, and its closure/globals are
    # the ones the rewritten body must see (ADVICE r3 #5)
    orig = fn
    fn = inspect.unwrap(fn)
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        filename = inspect.getsourcefile(fn) or "<dy2static>"
    except (OSError, TypeError):
        warnings.warn(
            f"dy2static: source of {getattr(fn, '__name__', fn)!r} is "
            "unavailable; data-dependent control flow will fail under jit",
            stacklevel=2)
        return orig
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return orig
    if _has(fdef.body, (ast.If, ast.While)) is None:
        return orig  # nothing to rewrite
    # Decorators are NEVER re-executed (re-exec'ing decorator source would
    # re-run registration side effects, recurse through aliased to_static,
    # and NameError on def-time-local arguments). Wrapper behavior from
    # decorators BELOW the conversion entry is preserved instead by
    # re-binding the live wrapper chain's closure cell onto the converted
    # function after the rewrite — see the `orig is not fn` tail
    # (ADVICE r3 #5).
    fdef.decorator_list = []
    new_tree = _RewriteControlFlow(filename).visit(tree)
    ast.fix_missing_locations(new_tree)
    glb = dict(fn.__globals__)
    glb["__dy2st_convert_ifelse"] = convert_ifelse
    glb["__dy2st_convert_while"] = convert_while
    glb["__dy2st_check_unconvertible"] = check_unconvertible
    glb["__dy2st_UNDEFINED"] = UNDEFINED
    freevars = fn.__code__.co_freevars
    if freevars:
        # re-bind the original closure: wrap the rewritten def in a factory
        # taking the free variables as parameters (their CURRENT cell values
        # are snapshotted at conversion time)
        factory = ast.FunctionDef(
            name="__dy2st_factory",
            args=ast.arguments(
                posonlyargs=[], args=[ast.arg(arg=v) for v in freevars],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[new_tree.body[0],
                  ast.Return(value=ast.Name(id=fdef.name, ctx=ast.Load()))],
            decorator_list=[], type_params=[])
        new_tree = ast.Module(body=[factory], type_ignores=[])
        ast.fix_missing_locations(new_tree)
        code = compile(new_tree, filename, "exec")
        loc: dict = {}
        exec(code, glb, loc)
        cells = [c.cell_contents for c in (fn.__closure__ or ())]
        new_fn = loc["__dy2st_factory"](*cells)
    else:
        code = compile(new_tree, filename, "exec")
        loc = {}
        exec(code, glb, loc)
        new_fn = loc[fdef.name]
    new_fn = functools.wraps(fn)(new_fn)
    new_fn.__dy2st_source__ = fn
    if orig is not fn:
        # ``orig`` is a live wraps-style wrapper chain around ``fn`` (user
        # decorators below the conversion entry). Preserve their per-call
        # behavior by pointing the wrapper that calls ``fn`` at the
        # converted function: find its closure cell holding ``fn`` and
        # re-bind it. The converted body is semantically identical eagerly,
        # so mutating the shared cell is safe. If no such cell exists (the
        # decorator stashed ``fn`` somewhere opaque), warn — never drop
        # silently (ADVICE r3 #5).
        link = orig
        while link is not None and link is not fn:
            for cell in (getattr(link, "__closure__", None) or ()):
                try:
                    held = cell.cell_contents
                except ValueError:   # empty cell
                    continue
                # match the raw fn OR a previous conversion of it, so
                # converting the same decorated function twice stays
                # idempotent instead of spuriously warning
                if held is fn or getattr(held, "__dy2st_source__",
                                         None) is fn:
                    cell.cell_contents = new_fn
                    return orig
            link = getattr(link, "__wrapped__", None)
        warnings.warn(
            f"dy2static: {getattr(orig, '__name__', orig)!r} is wrapped by "
            "a decorator whose reference to the original function cannot "
            "be re-bound; the decorator's per-call behavior is dropped "
            "from the converted path (the original object keeps it)",
            stacklevel=2)
    return new_fn
