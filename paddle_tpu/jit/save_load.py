"""``paddle.jit.save/load`` parity: serialized inference programs.

Reference: python/paddle/jit/api.py — ``jit.save`` lowers a to_static Layer
into a serialized inference Program (``.pdmodel``) + parameters
(``.pdiparams``); ``jit.load`` returns a TranslatedLayer
(SURVEY.md §2.5 dy2static row, §3.5 inference).

TPU-native: the serialized program format is **StableHLO** via
``jax.export`` — the exact artifact XLA consumes — instead of ProgramDesc
protobuf. Parameters ride in an ``.npz``; a small JSON carries input/output
metadata. The triple keeps the reference's file-extension convention.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer
from .functional import bind, buffer_arrays, param_arrays, tree_unwrap


from ..static import InputSpec  # noqa: E402  (re-export parity)
from ..core.compat import jax_export


def _as_sds(spec) -> jax.ShapeDtypeStruct:
    if isinstance(spec, jax.ShapeDtypeStruct):
        return spec
    if isinstance(spec, InputSpec):
        shape = tuple(1 if d is None or int(d) < 0 else int(d)
                      for d in spec.shape)
        return jax.ShapeDtypeStruct(shape, np.dtype(spec.dtype))
    v = spec._value if isinstance(spec, Tensor) else spec
    v = v if hasattr(v, "dtype") else np.asarray(v)
    return jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)


def save(layer, path: str, input_spec: Optional[List] = None, **config) -> None:
    """Serialize ``layer``'s forward as StableHLO + params.

    ``input_spec``: list of InputSpec/ShapeDtypeStruct/example arrays. For a
    Layer whose forward was wrapped by ``to_static``, the underlying function
    is used; plain Layers are traced directly.
    """
    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects an nn.Layer")
    if not input_spec:
        raise ValueError("jit.save requires input_spec (shapes to trace)")
    layer.eval()
    params = param_arrays(layer)
    buffers = buffer_arrays(layer)

    def pure(params_d, buffers_d, *xs):
        with bind(layer, params_d, buffers_d):
            out = layer(*[Tensor(x) for x in xs])
        return tree_unwrap(out)

    in_sds = [_as_sds(s) for s in input_spec]
    p_sds = {k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in params.items()}
    b_sds = {k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in buffers.items()}
    exported = jax_export().export(jax.jit(pure))(p_sds, b_sds, *in_sds)

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    np.savez(path + ".pdiparams",
             **{f"p::{k}": np.asarray(v) for k, v in params.items()},
             **{f"b::{k}": np.asarray(v) for k, v in buffers.items()})
    meta = {
        "inputs": [{"shape": list(s.shape), "dtype": str(np.dtype(s.dtype))}
                   for s in in_sds],
        "format": "stablehlo+npz",
        "version": 1,
    }
    with open(path + ".json", "w") as f:
        json.dump(meta, f)


class TranslatedLayer:
    """Loaded inference program (parity: paddle.jit.TranslatedLayer)."""

    def __init__(self, exported, params: Dict[str, Any],
                 buffers: Dict[str, Any], meta: Dict[str, Any]):
        self._exported = exported
        self._params = params
        self._buffers = buffers
        self._meta = meta

    @property
    def input_spec(self) -> List[InputSpec]:
        return [InputSpec(m["shape"], m["dtype"]) for m in self._meta["inputs"]]

    @property
    def n_outputs(self) -> int:
        return len(self._exported.out_avals)

    def __call__(self, *args):
        xs = [a._value if isinstance(a, Tensor) else np.asarray(a)
              for a in args]
        out = self._exported.call(self._params, self._buffers, *xs)
        if isinstance(out, (tuple, list)):
            return tuple(Tensor(o) for o in out)
        return Tensor(out)

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is an inference program")


def load(path: str) -> TranslatedLayer:
    with open(path + ".pdmodel", "rb") as f:
        exported = jax_export().deserialize(f.read())
    data = np.load(path + ".pdiparams.npz")
    params = {k[3:]: data[k] for k in data.files if k.startswith("p::")}
    buffers = {k[3:]: data[k] for k in data.files if k.startswith("b::")}
    with open(path + ".json") as f:
        meta = json.load(f)
    return TranslatedLayer(exported, params, buffers, meta)
