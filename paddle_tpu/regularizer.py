"""``paddle_tpu.regularizer`` — L1Decay / L2Decay.

Parity with python/paddle/regularizer.py of the reference. Optimizers
already read the ``coeff`` off these objects (optimizer.Optimizer.
_parse_wd); L2Decay maps onto the decoupled weight-decay the fused
update applies. L1Decay carries its coeff for the grad-penalty form —
apply it through the loss (``coeff * sum(|w|)``) or an optimizer that
reads ``regularization``; the decoupled path warns that it decays
L2-style if handed an L1 object.
"""

from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class L2Decay:
    """Weight decay ``coeff * w`` (the decoupled form every optimizer
    here implements)."""

    def __init__(self, coeff: float = 0.0):
        self.coeff = float(coeff)
        self._regularization_coeff = float(coeff)

    def __repr__(self):
        return f"L2Decay(coeff={self.coeff})"


class L1Decay:
    """L1 regularization ``coeff * sign(w)``. Kept for API parity; the
    built-in fused optimizers implement decoupled (L2-style) decay, so
    pass the penalty through the loss for true L1:
    ``loss + coeff * sum(abs(w))``."""

    def __init__(self, coeff: float = 0.0):
        self.coeff = float(coeff)
        self._regularization_coeff = float(coeff)

    def __repr__(self):
        return f"L1Decay(coeff={self.coeff})"
