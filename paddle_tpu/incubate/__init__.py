"""``paddle_tpu.incubate`` — fused layers and MoE (reference:
python/paddle/incubate/)."""

from . import autograd  # noqa: F401
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
