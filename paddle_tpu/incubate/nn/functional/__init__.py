"""Functional surface for the fused layers (reference:
python/paddle/incubate/nn/functional/fused_transformer.py:§0)."""

from __future__ import annotations

from ....core.dispatch import apply
from ....ops import fused_transformer_block as ftb
from ....ops.rms_norm import rms_norm_array
from ....ops.fused_linear import fused_linear_param_grad_add  # noqa: F401


def fused_multi_transformer(x, params, *, num_heads, activation="gelu",
                            epsilon=1e-5, attn_mask=None, cache_kvs=None,
                            time_step=None, max_cache_len=None, seq_lens=None):
    """Tensor-level entry for the fused decoder stack
    (ops/fused_transformer_block.py). Mirrors
    paddle.incubate.nn.functional.fused_multi_transformer:§0; the layer loop
    is a scanned XLA computation rather than a CUDA megakernel."""
    tensors = [x]
    keys = sorted(params)
    tensors += [params[k] for k in keys]
    if cache_kvs is not None:
        tensors.append(cache_kvs)

    def fn(xv, *rest):
        pv = dict(zip(keys, rest[:len(keys)]))
        cache = rest[len(keys)] if cache_kvs is not None else None
        out, kv = ftb.fused_multi_transformer_array(
            xv, pv, num_heads=num_heads, act=activation, epsilon=epsilon,
            attn_mask=attn_mask, cache_kv=cache, time_step=time_step,
            max_cache_len=max_cache_len, seq_lens=seq_lens)
        return out if kv is None else (out, kv)

    return apply(fn, *tensors, op_name="fused_multi_transformer")


def fused_rms_norm(x, weight, epsilon=1e-6):
    """paddle.incubate.nn.functional.fused_rms_norm:§0 parity (Pallas kernel
    in ops/rms_norm.py)."""
    return apply(lambda xv, wv: rms_norm_array(xv, wv, epsilon), x, weight,
                 op_name="fused_rms_norm")


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    """paddle.incubate.nn.functional.fused_rotary_position_embedding:§0 —
    reference argument ORDER is (q, k, v, sin, cos, position_ids,
    use_neox_rotary_style); returns a (q, k, v) tuple with None for absent
    inputs. Only the neox (rotate-half) style is implemented; the GPT-J
    interleaved style raises rather than rotating wrongly."""
    if not use_neox_rotary_style:
        raise NotImplementedError(
            "use_neox_rotary_style=False (GPT-J interleaved rotation) is "
            "not implemented; only the rotate-half (neox) style is")
    if sin is None or cos is None:
        raise ValueError("sin and cos caches are required")
    from ....core.tensor import Tensor
    from ....ops import rope as _rope

    cos_v = cos._value if isinstance(cos, Tensor) else cos
    sin_v = sin._value if isinstance(sin, Tensor) else sin
    if cos_v.ndim == 4:  # paddle caches are (1, S, 1, D)
        cos_v = cos_v[0, :, 0, :]
        sin_v = sin_v[0, :, 0, :]
    if position_ids is not None:
        pid = position_ids._value if isinstance(position_ids, Tensor) \
            else position_ids
        cos_v = cos_v[pid]  # (B, S, D)
        sin_v = sin_v[pid]

    def rot_pair(a, b):  # one dispatch + one tape record for the pair
        return apply(lambda av, bv: _rope.apply_rope_array(av, bv, cos_v,
                                                           sin_v),
                     a, b, op_name="fused_rope")

    def rot_one(t):
        if t is None:
            return None
        return apply(lambda av: _rope.apply_rope_array(av, av, cos_v,
                                                       sin_v)[0],
                     t, op_name="fused_rope")

    if q is not None and k is not None:
        qo, ko = rot_pair(q, k)
        return qo, ko, rot_one(v)
    return rot_one(q), rot_one(k), rot_one(v)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True, mode="upscale_in_train",
                                           name=None):
    """Reference CUDA fused epilogue (paddle/fluid/operators/fused/
    fused_bias_dropout_residual_layer_norm_op.cu:§0); kwarg names and the
    dropout_rate=0.5 default follow the reference API. On TPU this is one
    jitted expression — XLA fuses the chain; the LN numerics are the shared
    fp32-accumulated ``layer_norm_array`` (SURVEY §2.2 'other fused family'
    row)."""
    import jax
    import jax.numpy as jnp
    from .... import random as _random

    drop = dropout_rate if training else 0.0
    key = _random.next_key() if drop > 0.0 else None
    tensors = [t for t in (x, residual, bias, ln_scale, ln_bias)
               if t is not None]
    has = [t is not None for t in (bias, ln_scale, ln_bias)]

    def fn(xv, rv, *rest):
        it = iter(rest)
        b = next(it) if has[0] else None
        g = next(it) if has[1] else None
        be = next(it) if has[2] else None
        y = xv if b is None else xv + b
        if drop > 0.0:
            keep = jax.random.bernoulli(key, 1.0 - drop, y.shape)
            if mode == "downscale_in_infer":
                y = jnp.where(keep, y, 0.0)  # no rescale in train
            else:  # upscale_in_train
                y = jnp.where(keep, y / (1.0 - drop), 0.0)
        elif not training and dropout_rate > 0.0 and \
                mode == "downscale_in_infer":
            y = y * (1.0 - dropout_rate)
        return ftb.layer_norm_array(y + rv, g, be, ln_epsilon)

    return apply(fn, *tensors, op_name="fused_bias_dropout_residual_ln")


def _swap_last2(a):
    import jax.numpy as jnp
    return jnp.swapaxes(a, -1, -2)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """paddle.incubate.nn.functional.fused_linear:§0 (cublasLt gemm epilogue
    → one XLA dot+add, MXU-fused). Same computation as fused_matmul_bias
    with transpose_y."""
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """paddle.incubate.nn.functional.fused_matmul_bias:§0. Transposes swap
    the last two dims only (paddle matmul semantics for batched inputs)."""
    import jax.numpy as jnp

    def fn(xv, yv, *rest):
        a = _swap_last2(xv) if transpose_x else xv
        b = _swap_last2(yv) if transpose_y else yv
        out = jnp.matmul(a, b)
        return out + rest[0] if rest else out

    args = (x, y) if bias is None else (x, y, bias)
    return apply(fn, *args, op_name="fused_matmul_bias")


def fused_softmax_mask(x, mask, scale=1.0):
    """Reference fused_softmax_mask CUDA kernel:§0 — scale, add mask,
    softmax in fp32, one fused XLA expression."""
    import jax
    import jax.numpy as jnp

    def fn(xv, mv):
        s = xv.astype(jnp.float32) * scale + mv.astype(jnp.float32)
        return jax.nn.softmax(s, axis=-1).astype(xv.dtype)

    return apply(fn, x, mask, op_name="fused_softmax_mask")


def fused_softmax_mask_upper_triangle(x, scale=1.0):
    """Reference fused_softmax_mask_upper_triangle:§0 — causal-masked
    softmax without materialising the mask input."""
    import jax
    import jax.numpy as jnp

    def fn(xv):
        sq, sk = xv.shape[-2], xv.shape[-1]
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(causal, xv.astype(jnp.float32) * scale, -1e30)
        return jax.nn.softmax(s, axis=-1).astype(xv.dtype)

    return apply(fn, x, op_name="fused_softmax_mask_upper_triangle")
