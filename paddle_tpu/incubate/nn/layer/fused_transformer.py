"""Fused transformer layers — API parity with
python/paddle/incubate/nn/layer/fused_transformer.py:§0
(``FusedMultiTransformer``, ``FusedMultiHeadAttention``, ``FusedFeedForward``).

The compute goes through ops/fused_transformer_block.py: the whole decoder
stack runs as ONE scanned XLA computation (flash-attention prefill, cached
decode via ``time_step``), the TPU-native equivalent of the reference's
``fused_multi_transformer`` CUDA megakernel.

Weight layout note: the reference stores qkv as ``[3, num_heads, head_dim,
embed_dim]`` (``trans_qkvw``); here the idiomatic-XLA layout ``[embed_dim,
3*embed_dim]`` is used so the QKV projection is one MXU-friendly GEMM.
Parameter *names* keep the reference scheme (``qkv_weights`` list etc.).
"""

from __future__ import annotations

from ....nn.layer import Layer
from ....nn import initializer as I
from ....core.dispatch import apply
from ....ops import fused_transformer_block as ftb


def _run_stacked_block(layer, order, src, attn_mask, caches, time_step,
                       gen_cache_len, seq_lens, extra_consts=None,
                       int8=False, op_name="fused_multi_transformer"):
    """Shared forward plumbing for the float and int8 stacks: flatten the
    per-layer Parameter lists through `apply` (tape records each), stack
    per key inside the traced fn, run the scanned block op."""
    import jax.numpy as jnp

    L = layer.num_layers
    flat = [src]
    for _, plist in order:
        flat.extend(plist)
    mask = attn_mask._value if hasattr(attn_mask, "_value") else attn_mask
    cache = caches._value if hasattr(caches, "_value") else caches
    lens = seq_lens._value if hasattr(seq_lens, "_value") else seq_lens

    def fn(xv, *pv):
        d = {}
        for idx, (key, _) in enumerate(order):
            d[key] = jnp.stack(pv[idx * L:(idx + 1) * L])
        if extra_consts:
            d.update(extra_consts)
        out, kv = ftb.fused_multi_transformer_array(
            xv, d, num_heads=layer.num_heads, act=layer.activation,
            epsilon=layer.epsilon, attn_mask=mask, cache_kv=cache,
            time_step=time_step, max_cache_len=gen_cache_len,
            seq_lens=lens, int8=int8)
        if kv is None:
            return out
        return out, kv

    return apply(fn, *flat, op_name=op_name)


class FusedMultiTransformer(Layer):
    """Stack of ``num_layers`` pre-LN decoder layers, fused end-to-end.

    forward(src, attn_mask=None, caches=None, time_step=None) — matches the
    reference layer's surface: prefill when ``time_step`` is None (optionally
    materialising a KV cache when ``caches``/``gen_cache_len`` is given),
    single-token decode when ``time_step`` is an int.
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 epsilon=1e-5, num_layers=1, name=None):
        super().__init__()
        if not normalize_before:
            raise NotImplementedError(
                "post-LN fused stack not supported (reference default is pre-LN)")
        if embed_dim % num_heads:
            raise ValueError("num_heads must divide embed_dim")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.dim_feedforward = dim_feedforward
        self.activation = activation
        self.epsilon = epsilon
        self.num_layers = num_layers
        self.dropout_rate = dropout_rate

        names = ("ln_scales", "ln_biases", "qkv_weights", "qkv_biases",
                 "linear_weights", "linear_biases", "ffn_ln_scales",
                 "ffn_ln_biases", "ffn1_weights", "ffn1_biases",
                 "ffn2_weights", "ffn2_biases")
        for n in names:
            object.__setattr__(self, n, [])
        H, F = embed_dim, dim_feedforward
        shapes = {
            "ln_scales": (H,), "ln_biases": (H,),
            "qkv_weights": (H, 3 * H), "qkv_biases": (3 * H,),
            "linear_weights": (H, H), "linear_biases": (H,),
            "ffn_ln_scales": (H,), "ffn_ln_biases": (H,),
            "ffn1_weights": (H, F), "ffn1_biases": (F,),
            "ffn2_weights": (F, H), "ffn2_biases": (H,),
        }
        for i in range(num_layers):
            for n in names:
                is_scale = n.endswith("scales")
                is_bias = n.endswith("biases")
                init = (I.Constant(1.0) if is_scale else
                        I.Constant(0.0) if is_bias else I.XavierUniform())
                p = self.create_parameter(shapes[n], is_bias=is_bias,
                                          default_initializer=init)
                self.add_parameter(f"{n}.{i}", p)
                getattr(self, n).append(p)

    _STACK_KEYS = (
        ("ln_scale", "ln_scales"), ("ln_bias", "ln_biases"),
        ("qkv_w", "qkv_weights"), ("qkv_b", "qkv_biases"),
        ("out_w", "linear_weights"), ("out_b", "linear_biases"),
        ("ffn_ln_scale", "ffn_ln_scales"), ("ffn_ln_bias", "ffn_ln_biases"),
        ("ffn1_w", "ffn1_weights"), ("ffn1_b", "ffn1_biases"),
        ("ffn2_w", "ffn2_weights"), ("ffn2_b", "ffn2_biases"),
    )

    def forward(self, src, attn_mask=None, caches=None, time_step=None,
                gen_cache_len=None, seq_lens=None):
        order = [(key, getattr(self, attr)) for key, attr in self._STACK_KEYS]
        return _run_stacked_block(self, order, src, attn_mask, caches,
                                  time_step, gen_cache_len, seq_lens)


class FusedMultiHeadAttention(Layer):
    """Pre-LN self-attention block with residual — reference
    python/paddle/incubate/nn/layer/fused_transformer.py:§0
    (``FusedMultiHeadAttention``). Runs as one fused XLA computation (flash
    attention on TPU)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.0,
                 attn_dropout_rate=0.0, normalize_before=True, epsilon=1e-5,
                 name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError("num_heads must divide embed_dim")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        H = embed_dim
        self.pre_ln_scale = self.create_parameter(
            (H,), default_initializer=I.Constant(1.0))
        self.pre_ln_bias = self.create_parameter((H,), is_bias=True)
        self.qkv_weight = self.create_parameter((H, 3 * H))
        self.qkv_bias = self.create_parameter((3 * H,), is_bias=True)
        self.linear_weight = self.create_parameter((H, H))
        self.linear_bias = self.create_parameter((H,), is_bias=True)
        self.ln_scale = self.create_parameter(
            (H,), default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter((H,), is_bias=True)

    def forward(self, x, attn_mask=None, causal=True, seg_ids=None):
        """``seg_ids`` (B, S) int32 enables the sequence-packed mode:
        tokens attend only within their own segment (negative = padding),
        via the segment-masked Pallas flash kernel — the encoder-packing
        path the reference reaches through flash_attn_varlen glue
        (paddle/phi/kernels/gpu/flash_attn_kernel.cu:§0)."""
        mask = attn_mask._value if hasattr(attn_mask, "_value") else attn_mask
        seg = seg_ids._value if hasattr(seg_ids, "_value") else seg_ids
        nh = self.num_heads
        eps = self.epsilon
        pre = self.normalize_before

        def fn(xv, pls, plb, qkvw, qkvb, ow, ob, lns, lnb):
            b, s, h = xv.shape
            xn = ftb.layer_norm_array(xv, pls, plb, eps) if pre else xv
            qkv = xn @ qkvw + qkvb
            q, k, v = ftb._split_heads(qkv, nh)
            attn = ftb._prefill_attention(q, k, v, mask, causal=causal,
                                          seg_ids=seg)
            attn = attn.transpose(0, 2, 1, 3).reshape(b, s, h)
            y = xv + (attn @ ow + ob).astype(xv.dtype)
            if not pre:
                y = ftb.layer_norm_array(y, lns, lnb, eps)
            return y

        return apply(fn, x, self.pre_ln_scale, self.pre_ln_bias,
                     self.qkv_weight, self.qkv_bias, self.linear_weight,
                     self.linear_bias, self.ln_scale, self.ln_bias,
                     op_name="fused_multi_head_attention")


class FusedFeedForward(Layer):
    """Pre-LN FFN block with residual — reference ``FusedFeedForward``
    (python/paddle/incubate/nn/layer/fused_transformer.py:§0)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.0,
                 activation="relu", normalize_before=True, epsilon=1e-5,
                 name=None):
        super().__init__()
        self.d_model = d_model
        self.dim_feedforward = dim_feedforward
        self.activation = activation
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        self.ln_scale = self.create_parameter(
            (d_model,), default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter((d_model,), is_bias=True)
        self.w1 = self.create_parameter((d_model, dim_feedforward))
        self.b1 = self.create_parameter((dim_feedforward,), is_bias=True)
        self.w2 = self.create_parameter((dim_feedforward, d_model))
        self.b2 = self.create_parameter((d_model,), is_bias=True)

    def forward(self, x):
        eps = self.epsilon
        act = ftb._ACTS[self.activation]
        pre = self.normalize_before

        def fn(xv, lns, lnb, w1, b1, w2, b2):
            xn = ftb.layer_norm_array(xv, lns, lnb, eps) if pre else xv
            y = xv + (act(xn @ w1 + b1) @ w2 + b2).astype(xv.dtype)
            if not pre:
                y = ftb.layer_norm_array(y, lns, lnb, eps)
            return y

        return apply(fn, x, self.ln_scale, self.ln_bias, self.w1, self.b1,
                     self.w2, self.b2, op_name="fused_feedforward")


class FusedMultiTransformerInt8(Layer):
    """A8W8 fused decoder stack — the reference's int8 fused encoder
    (paddle/fluid/operators/fused/fused_multi_transformer_int8_op.cu:§0,
    paddle.incubate.nn.FusedMultiTransformerInt8).

    Weights are stored int8 with per-output-channel scales; the four
    projection matmuls quantize their activations (per-token dynamic amax,
    or the calibrated ``*_in_scale`` lists when provided) and run
    int8×int8→int32 on the MXU — the TPU's int8 path doubles matmul peak
    over bf16, which is where the reference CUDA kernel's win comes from
    too. Build from a trained float stack with :meth:`from_float`.
    """

    _WKEYS = ("qkv", "linear", "ffn1", "ffn2")

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 epsilon=1e-5, num_layers=1, qkv_in_scale=None,
                 linear_in_scale=None, ffn1_in_scale=None,
                 ffn2_in_scale=None, name=None):
        super().__init__()
        if not normalize_before:
            raise NotImplementedError("post-LN int8 stack not supported")
        if embed_dim % num_heads:
            raise ValueError("num_heads must divide embed_dim")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.dim_feedforward = dim_feedforward
        self.activation = activation
        self.epsilon = epsilon
        self.num_layers = num_layers
        self.in_scales = {"qkv": qkv_in_scale, "linear": linear_in_scale,
                          "ffn1": ffn1_in_scale, "ffn2": ffn2_in_scale}
        H, F = embed_dim, dim_feedforward
        wshapes = {"qkv": (H, 3 * H), "linear": (H, H),
                   "ffn1": (H, F), "ffn2": (F, H)}
        bshapes = {"qkv": (3 * H,), "linear": (H,),
                   "ffn1": (F,), "ffn2": (H,)}
        import jax.numpy as jnp

        names = ("ln_scales", "ln_biases", "ffn_ln_scales", "ffn_ln_biases")
        for n in names:
            object.__setattr__(self, n, [])
        for wk in self._WKEYS:
            object.__setattr__(self, f"{wk}_weights", [])
            object.__setattr__(self, f"{wk}_scales", [])
            object.__setattr__(self, f"{wk}_biases", [])
        for i in range(num_layers):
            for n in names:
                init = I.Constant(1.0) if n.endswith("scales") else I.Constant(0.0)
                p = self.create_parameter((H,), is_bias=n.endswith("biases"),
                                          default_initializer=init)
                self.add_parameter(f"{n}.{i}", p)
                getattr(self, n).append(p)
            for wk in self._WKEYS:
                q = self.create_parameter(wshapes[wk],
                                          default_initializer=I.Constant(0.0))
                q._value = jnp.zeros(wshapes[wk], jnp.int8)
                q.trainable = False
                q.stop_gradient = True
                s = self.create_parameter((wshapes[wk][1],),
                                          default_initializer=I.Constant(1.0))
                s.trainable = False
                s.stop_gradient = True
                b = self.create_parameter(bshapes[wk], is_bias=True,
                                          default_initializer=I.Constant(0.0))
                self.add_parameter(f"{wk}_w_q.{i}", q)
                self.add_parameter(f"{wk}_w_scale.{i}", s)
                self.add_parameter(f"{wk}_b.{i}", b)
                getattr(self, f"{wk}_weights").append(q)
                getattr(self, f"{wk}_scales").append(s)
                getattr(self, f"{wk}_biases").append(b)

    @classmethod
    def from_float(cls, float_stack: "FusedMultiTransformer",
                   qkv_in_scale=None, linear_in_scale=None,
                   ffn1_in_scale=None, ffn2_in_scale=None):
        """Quantize a trained FusedMultiTransformer's projection weights."""
        from ....quantization import weight_quantize

        m = cls(float_stack.embed_dim, float_stack.num_heads,
                float_stack.dim_feedforward,
                activation=float_stack.activation,
                epsilon=float_stack.epsilon,
                num_layers=float_stack.num_layers,
                qkv_in_scale=qkv_in_scale, linear_in_scale=linear_in_scale,
                ffn1_in_scale=ffn1_in_scale, ffn2_in_scale=ffn2_in_scale)
        src_w = {"qkv": float_stack.qkv_weights,
                 "linear": float_stack.linear_weights,
                 "ffn1": float_stack.ffn1_weights,
                 "ffn2": float_stack.ffn2_weights}
        src_b = {"qkv": float_stack.qkv_biases,
                 "linear": float_stack.linear_biases,
                 "ffn1": float_stack.ffn1_biases,
                 "ffn2": float_stack.ffn2_biases}
        for i in range(m.num_layers):
            m.ln_scales[i]._value = float_stack.ln_scales[i]._value
            m.ln_biases[i]._value = float_stack.ln_biases[i]._value
            m.ffn_ln_scales[i]._value = float_stack.ffn_ln_scales[i]._value
            m.ffn_ln_biases[i]._value = float_stack.ffn_ln_biases[i]._value
            for wk in cls._WKEYS:
                q, s = weight_quantize(src_w[wk][i]._value)
                getattr(m, f"{wk}_weights")[i]._value = q
                getattr(m, f"{wk}_scales")[i]._value = s
                getattr(m, f"{wk}_biases")[i]._value = src_b[wk][i]._value
        return m

    def forward(self, src, attn_mask=None, caches=None, time_step=None,
                gen_cache_len=None, seq_lens=None):
        import jax.numpy as jnp
        order = [("ln_scale", self.ln_scales), ("ln_bias", self.ln_biases),
                 ("ffn_ln_scale", self.ffn_ln_scales),
                 ("ffn_ln_bias", self.ffn_ln_biases)]
        # block-op key names: qkv_w/out_w/ffn1_w/ffn2_w (+_q/_scale) —
        # 'linear' in the public attr names maps to 'out' inside the op
        opname = {"qkv": "qkv_w", "linear": "out_w", "ffn1": "ffn1_w",
                  "ffn2": "ffn2_w"}
        opbias = {"qkv": "qkv_b", "linear": "out_b", "ffn1": "ffn1_b",
                  "ffn2": "ffn2_b"}
        for wk in self._WKEYS:
            order.append((opname[wk] + "_q", getattr(self, f"{wk}_weights")))
            order.append((opname[wk] + "_scale",
                          getattr(self, f"{wk}_scales")))
            order.append((opbias[wk], getattr(self, f"{wk}_biases")))
        in_scales = {opname[wk] + "_in_scale":
                     jnp.asarray(self.in_scales[wk], jnp.float32)
                     for wk in self._WKEYS
                     if self.in_scales[wk] is not None}
        return _run_stacked_block(self, order, src, attn_mask, caches,
                                  time_step, gen_cache_len, seq_lens,
                                  extra_consts=in_scales, int8=True,
                                  op_name="fused_multi_transformer_int8")
