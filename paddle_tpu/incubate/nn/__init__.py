"""``paddle_tpu.incubate.nn`` — fused layers (reference:
python/paddle/incubate/nn/layer/fused_transformer.py:§0, SURVEY.md §2.5
"incubate fused layers")."""

from .layer.fused_transformer import (  # noqa: F401
    FusedMultiTransformer, FusedMultiTransformerInt8,
    FusedMultiHeadAttention, FusedFeedForward,
)
from . import functional  # noqa: F401
