"""``paddle_tpu.incubate.autograd`` — functional higher-order autograd.

Parity with python/paddle/incubate/autograd/ of the reference (jvp, vjp,
Jacobian, Hessian — SURVEY.md §2.1 eager autograd row). The reference
builds these over dygraph double-grad; here each one IS the matching jax
transform (jvp/vjp/jacrev/jacfwd/hessian), so arbitrary order composes
for free and everything jits.

Functions take a callable ``func`` over Tensors (or jax arrays) and
Tensor inputs; outputs are Tensors.
"""

from __future__ import annotations

from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "jacobian", "hessian"]


def _unwrap(v):
    return v._value if isinstance(v, Tensor) else jnp.asarray(v)


def _wrap(v):
    return Tensor(v, stop_gradient=True)


def _as_tuple(xs):
    if isinstance(xs, (list, tuple)):
        return tuple(xs), True
    return (xs,), False


def _lift(func: Callable) -> Callable:
    """Lift a Tensor->Tensor function to jax arrays -> jax arrays."""

    def jf(*args):
        outs = func(*[_wrap(a) for a in args])
        if isinstance(outs, (list, tuple)):
            return tuple(_unwrap(o) for o in outs)
        return _unwrap(outs)

    return jf


def jvp(func: Callable, xs, v=None):
    """Forward-mode jacobian-vector product: returns ``(func(xs),
    J·v)``. ``v`` defaults to ones like ``xs`` (reference behaviour)."""
    xs_t, was_seq = _as_tuple(xs)
    primals = tuple(_unwrap(x) for x in xs_t)
    if v is None:
        tangents = tuple(jnp.ones_like(p) for p in primals)
    else:
        v_t, _ = _as_tuple(v)
        tangents = tuple(_unwrap(t) for t in v_t)
    out, tan = jax.jvp(_lift(func), primals, tangents)
    if isinstance(out, tuple):
        return [_wrap(o) for o in out], [_wrap(t) for t in tan]
    return _wrap(out), _wrap(tan)


def vjp(func: Callable, xs, v=None):
    """Reverse-mode vector-jacobian product: returns ``(func(xs),
    vᵀ·J)``. ``v`` defaults to ones like the output."""
    xs_t, was_seq = _as_tuple(xs)
    primals = tuple(_unwrap(x) for x in xs_t)
    out, vjp_fn = jax.vjp(_lift(func), *primals)
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        if isinstance(out, tuple):
            v_t, _ = _as_tuple(v)
            cot = tuple(_unwrap(t) for t in v_t)
        else:
            cot = _unwrap(v if not isinstance(v, (list, tuple)) else v[0])
    grads = vjp_fn(cot)
    outs = [_wrap(o) for o in out] if isinstance(out, tuple) else _wrap(out)
    gs = [_wrap(g) for g in grads]
    return outs, (gs if was_seq else gs[0])


class Jacobian:
    """Dense jacobian of ``func`` at ``xs`` (reference
    incubate.autograd.Jacobian). Computed with ``jax.jacrev`` on first
    access; supports indexing/slicing like the reference's lazy object.

    For single in/out: shape (ys_size, xs_size) flattened over non-batch
    dims (``is_batched`` keeps axis 0: (B, ys_size, xs_size))."""

    def __init__(self, func: Callable, xs, is_batched: bool = False):
        self._func = _lift(func)
        self._xs, self._multi_in = _as_tuple(xs)
        self._batched = is_batched
        self._mat = None

    def _compute(self):
        if self._mat is not None:
            return self._mat
        primals = tuple(_unwrap(x) for x in self._xs)
        if self._batched:
            # vmap(jacrev) yields the batch diagonal directly at O(B)
            # cost (jacrev over the full batch would materialize the
            # O(B²) cross-batch tensor just to discard it)
            jac = jax.vmap(jax.jacrev(
                self._func, argnums=tuple(range(len(primals)))))(*primals)
        else:
            jac = jax.jacrev(self._func,
                             argnums=tuple(range(len(primals))))(*primals)
        if isinstance(jac, tuple) and not self._multi_in:
            jac = jac[0]

        def np_prod(shape):
            out = 1
            for s in shape:
                out *= int(s)
            return out

        def flatten(j, y_shape, x_shape):
            m = np_prod(y_shape) if y_shape else 1
            n = np_prod(x_shape) if x_shape else 1
            if self._batched:
                # j: (B, *y_rest, *x_rest) from the vmapped jacrev
                return j.reshape((j.shape[0], m, n))
            return j.reshape((m, n))

        if self._multi_in:
            out = []
            for x, j in zip(self._xs, jac):
                xs_shape = tuple(_unwrap(x).shape)
                if self._batched:
                    xs_shape = xs_shape[1:]
                    ys_shape = tuple(
                        j.shape[1:len(j.shape) - len(xs_shape)])
                else:
                    ys_shape = tuple(j.shape[:len(j.shape) - len(xs_shape)])
                out.append(_wrap(flatten(j, ys_shape, xs_shape)))
            self._mat = out
        else:
            xs_shape = tuple(primals[0].shape)
            if self._batched:
                xs_shape = xs_shape[1:]
                ys_shape = tuple(jac.shape[1:len(jac.shape) - len(xs_shape)])
            else:
                ys_shape = tuple(jac.shape[:len(jac.shape) - len(xs_shape)])
            self._mat = _wrap(flatten(jac, ys_shape, xs_shape))
        return self._mat

    def __getitem__(self, idx):
        m = self._compute()
        if isinstance(m, list):
            return [t[idx] for t in m]
        return m[idx]

    @property
    def shape(self):
        m = self._compute()
        return [t.shape for t in m] if isinstance(m, list) else m.shape


class Hessian:
    """Dense hessian of a SCALAR-output ``func`` at ``xs`` (reference
    incubate.autograd.Hessian) — ``jax.hessian``, exact to machine
    precision at any order of composition."""

    def __init__(self, func: Callable, xs, is_batched: bool = False):
        self._func = _lift(func)
        self._xs, self._multi_in = _as_tuple(xs)
        self._batched = is_batched
        self._mat = None

    def _compute(self):
        if self._mat is not None:
            return self._mat
        primals = tuple(_unwrap(x) for x in self._xs)
        if self._multi_in:
            raise NotImplementedError(
                "Hessian over multiple inputs: concatenate them first "
                "(the reference has the same single-block limitation)")
        x = primals[0]

        def scalar(f_x):
            out = self._func(f_x)
            return jnp.sum(out)  # reference squeezes the (1,)-shaped output

        if self._batched:
            # vmap(hessian): per-row hessians directly, O(B) not O(B²)
            b = x.shape[0]
            n = int(x.size // b)
            h = jax.vmap(jax.hessian(scalar))(x)
            self._mat = _wrap(h.reshape((b, n, n)))
        else:
            h = jax.hessian(scalar)(x)
            n = int(x.size)
            self._mat = _wrap(h.reshape((n, n)))
        return self._mat

    def __getitem__(self, idx):
        return self._compute()[idx]

    @property
    def shape(self):
        return self._compute().shape


def jacobian(func: Callable, xs, is_batched: bool = False):
    """Materialized form of :class:`Jacobian` (returns the Tensor)."""
    return Jacobian(func, xs, is_batched=is_batched)._compute()


def hessian(func: Callable, xs, is_batched: bool = False):
    """Materialized form of :class:`Hessian` (returns the Tensor)."""
    return Hessian(func, xs, is_batched=is_batched)._compute()
