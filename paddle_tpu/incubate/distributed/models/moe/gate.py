"""MoE gates — Naive / GShard top-2 / Switch top-1.

Rebuild of python/paddle/incubate/distributed/models/moe/gate/
{naive,gshard,switch}_gate.py:§0 (SURVEY.md §2.4 EP row). Each gate returns
(per-k expert indices, per-k combine probs) and stashes its load-balancing
auxiliary loss on ``self.l_aux``.

Differentiability: probs/aux-loss flow through the eager tape (Tensor ops);
index computations (top-k choice, capacity pruning, random routing) are
index-only and run raw — they carry no gradient by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .....nn.layer import Layer
from .....nn import functional as F
from .....core import math_ops as pm
from .....core.tensor import Tensor
from .....ops import moe_ops
from ..... import random as _random


class BaseGate(Layer):
    def __init__(self, num_expert: int, world_size: int = 1):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = num_expert * world_size
        self.l_aux = None

    def capacity(self, num_tokens: int, capacity_factor: float) -> int:
        return max(int(np.ceil(capacity_factor * num_tokens / self.tot_expert)), 1)


def _gate_probs(gate_layer, inp) -> Tensor:
    """fp32 softmax over expert logits, on the tape."""
    logits = pm.matmul(inp, gate_layer.gate)
    return F.softmax(pm.cast(logits, "float32"), axis=-1)


def _aux_loss(probs: Tensor, top1_idx, num_experts: int) -> Tensor:
    """GShard/Switch load-balance loss: E * sum_e mean(P_e) * frac_top1_e.
    ``top1_idx`` is index data (constant); probs stay differentiable."""
    ce = jax.nn.one_hot(jnp.asarray(top1_idx), num_experts,
                        dtype=jnp.float32).mean(axis=0)
    me = pm.mean(probs, axis=0)
    return pm.sum(me * Tensor(ce)) * float(num_experts)


class NaiveGate(BaseGate):
    """Plain linear top-k gate, no capacity, no aux loss."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 2):
        super().__init__(num_expert, world_size)
        self.topk = topk
        self.gate = self.create_parameter((d_model, self.tot_expert))

    def forward(self, inp):
        probs = _gate_probs(self, inp)
        topv, topi = pm.topk(probs, self.topk, axis=-1)
        self.l_aux = Tensor(jnp.zeros((), jnp.float32))
        return topi, topv


class GShardGate(BaseGate):
    """Top-2 gate with capacity, random 2nd-expert routing, aux loss
    (reference gshard_gate.py)."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 2, capacity=(1.2, 2.4), random_routing: bool = True,
                 group=None):
        super().__init__(num_expert, world_size)
        assert topk == 2, "GShard gate is top-2"
        self.topk = 2
        self.capacity_factor = capacity
        self.random_routing = random_routing
        self.gate = self.create_parameter((d_model, self.tot_expert))

    def forward(self, inp):
        n = inp.shape[0]
        probs = _gate_probs(self, inp)
        topv, topi = pm.topk(probs, 2, axis=-1)
        idx = topi._value
        self.l_aux = _aux_loss(probs, idx[:, 0], self.tot_expert)
        raw_idx = idx
        if self.random_routing and self.training:
            prob = jax.random.uniform(_random.next_key(), (n,))
            raw_idx = moe_ops.random_routing(raw_idx, topv._value, prob)
        factor = self.capacity_factor
        if isinstance(factor, (tuple, list)):
            factor = factor[0] if self.training else factor[1]
        cap = self.capacity(n, factor)
        # joint capacity pruning, choice order = GShard order (index-only;
        # round 3: the index routes replace the dense (N,E,C) masks — same
        # admission set, O(N·E) instead of O(N·E·C))
        routes = moe_ops.dispatch_indices_topk(raw_idx, self.tot_expert, cap)
        raw_idx = jnp.stack(
            [jnp.where(routes[k][1], raw_idx[:, k], -1) for k in range(2)],
            axis=1)
        return Tensor(raw_idx), topv


class SwitchGate(BaseGate):
    """Top-1 gate with capacity + aux loss (reference switch_gate.py)."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 1, switch_eps: float = 0.1, capacity=(1.2, 2.4),
                 group=None):
        super().__init__(num_expert, world_size)
        assert topk == 1, "Switch gate is top-1"
        self.topk = 1
        self.switch_eps = switch_eps
        self.capacity_factor = capacity
        self.gate = self.create_parameter((d_model, self.tot_expert))

    def forward(self, inp):
        n = inp.shape[0]
        logits = pm.matmul(inp, self.gate)
        if self.training:
            # jitter noise (reference multiplies logits by U[1-eps, 1+eps])
            noise = jax.random.uniform(
                _random.next_key(), tuple(logits.shape),
                minval=1.0 - self.switch_eps, maxval=1.0 + self.switch_eps)
            logits = logits * Tensor(noise.astype(logits._value.dtype))
        probs = F.softmax(pm.cast(logits, "float32"), axis=-1)
        topv, topi = pm.topk(probs, 1, axis=-1)
        idx = topi._value
        self.l_aux = _aux_loss(probs, idx[:, 0], self.tot_expert)
        factor = self.capacity_factor
        if isinstance(factor, (tuple, list)):
            factor = factor[0] if self.training else factor[1]
        cap = self.capacity(n, factor)
        counts = moe_ops.number_count(idx[:, 0], self.tot_expert)
        pruned = moe_ops.prune_gate_by_capacity(
            idx[:, 0], jnp.minimum(counts, cap), self.tot_expert)
        return Tensor(pruned[:, None]), topv
