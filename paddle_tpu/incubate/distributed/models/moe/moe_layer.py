"""MoE layer with expert-parallel dispatch.

Rebuild of python/paddle/incubate/distributed/models/moe/moe_layer.py:§0
(SURVEY.md §2.4 EP row). Reference pipeline: gate → global_scatter (count
exchange + NCCL alltoall) → local experts → global_gather. TPU-native: the
dense GShard dispatch/combine einsums (ops.moe_ops) carry the routing; under
a mesh with an ``expert``-sharded axis, XLA lowers the expert dimension of
those einsums to an ICI all_to_all — no hand-written comm. Experts compute on
fixed-capacity slots, keeping shapes static for XLA.

Gradients: dispatch/combine masks are index-only constants; probabilities,
expert parameters, gate parameters and the input all differentiate through
the eager tape (Tensor ops).
"""

from __future__ import annotations

import functools
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from .....core import math_ops as pm
from .....core.tensor import Tensor
from .....nn.layer import Layer, LayerList
from .....ops import moe_ops
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate
from .....core.compat import shard_map


_ACTS = {"GELU": "gelu", "ReLU": "relu", "SiLU": "silu", "Silu": "silu"}


def _ffn_parts(expert):
    """(lin1, lin2, act_name) when ``expert`` is exactly Linear → recognized
    activation → Linear with a consistent bias layout; None otherwise (the
    caller falls back to the dense dispatch path rather than silently
    computing different numerics)."""
    from .....nn.common_layers import Linear

    seq = []  # traversal-ordered ("linear"|"act", payload)
    for _, sub in expert.named_sublayers(include_self=True):
        if isinstance(sub, Linear):
            seq.append(("linear", sub))
        elif type(sub).__name__ in _ACTS:
            seq.append(("act", _ACTS[type(sub).__name__]))
        elif not list(sub.children()):  # unrecognized leaf layer
            return None
    # order matters: gelu(x@w1)@w2 != gelu(x@w1@w2); shapes alone cannot
    # disambiguate when d_model == intermediate
    if [k for k, _ in seq] != ["linear", "act", "linear"]:
        return None
    l1, l2 = seq[0][1], seq[2][1]
    acts = [seq[1][1]]
    if l1.weight.shape[1] != l2.weight.shape[0] or \
            l1.weight.shape[0] != l2.weight.shape[1]:
        return None
    # bias layout must be uniform (the stacked kernel has one has_bias flag)
    if (l1.bias is None) != (l2.bias is None):
        return None
    return l1, l2, acts[0]


@functools.lru_cache(maxsize=64)
def _ep_program(mesh, axis: str, num_experts: int, capacity: int,
                act_name: str, has_bias: bool):
    """Cached jitted shard_map running expert_parallel_apply over ``axis``:
    tokens and stacked expert weights both sharded on dim 0."""
    import jax
    from jax.sharding import PartitionSpec as P

    if act_name == "gelu":
        # paddle GELU defaults to the exact erf form; jax.nn.gelu to tanh
        act = functools.partial(jax.nn.gelu, approximate=False)
    else:
        act = getattr(jax.nn, act_name)

    if has_bias:
        def fn(xl, idx, prob, w1, b1, w2, b2):
            return moe_ops.expert_parallel_apply(
                xl, idx, prob, w1, w2, axis, num_experts, capacity,
                act=act, b1_local=b1, b2_local=b2)
        n_in = 7
    else:
        def fn(xl, idx, prob, w1, w2):
            return moe_ops.expert_parallel_apply(
                xl, idx, prob, w1, w2, axis, num_experts, capacity, act=act)
        n_in = 5

    shmap = shard_map(fn, mesh=mesh, in_specs=(P(axis),) * n_in,
                          out_specs=P(axis), check_vma=False)
    return jax.jit(shmap)


class MoELayer(Layer):
    """``MoELayer(d_model, experts=[...], gate='gshard', ...)``.

    experts: list of Layers, each mapping (n, d_model) -> (n, d_model).
    gate: BaseGate instance or one of 'naive' | 'gshard' | 'switch'.
    """

    def __init__(self, d_model: int, experts: Optional[List[Layer]] = None,
                 gate="gshard", moe_group=None, mp_group=None,
                 recompute_interval: int = 0, random_routing: bool = True,
                 capacity_factor=(1.2, 2.4), topk: Optional[int] = None,
                 **kwargs):
        super().__init__()
        if not experts:
            raise ValueError("experts list must be non-empty")
        self.d_model = d_model
        self.experts = LayerList(experts)
        self.num_expert = len(experts)
        self.moe_group = moe_group
        if isinstance(gate, BaseGate):
            self.gate = gate
        elif gate in (None, "naive"):
            self.gate = NaiveGate(d_model, self.num_expert, 1, topk=topk or 2)
        elif gate == "gshard":
            self.gate = GShardGate(d_model, self.num_expert, 1,
                                   capacity=capacity_factor,
                                   random_routing=random_routing)
        elif gate == "switch":
            self.gate = SwitchGate(d_model, self.num_expert, 1,
                                   capacity=capacity_factor)
        else:
            raise ValueError(f"unknown gate {gate!r}")
        self.capacity_factor = capacity_factor
        # tag expert params for expert-aware grad clip / no-dp-sync
        for p in self.experts.parameters():
            p.expert = True
        self.l_aux = None
        # expert-parallel path: when moe_group names a multi-device mesh axis
        # and every expert is a homogeneous 2-Linear FFN, dispatch routes
        # through ops.moe_ops.expert_parallel_apply (explicit all_to_all over
        # the axis — the reference's global_scatter/global_gather) instead of
        # the dense (N,E,C) einsums + Python expert loop.
        self._ep_parts = None
        self._ep_axis = None
        if moe_group is not None and getattr(moe_group, "nranks", 1) > 1:
            parts = [_ffn_parts(e) for e in experts]
            homogeneous = (
                all(p is not None for p in parts)
                and len({p[2] for p in parts}) == 1          # same activation
                and len({p[0].bias is None for p in parts}) == 1)  # same bias
            if homogeneous and self.num_expert % moe_group.nranks == 0:
                self._ep_parts = parts
                self._ep_axis = moe_group.axis
                self._ep_mesh = moe_group.mesh

    def forward(self, inp):
        orig_shape = tuple(inp.shape)
        d = orig_shape[-1]
        xf = pm.reshape(inp, (-1, d))
        n = xf.shape[0]

        topi, topv = self.gate(xf)
        self.l_aux = self.gate.l_aux
        idx = topi._value
        K = idx.shape[1]

        # gates that prune by capacity define the factor; otherwise the
        # layer's own capacity_factor governs (naive/custom gates)
        factor = getattr(self.gate, "capacity_factor", None)
        if factor is None:
            factor = self.capacity_factor
        if isinstance(factor, (tuple, list)):
            factor = factor[0] if self.training else factor[1]
        capacity = max(int(np.ceil(factor * n / self.num_expert)), 1)

        valid = Tensor((idx >= 0).astype(jnp.float32))
        if K == 1:
            # top-1 (Switch) semantics: y = p(x) * E(x) — keep the raw gate
            # prob so the gate trains from the task loss
            probs = topv * valid
        else:
            # top-k: combine probs renormalized over admitted choices
            probs = topv * valid
            denom = pm.clip(pm.sum(probs, axis=-1, keepdim=True), min=1e-9)
            probs = probs / denom

        if self._ep_parts is not None and \
                n % self._ep_mesh.shape[self._ep_axis] == 0:
            out = self._forward_expert_parallel(xf, idx, probs, capacity)
            return pm.reshape(out, orig_shape)

        # gather-based dispatch (round 4): the dense (N,E,C) one-hot
        # einsums cost O(N·E·C·d); the round-3 index dispatch removed that
        # but SCATTERED the (N,d) activations into slots — a measured +8%
        # step-time regression on TPU. With the inverse slot->token map
        # (one N-element int32 scatter) every float movement in dispatch,
        # combine AND their gradients is a gather — the fast path on TPU.
        # Routing is unchanged (dispatch_indices_topk keeps
        # dispatch_masks_topk's joint capacity ordering — parity-tested in
        # test_moe).
        from .....core.dispatch import apply as _apply

        routes = moe_ops.dispatch_indices_topk(idx, self.num_expert,
                                               capacity)
        E, C = self.num_expert, capacity
        tfs, cfs, flats, oks = moe_ops.dispatch_plan(routes, E, C, n)
        plan = [Tensor(tfs), Tensor(cfs), Tensor(flats), Tensor(oks)]

        def fn_dispatch(xv, t, fl, ok):
            return moe_ops.moe_dispatch_gather(xv, t, fl, ok, E, C)

        expert_in = _apply(fn_dispatch, xf, Tensor(tfs), Tensor(flats),
                           Tensor(oks), op_name="moe_dispatch")

        # run experts on their capacity slots (static python loop: E is small
        # and each expert owns distinct parameters)
        outs = [self.experts[e](expert_in[e]) for e in range(self.num_expert)]
        expert_out = pm.stack(outs, axis=0)  # (E, C, d)

        def fn_combine(eo, pv, t, c, fl, ok):
            return moe_ops.moe_combine_gather(eo, pv, fl, ok, t, c)

        out = _apply(fn_combine, expert_out, probs, *plan,
                     op_name="moe_combine")
        return pm.reshape(out, orig_shape)

    def _forward_expert_parallel(self, xf, idx, probs, capacity):
        """all_to_all dispatch over the moe_group axis: tokens sharded over
        the axis dispatch locally (per-shard capacity ceil(C/n)), route to
        the expert's owning device, compute, and route back. Local capacity
        admission approximates the dense path's global ordering — identical
        whenever capacity is ample (no drops)."""
        from .....core.dispatch import apply

        nr = self._ep_mesh.shape[self._ep_axis]
        cap_local = max(int(np.ceil(capacity / nr)), 1)
        l1s, l2s, act = (list(z) for z in zip(*self._ep_parts))
        w1 = pm.stack([l.weight for l in l1s], axis=0)   # (E, d, ff)
        w2 = pm.stack([l.weight for l in l2s], axis=0)   # (E, ff, d)
        has_bias = l1s[0].bias is not None
        prog = _ep_program(self._ep_mesh, self._ep_axis, self.num_expert,
                           cap_local, act[0], has_bias)
        idx_t = Tensor(idx)
        if has_bias:
            b1 = pm.stack([l.bias for l in l1s], axis=0)
            b2 = pm.stack([l.bias for l in l2s], axis=0)
            return apply(prog, xf, idx_t, probs, w1, b1, w2, b2,
                         op_name="moe_expert_parallel")
        return apply(prog, xf, idx_t, probs, w1, w2,
                     op_name="moe_expert_parallel")
