"""MoE layer with expert-parallel dispatch.

Rebuild of python/paddle/incubate/distributed/models/moe/moe_layer.py:§0
(SURVEY.md §2.4 EP row). Reference pipeline: gate → global_scatter (count
exchange + NCCL alltoall) → local experts → global_gather. TPU-native: the
dense GShard dispatch/combine einsums (ops.moe_ops) carry the routing; under
a mesh with an ``expert``-sharded axis, XLA lowers the expert dimension of
those einsums to an ICI all_to_all — no hand-written comm. Experts compute on
fixed-capacity slots, keeping shapes static for XLA.

Gradients: dispatch/combine masks are index-only constants; probabilities,
expert parameters, gate parameters and the input all differentiate through
the eager tape (Tensor ops).
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from .....core import math_ops as pm
from .....core.tensor import Tensor
from .....nn.layer import Layer, LayerList
from .....ops import moe_ops
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate


class MoELayer(Layer):
    """``MoELayer(d_model, experts=[...], gate='gshard', ...)``.

    experts: list of Layers, each mapping (n, d_model) -> (n, d_model).
    gate: BaseGate instance or one of 'naive' | 'gshard' | 'switch'.
    """

    def __init__(self, d_model: int, experts: Optional[List[Layer]] = None,
                 gate="gshard", moe_group=None, mp_group=None,
                 recompute_interval: int = 0, random_routing: bool = True,
                 capacity_factor=(1.2, 2.4), topk: Optional[int] = None,
                 **kwargs):
        super().__init__()
        if not experts:
            raise ValueError("experts list must be non-empty")
        self.d_model = d_model
        self.experts = LayerList(experts)
        self.num_expert = len(experts)
        self.moe_group = moe_group
        if isinstance(gate, BaseGate):
            self.gate = gate
        elif gate in (None, "naive"):
            self.gate = NaiveGate(d_model, self.num_expert, 1, topk=topk or 2)
        elif gate == "gshard":
            self.gate = GShardGate(d_model, self.num_expert, 1,
                                   capacity=capacity_factor,
                                   random_routing=random_routing)
        elif gate == "switch":
            self.gate = SwitchGate(d_model, self.num_expert, 1,
                                   capacity=capacity_factor)
        else:
            raise ValueError(f"unknown gate {gate!r}")
        self.capacity_factor = capacity_factor
        # tag expert params for expert-aware grad clip / no-dp-sync
        for p in self.experts.parameters():
            p.expert = True
        self.l_aux = None

    def forward(self, inp):
        orig_shape = tuple(inp.shape)
        d = orig_shape[-1]
        xf = pm.reshape(inp, (-1, d))
        n = xf.shape[0]

        topi, topv = self.gate(xf)
        self.l_aux = self.gate.l_aux
        idx = topi._value
        K = idx.shape[1]

        # gates that prune by capacity define the factor; otherwise the
        # layer's own capacity_factor governs (naive/custom gates)
        factor = getattr(self.gate, "capacity_factor", None)
        if factor is None:
            factor = self.capacity_factor
        if isinstance(factor, (tuple, list)):
            factor = factor[0] if self.training else factor[1]
        capacity = max(int(np.ceil(factor * n / self.num_expert)), 1)

        valid = Tensor((idx >= 0).astype(jnp.float32))
        if K == 1:
            # top-1 (Switch) semantics: y = p(x) * E(x) — keep the raw gate
            # prob so the gate trains from the task loss
            probs = topv * valid
        else:
            # top-k: combine probs renormalized over admitted choices
            probs = topv * valid
            denom = pm.clip(pm.sum(probs, axis=-1, keepdim=True), min=1e-9)
            probs = probs / denom

        # reuse the gate's dispatch masks when it already built them for
        # pruning (GShard); identity check guards against stale caches
        cached = getattr(self.gate, "_dispatch_cache", None)
        if cached is not None and cached[0] is idx and cached[1] == capacity:
            masks = cached[2]
        else:
            masks = moe_ops.dispatch_masks_topk(idx, self.num_expert, capacity)
        dtype = str(xf.dtype).split(".")[-1]
        disp_sum = Tensor(sum(masks))  # (N,E,C) constant
        expert_in = pm.einsum("nec,nd->ecd", pm.cast(disp_sum, dtype), xf)

        # run experts on their capacity slots (static python loop: E is small
        # and each expert owns distinct parameters)
        outs = [self.experts[e](expert_in[e]) for e in range(self.num_expert)]
        expert_out = pm.stack(outs, axis=0)  # (E, C, d)

        # combine: sum_k mask_k * prob_k — probs differentiable
        comb = None
        for k in range(K):
            pk = pm.unsqueeze(pm.unsqueeze(probs[:, k], -1), -1)  # (N,1,1)
            term = pm.cast(Tensor(masks[k]), "float32") * pk
            comb = term if comb is None else comb + term
        out = pm.einsum("nec,ecd->nd", pm.cast(comb, dtype), expert_out)
        return pm.reshape(out, orig_shape)
