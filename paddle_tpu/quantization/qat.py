"""Quantization-aware training + post-training quantization workflow.

Parity with python/paddle/quantization/ of the reference (QuantConfig,
QAT, PTQ, the quanter/observer zoo — quanters/abs_max.py,
observers/abs_max.py:§0). TPU-first mechanics:

- fake-quant is the straight-through estimator written as
  ``x + stop_gradient(q(x) - x)`` — pure jnp, so it traces, jits, and
  rides the compiled TrainStep with zero custom-vjp machinery;
- activation observers keep a moving-average abs-max in a float buffer
  (eager updates; frozen under trace, like BN stats under jit);
- ``convert`` lowers quantized Linears onto the existing serving path
  (WeightOnlyLinear: int8 weights, dequant fused into the matmul).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..nn import Linear
from . import WeightOnlyLinear, weight_quantize

__all__ = [
    "QuantConfig", "QAT", "PTQ", "FakeQuanterWithAbsMax",
    "AbsmaxObserver", "QuantedLinear", "quanted_layers",
]


def _fake_quant(x, scale, bits: int = 8):
    """STE fake quant: forward rounds onto the int grid, backward is
    identity (the stop_gradient sandwich)."""
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s), -qmax, qmax) * s
    return x + jax.lax.stop_gradient(q - x)


class FakeQuanterWithAbsMax(Layer):
    """Activation fake-quanter: moving-average abs-max scale (reference
    FakeQuanterWithAbsMaxObserver)."""

    def __init__(self, moving_rate: float = 0.9, bits: int = 8):
        super().__init__()
        self.moving_rate = moving_rate
        self.bits = bits
        self.register_buffer("scale", Tensor(jnp.asarray(0.0)))

    def forward(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        amax = jnp.max(jnp.abs(v.astype(jnp.float32)))
        qmax = float(2 ** (self.bits - 1) - 1)
        # the moving average updates under jit too: the buffer write
        # rides the TrainStep bind carry exactly like BN running stats
        # (review r5: a frozen 0 scale under trace collapsed every
        # activation to ~0 on QAT loops with no eager warmup)
        prev = self.scale._value
        new = jnp.where(prev == 0.0, amax,
                        self.moving_rate * prev
                        + (1 - self.moving_rate) * amax)
        self.scale._value = new.astype(jnp.float32)
        return Tensor(_fake_quant(v, new / qmax, self.bits))


class AbsmaxObserver(Layer):
    """PTQ calibration observer: tracks the max abs seen (reference
    observers/abs_max.py)."""

    def __init__(self, bits: int = 8):
        super().__init__()
        self.bits = bits
        self.register_buffer("amax", Tensor(jnp.asarray(0.0)))

    def forward(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        amax = jnp.max(jnp.abs(v.astype(jnp.float32)))
        # buffer max-update works under trace too (bind carry)
        self.amax._value = jnp.maximum(self.amax._value, amax)
        return x if isinstance(x, Tensor) else Tensor(v)

    @property
    def scale(self) -> float:
        return float(self.amax._value) / float(2 ** (self.bits - 1) - 1)


class QuantedLinear(Layer):
    """Linear with fake-quantized weights (per-out-channel abs-max) and
    an activation quanter/observer in front — the QAT stand-in the
    reference swaps in for nn.Linear."""

    def __init__(self, inner: Linear, activation_quanter: Optional[Layer],
                 weight_bits: int = 8):
        super().__init__()
        self.inner = inner
        self.activation_quanter = activation_quanter
        self.weight_bits = weight_bits

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.inner.weight
        qmax = float(2 ** (self.weight_bits - 1) - 1)
        amax = jnp.max(jnp.abs(w._value.astype(jnp.float32)), axis=0)
        scale = jnp.maximum(amax / qmax, 1e-9)
        # one STE sandwich, riding the tape through the original weight
        wq = w + Tensor(jax.lax.stop_gradient(
            _fake_quant(w._value, scale[None, :], self.weight_bits)
            - w._value))
        out = x.matmul(wq)
        if self.inner.bias is not None:
            out = out + self.inner.bias
        return out


class QuantConfig:
    """Maps layer types/names to quanters (reference QuantConfig).

    ``weight`` configures the weight fake-quant BITS: pass an int, or a
    quanter/factory exposing ``bits`` (the built-in per-out-channel
    abs-max grid is the only weight scheme — matching the serving
    path's layout); anything else raises rather than silently running
    the default."""

    def __init__(self, activation=None, weight=None):
        self._default_act = activation
        self._default_wbits = _weight_bits(weight)
        self._type_cfg: Dict[Type, dict] = {}
        self._name_cfg: Dict[str, dict] = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            self._type_cfg[t] = {"activation": activation,
                                 "weight_bits": _weight_bits(weight)}
        return self

    def add_name_config(self, names, activation=None, weight=None):
        for n in (names if isinstance(names, (list, tuple)) else [names]):
            self._name_cfg[n] = {"activation": activation,
                                 "weight_bits": _weight_bits(weight)}
        return self

    def _lookup(self, name: str, layer) -> Optional[dict]:
        if name in self._name_cfg:
            return self._name_cfg[name]
        for t, cfg in self._type_cfg.items():
            if isinstance(layer, t):
                return cfg
        if self._default_act is not None and isinstance(layer, Linear):
            return {"activation": self._default_act,
                    "weight_bits": self._default_wbits}
        return None


def _weight_bits(weight) -> int:
    """Resolve a weight-quanter config to a bit width (see QuantConfig
    docstring); None -> the default 8."""
    if weight is None:
        return 8
    if isinstance(weight, int):
        return weight
    bits = getattr(weight, "bits", None)
    if bits is None and callable(weight):
        bits = getattr(weight(), "bits", None)
    if isinstance(bits, int):
        return bits
    raise ValueError(
        "unsupported weight quanter config: pass an int bit width or an "
        "object/factory with a `bits` attribute (the weight scheme is "
        "per-out-channel abs-max, the serving layout)")


def quanted_layers(model: Layer):
    """All QuantedLinear instances under ``model`` (with names)."""
    return [(n, sub) for n, sub in model.named_sublayers()
            if isinstance(sub, QuantedLinear)]


def _swap_sublayer(model: Layer, dotted: str, new: Layer):
    parts = dotted.split(".")
    parent = model
    for p in parts[:-1]:
        parent = getattr(parent, p)
    # Sequential children live in _sub_layers under string indices
    leaf = parts[-1]
    parent._sub_layers[leaf] = new


class QAT:
    """Quantization-aware training driver (reference paddle.quantization.
    QAT): ``quantize`` swaps configured Linears for QuantedLinear;
    ``convert`` lowers to the int8 serving layer."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        if not inplace:
            raise NotImplementedError(
                "deep-copying Layers is not supported; use inplace=True")
        for name, sub in list(model.named_sublayers()):
            if not isinstance(sub, Linear):
                continue
            cfg = self.config._lookup(name, sub)
            if cfg is None:
                continue
            act_q = None
            maker = cfg.get("activation")
            if maker is not None:
                act_q = maker() if callable(maker) else maker
            _swap_sublayer(model, name, QuantedLinear(
                sub, act_q, weight_bits=cfg.get("weight_bits", 8)))
        return model

    def convert(self, model: Layer, inplace: bool = True) -> Layer:
        """Replace every QuantedLinear with the real-int8 serving layer
        (weights quantized once; dequant fuses into the matmul)."""
        if not inplace:
            raise NotImplementedError("use inplace=True")
        for name, sub in quanted_layers(model):
            inner = sub.inner
            wol = WeightOnlyLinear.from_linear(inner)
            _swap_sublayer(model, name, wol)
        return model


class PTQ:
    """Post-training quantization: insert observers, run calibration
    batches, then convert. Weights land on the int8 serving grid; the
    calibrated ACTIVATION scales are attached to each converted layer
    as ``act_scale`` (the A8W8 prefill path consumes per-layer
    activation scales of exactly this form — models/llama._mm_prefill)
    and returned by :meth:`activation_scales`."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig(activation=AbsmaxObserver)

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        cfg = QuantConfig(activation=self.config._default_act
                          or AbsmaxObserver)
        cfg._type_cfg = self.config._type_cfg
        cfg._name_cfg = self.config._name_cfg
        return QAT(cfg).quantize(model, inplace=inplace)

    def activation_scales(self, model: Layer) -> Dict[str, float]:
        """name -> calibrated activation scale for every observed
        QuantedLinear."""
        out = {}
        for name, sub in quanted_layers(model):
            obs = sub.activation_quanter
            if isinstance(obs, AbsmaxObserver):
                out[name] = obs.scale
        return out

    def convert(self, model: Layer, inplace: bool = True) -> Layer:
        if not inplace:
            raise NotImplementedError("use inplace=True")
        scales = self.activation_scales(model)
        for name, sub in quanted_layers(model):
            wol = WeightOnlyLinear.from_linear(sub.inner)
            if name in scales:
                wol.act_scale = scales[name]
            _swap_sublayer(model, name, wol)
        return model
