"""Weight-only quantization for serving.

Rebuild of the reference's int8 serving path (fused_multi_transformer_int8
— paddle/fluid/operators/fused/fused_multi_transformer_int8_op.cu:§0 — and
the paddle.nn.quant weight_only_linear surface; SURVEY.md §2.2). TPU-first
rationale: decode is HBM-bandwidth-bound, so storing weights int8 halves
the bytes the MXU waits on; dequantization is expressed as a multiply that
XLA fuses into the matmul (no separate dequant pass, mirroring the CUDA
kernel's in-register dequant).

Symmetric per-output-channel scales (int8, [-127, 127]); "weight_only_int4"
packs two nibbles per byte with the same scale scheme.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..nn import initializer as I


def weight_quantize(w, algo: str = "weight_only_int8"):
    """w: (in, out) float → (quantized weights, per-out-channel scales).

    Parity with paddle.nn.quant.weight_quantize. int8: values in
    [-127, 127]; int4: [-7, 7] packed two-per-byte along the input dim.
    """
    wv = w._value if isinstance(w, Tensor) else jnp.asarray(w)
    wf = wv.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=0)                     # (out,)
    if algo == "weight_only_int8":
        scale = jnp.where(amax > 0, amax / 127.0, 1.0)
        q = jnp.clip(jnp.round(wf / scale[None, :]), -127, 127) \
            .astype(jnp.int8)
        return q, scale
    if algo == "weight_only_int4":
        scale = jnp.where(amax > 0, amax / 7.0, 1.0)
        q = jnp.clip(jnp.round(wf / scale[None, :]), -7, 7).astype(jnp.int8)
        if q.shape[0] % 2:
            raise ValueError("int4 packing needs an even input dim")
        lo = q[0::2] & 0x0F
        hi = (q[1::2] & 0x0F) << 4
        return (lo | hi).astype(jnp.int8), scale
    raise ValueError(f"unknown algo {algo!r}")


def weight_dequantize(q, scale, algo: str = "weight_only_int8"):
    """Inverse of weight_quantize. Accepts stacked layouts too: q
    (..., in, out) with scale (..., out) — the broadcast keeps per-layer
    scales aligned (quantize_stacked_params format)."""
    if algo == "weight_only_int8":
        return q.astype(jnp.float32) * scale[..., None, :]
    if algo == "weight_only_int4":
        u = q.astype(jnp.uint8)
        lo = (u & 0x0F).astype(jnp.int8)
        hi = ((u >> 4) & 0x0F).astype(jnp.int8)
        # sign-extend 4-bit two's complement
        lo = jnp.where(lo > 7, lo - 16, lo)
        hi = jnp.where(hi > 7, hi - 16, hi)
        # Packed axis is the INPUT dim (axis -2): row 2i came from lo[i],
        # row 2i+1 from hi[i]. Interleave there so stacked (L, in/2, out)
        # layouts unpack to (L, in, out) — stacking on axis 1 only worked
        # for 2-D q.
        full = jnp.stack([lo, hi], axis=-2)
        full = full.reshape(q.shape[:-2] + (2 * q.shape[-2], q.shape[-1]))
        return full.astype(jnp.float32) * scale[..., None, :]
    raise ValueError(f"unknown algo {algo!r}")


_ALGOS = {"int8": "weight_only_int8", "weight_only_int8": "weight_only_int8",
          "int4": "weight_only_int4", "weight_only_int4": "weight_only_int4"}


def weight_only_linear(x, weight, weight_scale, bias=None,
                       weight_dtype: str = "int8"):
    """Parity with paddle.nn.quant.weight_only_linear: x @ dequant(w) + b.
    The dequant multiply fuses into the matmul under XLA."""
    if weight_dtype not in _ALGOS:
        raise ValueError(f"unknown weight_dtype {weight_dtype!r}; "
                         f"expected one of {sorted(_ALGOS)}")
    algo = _ALGOS[weight_dtype]

    def fn(xv, qv, sv, *rest):
        w = weight_dequantize(qv, sv, algo).astype(jnp.float32)
        y = jnp.matmul(xv.astype(jnp.float32), w)
        if rest:
            y = y + rest[0]
        return y.astype(xv.dtype)

    args = [x, weight, weight_scale] + ([bias] if bias is not None else [])
    return apply(fn, *args, op_name="weight_only_linear")


class WeightOnlyLinear(Layer):
    """Drop-in Linear whose weight is stored int8/int4 (serving layer;
    parity with paddle.nn.quant.qat-exported weight-only linears)."""

    def __init__(self, in_features, out_features, weight_dtype: str = "int8",
                 has_bias: bool = True):
        super().__init__()
        if weight_dtype not in _ALGOS:
            raise ValueError(f"unknown weight_dtype {weight_dtype!r}")
        if _ALGOS[weight_dtype] == "weight_only_int4" and in_features % 2:
            raise ValueError("int4 packing needs an even in_features")
        self.weight_dtype = weight_dtype
        store_rows = (in_features if _ALGOS[weight_dtype] == "weight_only_int8"
                      else in_features // 2)
        self.weight = self.create_parameter(
            (store_rows, out_features),
            default_initializer=I.Constant(0.0))
        self.weight._value = jnp.zeros((store_rows, out_features), jnp.int8)
        self.weight.trainable = False
        self.weight.stop_gradient = True
        self.weight_scale = self.create_parameter(
            (out_features,), default_initializer=I.Constant(1.0))
        self.weight_scale.trainable = False
        self.weight_scale.stop_gradient = True
        self.bias = (self.create_parameter((out_features,), is_bias=True)
                     if has_bias else None)

    @classmethod
    def from_linear(cls, linear, weight_dtype: str = "int8"):
        w = linear.weight._value
        qcls = cls(int(w.shape[0]), int(w.shape[1]),
                   weight_dtype=weight_dtype,
                   has_bias=linear.bias is not None)
        algo = _ALGOS[weight_dtype]  # cls() above validated the name
        q, s = weight_quantize(w, algo)
        qcls.weight._value = q
        qcls.weight_scale._value = s
        if linear.bias is not None:
            qcls.bias._value = linear.bias._value
        return qcls

    def forward(self, x):
        return weight_only_linear(x, self.weight, self.weight_scale,
                                  self.bias, self.weight_dtype)


def quantize_stacked_params(params: dict, keys=None,
                            algo: str = "weight_only_int8") -> dict:
    """Quantize a stacked-param dict (models/llama layout): each selected
    (L, in, out) weight becomes {"q": int8, "scale": (L, out)}. The llama
    serving paths (forward_stacked / prefill / decode, contiguous and
    paged) consume this format directly — dequant happens inside the
    per-layer einsums (models/llama.py::_dense)."""
    if algo != "weight_only_int8":
        raise ValueError(
            "stacked-param quantization supports weight_only_int8 (int4's "
            "nibble packing changes the contraction-dim shape the layer "
            "einsums expect)")
    keys = keys or ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
                    "lm_head")
    out = dict(params)
    for k in keys:
        if k not in params:
            continue
        w = params[k]
        if w.ndim == 3:
            qs = [weight_quantize(w[i], algo) for i in range(w.shape[0])]
            out[k] = {"q": jnp.stack([q for q, _ in qs]),
                      "scale": jnp.stack([s for _, s in qs])}
        else:
            q, s = weight_quantize(w, algo)
            out[k] = {"q": q, "scale": s}
    return out


from .qat import (  # noqa: E402,F401
    QAT, PTQ, QuantConfig, FakeQuanterWithAbsMax, AbsmaxObserver,
    QuantedLinear, quanted_layers,
)
