"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of AphelionGroup/Paddle (PaddlePaddle), rebuilt on jax/XLA/Pallas.

Design (SURVEY.md §7): imperative paddle-shaped API over jax.Array + vjp tape;
Fleet-shaped hybrid parallelism over one jax.sharding.Mesh; Pallas kernels for
the fused-CUDA-kernel corpus; XLA replaces executors/CINN/PIR wholesale.
"""

__version__ = "0.1.0"

from . import flags  # noqa: F401  (registers flag corpus first)
from .flags import get_flags, set_flags  # noqa: F401

from .core import (  # noqa: F401
    Tensor, Parameter, CPUPlace, TPUPlace, XLAPlace, CUDAPlace,
    set_device, get_device, is_compiled_with_tpu,
    no_grad, enable_grad, set_grad_enabled, is_grad_enabled,
)
from .core.autograd import grad  # noqa: F401  (paddle.grad top level)

from .core.dtype import (  # noqa: F401
    bool_ as bool, uint8, int8, int16, int32, int64, float16, bfloat16,
    float32, float64, complex64, complex128,
    set_default_dtype, get_default_dtype,
)
from .core.math_ops import *  # noqa: F401,F403
from .core.math_ops import sum, max, min, abs, all, any, pow, round  # noqa: F401
from .core.extra_ops import (  # noqa: F401
    is_complex, is_floating_point, is_empty, rank, tolist, broadcast_shape,
    clone, view, broadcast_tensors, unstack, hsplit, vsplit, dsplit, slice,
    shard_index, unique_consecutive, inverse, poisson, hstack,
    vstack, row_stack, column_stack, dstack, atleast_1d, atleast_2d,
    atleast_3d, tensor_split, mode, masked_scatter, diagonal_scatter,
    select_scatter, slice_scatter, histogramdd,
    frac, gammaln, isin, clip_, geometric_, index_put, index_put_, unfold,
)
from .core import op_schema as _op_schema  # noqa: E402
_op_schema.install(globals())  # schema-generated ops (only missing names)
from .creation import (  # noqa: F401
    to_tensor, zeros, ones, full, empty, zeros_like, ones_like, full_like,
    empty_like, arange, linspace, logspace, eye, meshgrid, diag_embed,
    rand, randn, randint, randperm, uniform, normal, multinomial, bernoulli,
    create_parameter,
)
from .random import seed, get_rng_state, set_rng_state  # noqa: F401
from .nn.layer import ParamAttr  # noqa: F401

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import ops  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import amp  # noqa: F401
from . import autograd  # noqa: F401
from . import jit  # noqa: F401
from . import framework  # noqa: F401
from .framework.io_save import save, load  # noqa: F401

_static_mode = [False]


def in_dynamic_mode() -> bool:
    """True unless enable_static() was called. Execution stays
    eager-first either way; static graphs exist only as traced
    StableHLO programs (paddle.static), so the flag is a mode QUERY
    for gated user code, not an execution switch."""
    return not _static_mode[0]


def enable_static():
    """Reference enable_static: flips the in_dynamic_mode() query —
    ops stay eager (the static surface is paddle.static over traces)."""
    _static_mode[0] = True


def disable_static():
    _static_mode[0] = False


# subpackages imported lazily by user code: distributed, vision, hapi, parallel,
# incubate, profiler (kept out of the base import to keep import time low)


#: linalg functions paddle also exposes at top level (paddle.cholesky etc.)
_LINALG_TOPLEVEL = frozenset((
    "cholesky", "cholesky_solve", "matrix_power", "slogdet", "corrcoef",
    "cov", "det", "pinv", "matrix_rank", "eig", "eigh", "eigvals",
    "eigvalsh", "svd", "qr", "lu", "lstsq", "solve", "triangular_solve",
))


def __getattr__(name):
    import importlib
    if name in ("distributed", "vision", "hapi", "parallel", "incubate",
                "profiler", "models", "inference", "serving", "static",
                "quantization", "observability", "resilience", "kvcache",
                "linalg", "fft", "sparse", "distribution", "signal",
                "audio", "text", "utils", "onnx", "geometric",
                "device", "regularizer", "callbacks", "version", "hub"):
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name in _LINALG_TOPLEVEL:
        mod = importlib.import_module(".linalg", __name__)
        fn = getattr(mod, name)
        globals()[name] = fn
        return fn
    if name in ("Model", "summary"):
        from .hapi import Model, summary
        globals().update(Model=Model, summary=summary)
        return globals()[name]
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def device_count() -> int:
    import jax
    return len(jax.devices())


def is_grad_enabled_():  # internal alias guard
    return is_grad_enabled()
