"""``paddle_tpu.kvcache`` — refcounted prefix cache for the paged KV pool.

PR 1 made KV paging the serving substrate (``ops.paged_attention``); this
package makes those pages a SHARED, reusable cache instead of per-request
scratch: shared system prompts and multi-turn prefixes are prefilled
once, then every later request with the same leading tokens borrows the
resident pages and computes only its suffix.

* :mod:`.radix` — token-block radix tree mapping prompt prefixes to page
  lists at ``page_size`` granularity;
* :mod:`.pool` — :class:`RefcountedKVCacheManager`, the page pool with
  shared ownership (refcounts, cached-at-refcount-0 residency,
  device-side copy-on-write) and the conservation invariant
  ``free + live + cached == num_pages - 1``;
* :mod:`.policy` — :class:`LRUEvictionPolicy` over evictable radix
  leaves (cache is free until allocation pressure; then coldest dies
  first);
* :mod:`.cache` — :class:`PrefixCache`, the lookup/insert/evict surface
  the engine and scheduler drive, with registry counters
  (``paddle_kvcache_*_total``), a free/live/cached page gauge split and
  ``cache_hit``/``cache_evict`` JSONL events.

Enable it per engine::

    eng = ContinuousBatchingEngine(cfg, gen_cfg, num_slots=8,
                                   prefix_cache=True)
    # identical outputs, cheaper prefills:
    eng.cache.snapshot()   # {'hits': ..., 'cached_tokens': ..., ...}
"""

from .cache import PrefixCache  # noqa: F401
from .policy import LRUEvictionPolicy  # noqa: F401
from .pool import RefcountedKVCacheManager  # noqa: F401
from .radix import RadixNode, RadixTree  # noqa: F401

__all__ = [
    "PrefixCache", "LRUEvictionPolicy", "RefcountedKVCacheManager",
    "RadixNode", "RadixTree",
]
