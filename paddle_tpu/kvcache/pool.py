"""Refcounted page pool: shared ownership over the paged KV arrays.

``ops.paged_attention.PagedKVCacheManager`` hands every page to exactly
one sequence and returns it to the free list on ``free()``. Prefix reuse
needs three more states, so this subclass turns the pool into a
reference-counted cache:

* **live** — refcount > 0: one page may back MANY sequences at once
  (``allocate(..., shared=...)`` increments instead of popping the free
  list);
* **cached** — refcount == 0 but held by the radix tree (:mod:`.radix`):
  resident, reusable, evictable under pressure;
* **free** — on the free list.

The conservation invariant the whole subsystem is anchored on::

    free + live + cached(ref==0)  ==  num_pages - 1      (page 0 reserved)

is checked by :meth:`check_conservation` (the serving engine runs it
after every step when the cache is enabled), together with: refcounts
never negative, refcounts exactly equal to block-table occurrences, and
the three sets pairwise disjoint.

Copy-on-write lives here too (:meth:`copy_page`): when a new sequence's
suffix must write INTO a shared page (full-prompt cache hit — the last
prompt token is recomputed to produce logits, and its slot sits mid-page),
the cache layer copies the page device-side and the sequence appends into
its private copy; the original stays immutable for other sharers.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Sequence, Set

import jax
import jax.numpy as jnp

from ..ops.paged_attention import PagedKVCacheManager


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _copy_page_slab(k_pages, v_pages, src, dst):
    # donated buffers update in place: only the copied page's slab moves,
    # not the whole pool (an eager .at[].set would copy both pool arrays)
    return (k_pages.at[:, dst].set(k_pages[:, src]),
            v_pages.at[:, dst].set(v_pages[:, src]))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _write_page_slab(k_pages, v_pages, k_slab, v_slab, dst):
    # migration import: scatter one host-provided page slab (every layer)
    # into the donated pool arrays; the page id rides as a traced scalar
    # so N imported pages reuse one compiled program
    return (k_pages.at[:, dst].set(k_slab),
            v_pages.at[:, dst].set(v_slab))


class RefcountedKVCacheManager(PagedKVCacheManager):
    """See module docstring. Drop-in for ``PagedKVCacheManager`` — the
    exclusive-ownership surface (``allocate``/``extend``/``free``/
    ``block_tables``) keeps its contract; sharing is opt-in per call."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._refs: Dict[int, int] = {}     # page -> live refcount (> 0)
        self._cached: Set[int] = set()      # pages owned by the radix tree

    # -- allocation with sharing --------------------------------------------

    def allocate(self, seq_id, n_tokens: int,
                 shared: Sequence[int] = ()) -> List[int]:
        """Reserve pages for ``n_tokens``; the leading ``shared`` pages are
        borrowed (refcount bumped, NOT popped from the free list) and only
        the remainder comes from free pages. Block table = shared + owned."""
        need = self.pages_for(n_tokens) - len(shared)
        if need < 0:
            raise ValueError(
                f"{len(shared)} shared pages exceed the "
                f"{self.pages_for(n_tokens)} this sequence spans")
        if len(self._free) < need:
            self._oom("allocate", need)
            raise MemoryError(
                f"KV pool exhausted: need {need} pages, "
                f"{len(self._free)} free")
        table = [int(p) for p in shared]
        for p in table:
            self._refs[p] = self._refs.get(p, 0) + 1
        for _ in range(need):
            p = self._free.pop()
            self._refs[p] = self._refs.get(p, 0) + 1
            table.append(p)
        self._tables[seq_id] = table
        self._lens[seq_id] = n_tokens
        return table

    def extend(self, seq_id, n_new: int = 1) -> None:
        cur = self._lens[seq_id]
        new_len = cur + n_new
        have = len(self._tables[seq_id])
        need = self.pages_for(new_len)
        for _ in range(need - have):
            if not self._free:
                self._oom("extend", 1)
                raise MemoryError("KV pool exhausted on extend")
            p = self._free.pop()
            self._refs[p] = self._refs.get(p, 0) + 1
            self._tables[seq_id].append(p)
        self._lens[seq_id] = new_len

    def grow_to(self, seq_id, n_tokens: int) -> List[int]:
        """Speculative tail growth under shared ownership: appended
        pages come fresh from the free list at refcount 1 (a drafted
        span is always written exclusively — sharing happens at
        admission via ``allocate(shared=...)`` and at retire via the
        radix tree, never mid-draft). Committed length untouched; see
        the base class."""
        added = super().grow_to(seq_id, n_tokens)
        for p in added:
            self._refs[p] = self._refs.get(p, 0) + 1
        return added

    def truncate_pages(self, seq_id, keep_pages: int) -> List[int]:
        """Speculative rollback under shared ownership: each stranded
        page is dereferenced; it returns to the free list only at
        refcount 0 and only if the radix tree doesn't cache it (a
        cached page stays resident/evictable — same release rule as
        :meth:`free`). Returns the pages actually freed."""
        table = self._tables[seq_id]
        freed: List[int] = []
        while len(table) > keep_pages:
            p = table.pop()
            r = self._refs.get(p, 0) - 1
            if r < 0:
                raise RuntimeError(f"page {p} refcount went negative")
            if r == 0:
                self._refs.pop(p)
                if p not in self._cached:
                    self._free.append(p)
                    freed.append(p)
            else:
                self._refs[p] = r
        if self._lens.get(seq_id, 0) > keep_pages * self.page_size:
            self._lens[seq_id] = keep_pages * self.page_size
        return freed

    def free(self, seq_id) -> None:
        """Release a sequence: decrement every page it holds; a page whose
        refcount reaches 0 returns to the free list UNLESS the radix tree
        caches it (then it stays resident, evictable)."""
        for p in self._tables.pop(seq_id):
            r = self._refs.get(p, 0) - 1
            if r < 0:
                raise RuntimeError(f"page {p} refcount went negative")
            if r == 0:
                self._refs.pop(p)
                if p not in self._cached:
                    self._free.append(p)
            else:
                self._refs[p] = r
        self._lens.pop(seq_id)

    # -- cache-side hooks (PrefixCache / eviction policy only) ---------------

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)

    def adopt_cached(self, page: int) -> None:
        """The radix tree now indexes ``page``: it survives refcount 0."""
        self._cached.add(page)

    def evict_cached(self, page: int) -> None:
        """The radix tree dropped ``page``: back to the free list if no
        live sequence still shares it (else it frees on last release)."""
        self._cached.discard(page)
        if self._refs.get(page, 0) == 0:
            self._free.append(page)

    def copy_page(self, src: int, dst: int) -> None:
        """Device-side COW: copy ``src``'s slab (every layer) into
        ``dst``. One jitted, donated gather-scatter on the pool arrays —
        the same update machinery as ``paged_write_array``, page-granular
        (page ids ride as traced scalars, so this compiles once)."""
        self.k_pages, self.v_pages = _copy_page_slab(
            self.k_pages, self.v_pages, jnp.int32(src), jnp.int32(dst))

    # -- page-granular export/import (DCN migration) -------------------------

    def take_free_pages(self, n: int) -> List[int]:
        """Reserve ``n`` pages off the free list WITHOUT binding them to a
        sequence or bumping refcounts — the migration import's staging
        step. The caller owns them transiently and must hand every one
        back (``give_back_pages``) or into the radix tree
        (``adopt_cached``); anything else breaks conservation, which is
        exactly what makes partial-transfer rollback auditable."""
        if n < 0:
            raise ValueError(f"cannot take {n} pages")
        if len(self._free) < n:
            self._oom("import", n)
            raise MemoryError(
                f"KV pool exhausted: need {n} pages, "
                f"{len(self._free)} free")
        return [self._free.pop() for _ in range(n)]

    def give_back_pages(self, pages: Sequence[int]) -> None:
        """Return staged pages (from ``take_free_pages``) to the free
        list — the rollback half of an aborted import."""
        for p in pages:
            if p == 0 or p in self._refs or p in self._cached:
                raise RuntimeError(
                    f"page {p} is not a staged page (reserved/live/cached)")
        self._free.extend(pages)

    def export_page(self, page: int):
        """Read one page's K and V slabs (every layer) off the device as
        a ``(k_slab, v_slab)`` pair of host arrays — the wire format's
        payload unit."""
        import numpy as np
        return (np.asarray(self.k_pages[:, page]),
                np.asarray(self.v_pages[:, page]))

    def write_page(self, page: int, k_slab, v_slab) -> None:
        """Scatter a host-provided slab pair into ``page`` device-side
        (jitted, donated; compiles once — page ids are traced)."""
        self.k_pages, self.v_pages = _write_page_slab(
            self.k_pages, self.v_pages,
            jnp.asarray(k_slab, self.k_pages.dtype),
            jnp.asarray(v_slab, self.v_pages.dtype),
            jnp.int32(page))

    # -- accounting ----------------------------------------------------------

    @property
    def num_live_pages(self) -> int:
        return len(self._refs)

    @property
    def num_cached_pages(self) -> int:
        """Resident-but-unreferenced (evictable) cached pages."""
        return sum(1 for p in self._cached if p not in self._refs)

    def check_conservation(self) -> None:
        """Assert the pool's books balance (module docstring). Raises
        ``RuntimeError`` with a full breakdown on any violation."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise RuntimeError("duplicate pages on the free list")
        if any(r <= 0 for r in self._refs.values()):
            raise RuntimeError("non-positive refcount retained")
        live = set(self._refs)
        cached0 = {p for p in self._cached if p not in live}
        if free & live or free & cached0:
            raise RuntimeError(
                f"page state overlap: free∩live={free & live}, "
                f"free∩cached={free & cached0}")
        if 0 in free | live | self._cached:
            raise RuntimeError("reserved page 0 entered circulation")
        counts: Dict[int, int] = {}
        for table in self._tables.values():
            for p in table:
                counts[p] = counts.get(p, 0) + 1
        if counts != self._refs:
            raise RuntimeError(
                f"refcounts diverge from block-table occupancy: "
                f"refs={self._refs} tables={counts}")
        total = len(free) + len(live) + len(cached0)
        if total != self.usable_pages:
            raise RuntimeError(
                f"page conservation violated: {len(free)} free + "
                f"{len(live)} live + {len(cached0)} cached = {total} "
                f"!= {self.usable_pages} usable")
