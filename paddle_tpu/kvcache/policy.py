"""Eviction policy: which cached pages die when the pool runs dry.

The cache keeps finished prefixes resident as long as pages are plentiful
— caching is free until allocation pressure appears, so the policy is
invoked only from the admission paths (engine/scheduler) when fresh pages
run short. Strategy here: **LRU over evictable leaves**. Only radix
leaves are candidates (dropping an interior node would orphan the cached
blocks beneath it), and only pages no live sequence references (a pinned
page frees no memory and its node would lose a still-hot prefix).
Removing a leaf can expose its parent as the next candidate, so deep cold
chains unwind oldest-first.
"""

from __future__ import annotations

import heapq
from typing import Iterable, List, Set

from .radix import RadixNode, RadixTree


class LRUEvictionPolicy:
    """Pick the least-recently-used evictable leaves (module docstring).

    Stateless: LRU stamps live on the radix nodes; ``protect`` lets an
    admission in flight shield the pages it is about to share (their
    refcounts rise only once the sequence's table is built)."""

    def select(self, tree: RadixTree, refcount, n: int,
               protect: Iterable[int] = ()) -> List[RadixNode]:
        """Up to ``n`` victims, coldest-first, children before parents —
        ONE leaf scan plus a heap, not a rescan per victim (eviction runs
        on the admission hot path exactly when the system is loaded).
        Parents whose children are all selected join the candidate heap
        (simulated removal; the caller performs the real detach in the
        returned order)."""
        protected: Set[int] = {p for p in protect if p is not None}

        def evictable(node: RadixNode) -> bool:
            return node.page not in protected and refcount(node.page) == 0

        heap = [(leaf.last_access, id(leaf), leaf)
                for leaf in tree.leaves() if evictable(leaf)]
        heapq.heapify(heap)
        victims: List[RadixNode] = []
        live_children: dict = {}      # id(parent) -> not-yet-selected count
        while heap and len(victims) < n:
            _, _, node = heapq.heappop(heap)
            victims.append(node)
            parent = node.parent
            if parent is None or parent is tree.root:
                continue
            left = live_children.get(id(parent), len(parent.children)) - 1
            live_children[id(parent)] = left
            if left == 0 and evictable(parent):
                heapq.heappush(heap,
                               (parent.last_access, id(parent), parent))
        return victims
