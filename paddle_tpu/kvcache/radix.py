"""Token-block radix tree: prompt prefixes -> resident KV page lists.

The prefix cache's index (the tree SGLang's RadixAttention and the TPU
ragged-paged-attention layout in PAPERS.md make cheap to exploit): one
node per ``page_size``-token block, child edges keyed by the block's token
tuple, each node holding the physical page id whose KV encodes exactly
those tokens at their absolute positions. A prefix lookup walks full
blocks from the root; the matched node path IS the list of reusable
pages. Page ownership/refcounts live in :mod:`.pool`; this module is pure
host-side index structure (no device arrays, no refcounts).

Blocks are only ever cached WHOLE — a page whose tokens are partially
garbage can never be indexed, so a match is always byte-trustworthy.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple


class RadixNode:
    """One cached token block: ``key`` (the block's token tuple) edges
    from ``parent``; ``page`` is the physical page holding its KV."""

    __slots__ = ("children", "parent", "key", "page", "last_access")

    def __init__(self, parent: Optional["RadixNode"] = None,
                 key: Optional[Tuple[int, ...]] = None,
                 page: Optional[int] = None, last_access: int = 0):
        self.children: Dict[Tuple[int, ...], RadixNode] = {}
        self.parent = parent
        self.key = key
        self.page = page
        self.last_access = last_access

    @property
    def is_leaf(self) -> bool:
        return not self.children

    def depth_tokens(self, page_size: int) -> int:
        """Prefix length (tokens) this node's block completes."""
        n, node = 0, self
        while node.parent is not None:
            n += page_size
            node = node.parent
        return n


class RadixTree:
    """See module docstring. ``last_access`` stamps come from a logical
    clock (monotone int) so LRU ordering is deterministic under tests."""

    def __init__(self, page_size: int):
        self.page_size = page_size
        self.root = RadixNode()
        self._clock = 0
        self._by_page: Dict[int, RadixNode] = {}

    def __len__(self) -> int:
        return len(self._by_page)

    @property
    def pages(self) -> List[int]:
        return list(self._by_page)

    def tick(self) -> int:
        self._clock += 1
        return self._clock

    def _block(self, tokens: Sequence[int], i: int) -> Tuple[int, ...]:
        ps = self.page_size
        return tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])

    def match(self, tokens: Sequence[int], touch: bool = True
              ) -> List[RadixNode]:
        """Longest cached full-block prefix of ``tokens``: the node path
        root-outward. ``touch`` refreshes LRU stamps (peek-style callers —
        admission sizing — pass False so sizing never distorts LRU)."""
        node, out = self.root, []
        stamp = self.tick() if touch else None
        for i in range(len(tokens) // self.page_size):
            child = node.children.get(self._block(tokens, i))
            if child is None:
                break
            if stamp is not None:
                child.last_access = stamp
            out.append(child)
            node = child
        return out

    def insert(self, tokens: Sequence[int], pages: Sequence[int]
               ) -> Tuple[List[int], List[int]]:
        """Index ``tokens``'s full blocks, adopting ``pages[i]`` for each
        block not yet cached. Returns ``(adopted, duplicates)`` page-id
        lists: *adopted* pages are now owned by the tree (the caller must
        mark them cached in the pool); *duplicates* back blocks already
        cached under a DIFFERENT page — redundant KV the caller lets the
        pool free when the sequence releases."""
        node = self.root
        stamp = self.tick()
        adopted: List[int] = []
        dup: List[int] = []
        for i in range(min(len(tokens) // self.page_size, len(pages))):
            blk = self._block(tokens, i)
            child = node.children.get(blk)
            if child is None:
                child = RadixNode(parent=node, key=blk, page=int(pages[i]),
                                  last_access=stamp)
                node.children[blk] = child
                self._by_page[child.page] = child
                adopted.append(child.page)
            else:
                child.last_access = stamp
                if int(pages[i]) != child.page:
                    dup.append(int(pages[i]))
            node = child
        return adopted, dup

    def remove(self, node: RadixNode) -> None:
        """Detach a LEAF node (eviction). Interior nodes must keep their
        place or descendants' prefixes would dangle."""
        if node.children:
            raise ValueError("cannot remove an interior radix node")
        if node.parent is None:
            raise ValueError("cannot remove the radix root")
        del node.parent.children[node.key]
        self._by_page.pop(node.page, None)
        node.parent = None

    def leaves(self) -> Iterator[RadixNode]:
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            else:
                yield node
