"""PrefixCache: the radix index, refcounted pool and eviction policy
wired together behind the three calls the serving stack makes.

* :meth:`lookup` (admission) — longest reusable cached prefix for a
  prompt, capped at ``len(prompt) - 1`` tokens so at least one suffix
  token runs through the model (logits for sampling must come from
  somewhere). When the cap lands MID-page — a full-prompt match with the
  prompt a whole number of pages — the last matched page cannot be shared
  read-write, so lookup hands back a ``cow_src``: the engine copies that
  page device-side (:meth:`RefcountedKVCacheManager.copy_page`) and the
  sequence appends into its private copy.
* :meth:`insert` (retire) — index a finished sequence's full token blocks;
  newly adopted pages survive release as cached, blocks already indexed
  under another page are left alone (the duplicate frees with the
  sequence).
* :meth:`evict` (pressure) — LRU leaves back to the free list until the
  deficit is covered or nothing evictable remains.

Telemetry: ``paddle_kvcache_{hits,misses,evictions,cow_copies}_total``
counters and the ``paddle_kvcache_pages{state=free|live|cached}`` gauge
split in the process-global registry, plus ``cache_hit``/``cache_evict``
JSONL events — hit rate is measurable from the first request.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..observability.events import emit_event
from ..observability.registry import get_registry
from .policy import LRUEvictionPolicy
from .pool import RefcountedKVCacheManager
from .radix import RadixTree


class PrefixCache:
    """See module docstring."""

    def __init__(self, mgr: RefcountedKVCacheManager,
                 policy: Optional[LRUEvictionPolicy] = None):
        self.mgr = mgr
        self.page_size = mgr.page_size
        self.tree = RadixTree(mgr.page_size)
        self.policy = policy or LRUEvictionPolicy()
        #: local mirrors of the registry counters (benchmarks diff these
        #: without scraping; the registry may be reset() between tests)
        self.stats: Dict[str, int] = {
            "hits": 0, "misses": 0, "evictions": 0, "cow_copies": 0,
            "cached_tokens": 0,
        }
        reg = get_registry()
        self._c_hits = reg.counter(
            "paddle_kvcache_hits_total",
            "admissions that reused >=1 cached prefix page")
        self._c_misses = reg.counter(
            "paddle_kvcache_misses_total",
            "admissions with no reusable cached prefix")
        self._c_evict = reg.counter(
            "paddle_kvcache_evictions_total",
            "cached pages LRU-evicted back to the free list")
        self._c_cow = reg.counter(
            "paddle_kvcache_cow_copies_total",
            "copy-on-write page copies (suffix append into a shared page)")
        self._c_cached_tokens = reg.counter(
            "paddle_kvcache_cached_tokens_total",
            "prompt tokens served from cache instead of prefill")
        self._g_pages = reg.gauge(
            "paddle_kvcache_pages",
            "page pool split: free / live (refcounted) / cached (evictable)",
            labels=("state",))

    # -- admission ------------------------------------------------------------

    def _capped_match(self, prompt: Sequence[int], touch: bool
                      ) -> Tuple[List[int], int, Optional[int]]:
        lp = len(prompt)
        nodes = self.tree.match(prompt, touch=touch)
        pages = [nd.page for nd in nodes]
        cow_src: Optional[int] = None
        if pages and len(pages) * self.page_size >= lp:
            # full-prompt match: the last prompt token must be recomputed
            # for logits and its slot sits inside the final matched page —
            # share all but that page and copy-on-write its content
            cow_src = pages[-1]
            pages = pages[:-1]
            return pages, lp - 1, cow_src
        return pages, len(pages) * self.page_size, cow_src

    def lookup(self, prompt: Sequence[int]
               ) -> Tuple[List[int], int, Optional[int]]:
        """Reusable prefix for ``prompt``: ``(shared_pages, cached_tokens,
        cow_src)``. Refreshes LRU stamps; counters are bumped by
        :meth:`record` only when the request actually admits (a blocked
        head-of-queue request is looked up every step — counting those
        would fabricate hits)."""
        return self._capped_match(prompt, touch=True)

    def peek(self, prompt: Sequence[int]
             ) -> Tuple[List[int], int, Optional[int]]:
        """Sizing-only view for admission control: same ``(shared_pages,
        cached_tokens, cow_src)`` shape as :meth:`lookup` but without
        touching LRU or stats. Shared pages AND the COW source double as
        the ``protect`` set when the caller evicts to make room for the
        same request."""
        return self._capped_match(prompt, touch=False)

    def record(self, request_id, prompt_len: int, cached_tokens: int,
               shared_pages: int, cow: bool, trace_id: str = "") -> None:
        """Account one ADMITTED request's lookup outcome (metrics+event)."""
        if cow:
            self.stats["cow_copies"] += 1
            self._c_cow.inc()
        if cached_tokens > 0:
            self.stats["hits"] += 1
            self.stats["cached_tokens"] += cached_tokens
            self._c_hits.inc()
            self._c_cached_tokens.inc(cached_tokens)
            emit_event("cache_hit", request_id=request_id,
                       trace_id=trace_id, prompt_len=prompt_len,
                       cached_tokens=cached_tokens, pages=shared_pages,
                       cow=cow)
        else:
            self.stats["misses"] += 1
            self._c_misses.inc()

    # -- retire ---------------------------------------------------------------

    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Index a finished sequence's prefix (full blocks only; the
        ragged tail page frees with the sequence). Returns the number of
        pages the tree adopted."""
        adopted, _dup = self.tree.insert(tokens, pages)
        for p in adopted:
            self.mgr.adopt_cached(p)
        return len(adopted)

    # -- migration import -----------------------------------------------------

    def _slab_shape(self) -> Tuple[int, ...]:
        """Expected shape of ONE page's K (or V) slab: the pool array
        minus its page axis — ``(layers, page_size, kv_heads, head_dim)``."""
        s = self.mgr.k_pages.shape
        return (s[0],) + s[2:]

    def import_prefix(self, tokens: Sequence[int], k_slabs: Sequence,
                      v_slabs: Sequence) -> Dict[str, int]:
        """Adopt a migrated prefix into THIS host's cache: ``k_slabs[i]``/
        ``v_slabs[i]`` hold the KV for ``tokens``' i-th full block.
        Blocks the radix tree already caches are skipped (their payload
        is dropped, not written — the destination replays only pages it
        lacks); the remainder is staged off the free list, written
        device-side, and indexed.

        All-or-nothing: geometry is validated before the pool is
        touched, and any failure mid-import hands every staged page back
        (``give_back_pages``) so a half-transferred payload can never
        leak — ``check_conservation`` runs on every exit path that
        mutated the pool. Returns ``{imported_pages, skipped_pages,
        imported_bytes, evicted_pages}``."""
        ps = self.page_size
        n_blocks = len(k_slabs)
        if len(v_slabs) != n_blocks:
            raise ValueError(
                f"K/V slab count mismatch: {n_blocks} != {len(v_slabs)}")
        if len(tokens) < n_blocks * ps:
            raise ValueError(
                f"{len(tokens)} tokens cannot cover {n_blocks} "
                f"full blocks of {ps}")
        want = self._slab_shape()
        for i in range(n_blocks):
            for name, slab in (("k", k_slabs[i]), ("v", v_slabs[i])):
                got = tuple(getattr(slab, "shape", ()))
                if got != want:
                    raise ValueError(
                        f"{name}_slab[{i}] shape {got} != pool page "
                        f"geometry {want}")
        blocks = list(tokens[:n_blocks * ps])
        matched = self.tree.match(blocks, touch=False)
        n_have = len(matched)
        n_new = n_blocks - n_have
        out = {"imported_pages": 0, "skipped_pages": n_have,
               "imported_bytes": 0, "evicted_pages": 0}
        if n_new <= 0:
            return out
        protect = [nd.page for nd in matched]
        deficit = n_new - self.mgr.num_free_pages
        if deficit > 0:
            out["evicted_pages"] = self.evict(deficit, protect=protect)
        staged = self.mgr.take_free_pages(n_new)
        try:
            for j, p in enumerate(staged):
                i = n_have + j
                self.mgr.write_page(p, k_slabs[i], v_slabs[i])
            adopted, dup = self.tree.insert(blocks, protect + staged)
        except Exception:
            self.mgr.give_back_pages(staged)
            self.mgr.check_conservation()
            raise
        for p in adopted:
            self.mgr.adopt_cached(p)
        if dup:
            # a block raced into the tree under another page between
            # match and insert — the staged copy is redundant
            self.mgr.give_back_pages(dup)
        out["imported_pages"] = len(adopted)
        out["imported_bytes"] = len(adopted) * self.mgr.page_nbytes
        self.mgr.check_conservation()
        return out

    # -- pressure -------------------------------------------------------------

    def evict(self, n_pages: int, protect: Sequence[int] = ()) -> int:
        """Return up to ``n_pages`` cached pages to the free list, LRU
        leaves first; ``protect`` shields pages an in-flight admission is
        about to share. Returns the number actually freed."""
        victims = self.policy.select(self.tree, self.mgr.refcount,
                                     n_pages, protect)
        for victim in victims:        # children precede parents
            self.tree.remove(victim)
            self.mgr.evict_cached(victim.page)
        freed = len(victims)
        if freed:
            self.stats["evictions"] += freed
            self._c_evict.inc(freed)
            emit_event("cache_evict", pages=freed,
                       cached_left=self.mgr.num_cached_pages)
        return freed

    @property
    def evictable_pages(self) -> int:
        return self.mgr.num_cached_pages

    # -- telemetry ------------------------------------------------------------

    def update_gauges(self) -> None:
        """Refresh the free/live/cached page split in the registry."""
        self._g_pages.set(self.mgr.num_free_pages, state="free")
        self._g_pages.set(self.mgr.num_live_pages, state="live")
        self._g_pages.set(self.mgr.num_cached_pages, state="cached")

    def snapshot(self) -> Dict[str, int]:
        out = dict(self.stats)
        out["cached_pages"] = self.mgr.num_cached_pages
        out["tree_nodes"] = len(self.tree)
        return out

    def statusz(self) -> Dict[str, object]:
        """Diagnostics-server view (``DiagServer.attach_kvcache``): the
        hit/evict stats plus the live page-pool ownership split."""
        out: Dict[str, object] = dict(self.snapshot())
        out["pages"] = {"usable": self.mgr.usable_pages,
                        "free": self.mgr.num_free_pages,
                        "live": self.mgr.num_live_pages,
                        "cached": self.mgr.num_cached_pages}
        return out
