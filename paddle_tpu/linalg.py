"""``paddle.linalg`` parity namespace.

Reference: python/paddle/tensor/linalg.py + python/paddle/linalg.py:§0.
Decompositions and solvers delegate to jnp.linalg (XLA lowers QR/SVD/
eigh/cholesky natively; on TPU these run in fp32 on the MXU where shapes
allow). Everything funnels through the dispatch `apply` so autograd and
profiler hooks see them.
"""

from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import apply


def _op(name, fn, *args, **static):
    return apply(fn, *args, op_name=name, **static)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    from .core import math_ops as M
    return M.matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    # paddle semantics (flattened vector norm when axis is None) — shared
    # with the tensor-method implementation
    from .core import math_ops as M
    return M.norm(x, p=p, axis=axis, keepdim=keepdim)


def cond(x, p=None, name=None):
    return _op("cond", lambda v: jnp.linalg.cond(v, p=p), x)


def inv(x, name=None):
    return _op("inv", lambda v: jnp.linalg.inv(v), x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return _op("pinv", lambda v: jnp.linalg.pinv(v, rtol=rcond,
                                                 hermitian=hermitian), x)


def det(x, name=None):
    return _op("det", lambda v: jnp.linalg.det(v), x)


def slogdet(x, name=None):
    def fn(v):
        sign, logabs = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logabs])
    return _op("slogdet", fn, x)


def cholesky(x, upper=False, name=None):
    def fn(v):
        l = jnp.linalg.cholesky(v)
        return jnp.swapaxes(l, -1, -2) if upper else l
    return _op("cholesky", fn, x)


def qr(x, mode="reduced", name=None):
    if mode == "r":
        # jnp returns the bare R matrix here — tuple() would split rows
        return _op("qr", lambda v: jnp.linalg.qr(v, mode="r"), x)
    return _op("qr", lambda v: tuple(jnp.linalg.qr(v, mode=mode)), x)


def svd(x, full_matrices=False, name=None):
    return _op("svd", lambda v: tuple(
        jnp.linalg.svd(v, full_matrices=full_matrices)), x)


def eig(x, name=None):
    return _op("eig", lambda v: tuple(jnp.linalg.eig(v)), x)


def _from_triangle(v, UPLO):
    """Symmetric matrix read from one triangle (paddle UPLO semantics)."""
    if UPLO == "L":
        lo = jnp.tril(v)
        return lo + jnp.swapaxes(jnp.tril(v, -1), -1, -2)
    up = jnp.triu(v)
    return up + jnp.swapaxes(jnp.triu(v, 1), -1, -2)


def eigh(x, UPLO="L", name=None):
    return _op("eigh", lambda v: tuple(
        jnp.linalg.eigh(_from_triangle(v, UPLO), symmetrize_input=False)), x)


def eigvals(x, name=None):
    return _op("eigvals", lambda v: jnp.linalg.eigvals(v), x)


def eigvalsh(x, UPLO="L", name=None):
    return _op("eigvalsh", lambda v: jnp.linalg.eigvalsh(
        _from_triangle(v, UPLO)), x)


def solve(x, y, name=None):
    return _op("solve", lambda a, b: jnp.linalg.solve(a, b), x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    import jax.scipy.linalg as jsl
    return _op("triangular_solve",
               lambda a, b: jsl.solve_triangular(
                   a, b, lower=not upper, trans=1 if transpose else 0,
                   unit_diagonal=unitriangular), x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def fn(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return _op("lstsq", fn, x, y)


def matrix_power(x, n, name=None):
    return _op("matrix_power",
               lambda v: jnp.linalg.matrix_power(v, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    def fn(v):
        s = (jnp.abs(jnp.linalg.eigvalsh(v)) if hermitian
             else jnp.linalg.svd(v, compute_uv=False))
        if tol is None:
            # numpy default: max(dims) * eps * largest singular value
            t = (max(v.shape[-2:]) * jnp.finfo(v.dtype).eps
                 * jnp.max(s, axis=-1, keepdims=True))
        else:
            t = jnp.asarray(tol)  # paddle: ABSOLUTE tolerance
        return jnp.sum(s > t, axis=-1)
    return _op("matrix_rank", fn, x)


def multi_dot(xs, name=None):
    return _op("multi_dot", lambda *vs: jnp.linalg.multi_dot(vs), *xs)


# ---------------------------------------------------------------------------
# round-2 audit batch
# ---------------------------------------------------------------------------
def cholesky_solve(x, y, upper=False, name=None):
    """Solve A z = x given y = cholesky factor of A (paddle arg order:
    x is the right-hand side, y the factor)."""
    import jax

    def fn(b, L):
        if upper:
            z = jax.scipy.linalg.solve_triangular(L, b, lower=False,
                                                  trans="T")
            return jax.scipy.linalg.solve_triangular(L, z, lower=False)
        z = jax.scipy.linalg.solve_triangular(L, b, lower=True)
        return jax.scipy.linalg.solve_triangular(L, z, lower=True, trans="T")

    return _op("cholesky_solve", fn, x, y)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    extra = [w for w in (fweights, aweights) if w is not None]
    has_f, has_a = fweights is not None, aweights is not None

    def fn(v, *ws):
        it = iter(ws)
        fw = next(it) if has_f else None
        aw = next(it) if has_a else None
        return jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0,
                       fweights=fw, aweights=aw)

    return _op("cov", fn, x, *extra)


def corrcoef(x, rowvar=True, name=None):
    return _op("corrcoef", lambda v: jnp.corrcoef(v, rowvar=rowvar), x)


def lu(x, pivot=True, get_infos=False, name=None):
    """LU factorization. Returns (LU-packed, pivots[, infos]) — paddle
    layout: pivots are 1-based row-swap indices."""
    import jax

    def fn(v):
        packed, pivots = jax.scipy.linalg.lu_factor(v)
        outs = (packed, pivots.astype(jnp.int32) + 1)
        if get_infos:
            # LAPACK getrf info: 1-based index of the first zero pivot on
            # the U diagonal, 0 on success (per matrix for batched input)
            diag = jnp.diagonal(packed, axis1=-2, axis2=-1)
            zero = diag == 0
            first = jnp.argmax(zero, axis=-1) + 1
            info = jnp.where(jnp.any(zero, axis=-1), first, 0) \
                .astype(jnp.int32)
            outs = outs + (info,)
        return outs

    return _op("lu", fn, x, n_outputs=3 if get_infos else 2)


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    """(P, L, U) from the packed LU factorization."""
    def fn2d(packed, piv):
        m = packed.shape[-2]
        n = packed.shape[-1]
        k = min(m, n)
        L = jnp.tril(packed[:, :k], -1) + jnp.eye(m, k, dtype=packed.dtype)
        U = jnp.triu(packed[:k, :])
        # pivots (1-based sequential row swaps) -> permutation matrix
        perm = jnp.arange(m)
        for i in range(piv.shape[-1]):
            j = piv[i] - 1
            pi = perm[i]
            perm = perm.at[i].set(perm[j]).at[j].set(pi)
        P = jnp.eye(m, dtype=packed.dtype)[perm].T
        return P, L, U

    def fn(packed, piv):
        f = fn2d
        for _ in range(packed.ndim - 2):  # batched: vmap leading dims
            f = jax.vmap(f)
        return f(packed, piv)

    import jax
    return _op("lu_unpack", fn, lu_data, lu_pivots, n_outputs=3)


def _householder_full_2d(a, t):
    m = a.shape[0]
    q = jnp.eye(m, dtype=a.dtype)
    for i in range(t.shape[0]):
        v = jnp.where(jnp.arange(m) > i, a[:, i], 0.0)
        v = v.at[i].set(1.0)
        q = q - t[i] * (q @ v)[:, None] * v[None, :]
    return q


def _householder_full(a, t):
    """Full m x m  Q = H_0 H_1 ... from geqrf-packed reflectors
    (batched via vmap over leading dims)."""
    import jax

    f = _householder_full_2d
    for _ in range(a.ndim - 2):
        f = jax.vmap(f)
    return f(a, t)


def householder_product(x, tau, name=None):
    """Q (economy, m x n) from Householder reflectors (geqrf layout) —
    paddle.linalg.householder_product."""
    def fn(a, t):
        return _householder_full(a, t)[..., :, :a.shape[-1]]

    return _op("householder_product", fn, x, tau)


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """Multiply ``other`` by the FULL m x m Q of a geqrf factorization
    (LAPACK ormqr semantics)."""
    def fn(a, t, ov):
        qq = _householder_full(a, t)
        if transpose:
            qq = jnp.swapaxes(qq, -1, -2)
        return qq @ ov if left else ov @ qq

    return _op("ormqr", fn, x, tau, other)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized truncated SVD (Halko et al.) — paddle.linalg.svd_lowrank."""
    import jax

    extra = [M] if M is not None else []

    def fn(a, *rest):
        if rest:
            a = a - rest[0]  # paddle: SVD of A - M (the PCA/centered path)
        mT = lambda z: jnp.swapaxes(z, -1, -2)  # noqa: E731 — batch-safe
        m, n = a.shape[-2], a.shape[-1]
        k = min(q, m, n)
        # fixed-seed sketch: deterministic under jit, adequate for the
        # low-rank approximation contract
        g = jax.random.normal(jax.random.key(0), (n, k), a.dtype)
        y = a @ g
        for _ in range(niter):
            # re-orthonormalize each iteration: without it y scales as
            # sigma_max^(2*niter+1) and overflows fp32 for large inputs
            qy, _ = jnp.linalg.qr(y)
            y = a @ (mT(a) @ qy)
        qmat, _ = jnp.linalg.qr(y)
        b = mT(qmat) @ a
        u, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return qmat @ u, s, mT(vh)

    return _op("svd_lowrank", fn, x, *extra, n_outputs=3)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    def fn(v):
        return jnp.linalg.vector_norm(v, ord=p, axis=axis, keepdims=keepdim)
    return _op("vector_norm", fn, x)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    ax = tuple(axis)

    def fn(v):
        vm = jnp.moveaxis(v, ax, (-2, -1))
        out = jnp.linalg.matrix_norm(vm, ord=p, keepdims=keepdim)
        if keepdim:
            # restore the kept 1-dims to the REDUCED axes' positions
            out = jnp.moveaxis(out, (-2, -1), ax)
        return out
    return _op("matrix_norm", fn, x)
