"""``paddle_tpu.geometric`` — graph message passing and segment ops.

Parity with python/paddle/geometric/ of the reference
(message_passing/send_recv.py, segment ops, sampling —
paddle/phi/kernels/gpu/graph_send_recv_kernel.cu:§0). The compute ops
are gather + ``jax.ops.segment_*`` (XLA scatter-reduce on TPU), so they
jit and differentiate; the two sampling utilities are host-side numpy
by nature (the reference runs them on CPU for graph batching too) and
are documented eager-only.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .core.dispatch import apply
from .core.tensor import Tensor

__all__ = [
    "send_u_recv", "send_ue_recv", "send_uv",
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "reindex_graph", "sample_neighbors",
]

_MSG_OPS = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide,
}


def _seg_reduce(msgs, dst, n, reduce_op: str):
    if reduce_op == "sum":
        return jax.ops.segment_sum(msgs, dst, num_segments=n)
    if reduce_op == "mean":
        tot = jax.ops.segment_sum(msgs, dst, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((msgs.shape[0],), msgs.dtype),
                                  dst, num_segments=n)
        return tot / jnp.maximum(cnt, 1.0).reshape(
            (n,) + (1,) * (msgs.ndim - 1))
    if reduce_op in ("min", "max"):
        red = jax.ops.segment_min if reduce_op == "min" \
            else jax.ops.segment_max
        out = red(msgs, dst, num_segments=n)
        # empty segments hold the reduction identity (±inf for floats,
        # iinfo extremes for ints); mask them to 0 by count, which is
        # exact for every dtype
        cnt = jax.ops.segment_sum(jnp.ones((msgs.shape[0],), jnp.int32),
                                  dst, num_segments=n)
        mask = (cnt > 0).reshape((n,) + (1,) * (msgs.ndim - 1))
        return jnp.where(mask, out, jnp.zeros((), msgs.dtype))
    raise ValueError(f"unknown reduce_op {reduce_op!r}; "
                     "pick from sum/mean/min/max")


def _out_size(dst, x_rows, out_size):
    if out_size is not None:
        return int(out_size)
    if dst.size == 0:
        return 0
    try:
        return int(jnp.max(dst)) + 1
    except jax.errors.ConcretizationTypeError:
        # data-dependent max(dst)+1 cannot shape an output under jit;
        # fall back to the node count (pass out_size to override)
        return int(x_rows)


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size: Optional[int] = None, name=None):
    """Gather ``x`` rows at ``src_index``, reduce them at ``dst_index``
    (reference graph_send_recv). ``out_size=None`` infers max(dst)+1
    eagerly; under jit it defaults to ``x.shape[0]`` (pass ``out_size``
    for anything else — output shapes must be static)."""

    def fn(xv, src, dst):
        n = _out_size(dst, xv.shape[0], out_size)
        return _seg_reduce(xv[src.astype(jnp.int32)],
                           dst.astype(jnp.int32), n, reduce_op)
    return apply(fn, x, src_index, dst_index, op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size: Optional[int] = None,
                 name=None):
    """Node features combined with EDGE features
    (``message_op(x[src], y_edge)``), then reduced at dst."""
    if message_op not in _MSG_OPS:
        raise ValueError(f"unknown message_op {message_op!r}")

    def fn(xv, yv, src, dst):
        n = _out_size(dst, xv.shape[0], out_size)
        msgs = _MSG_OPS[message_op](xv[src.astype(jnp.int32)], yv)
        return _seg_reduce(msgs, dst.astype(jnp.int32), n, reduce_op)
    return apply(fn, x, y, src_index, dst_index, op_name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op: str = "add", name=None):
    """Per-edge messages ``message_op(x[src], y[dst])`` — no reduction
    (reference graph_send_uv)."""
    if message_op not in _MSG_OPS:
        raise ValueError(f"unknown message_op {message_op!r}")

    def fn(xv, yv, src, dst):
        return _MSG_OPS[message_op](xv[src.astype(jnp.int32)],
                                    yv[dst.astype(jnp.int32)])
    return apply(fn, x, y, src_index, dst_index, op_name="send_uv")


def _segment(data, segment_ids, reduce_op):
    def fn(d, s):
        n = _out_size(s, d.shape[0], None)
        return _seg_reduce(d, s.astype(jnp.int32), n, reduce_op)
    return apply(fn, data, segment_ids, op_name=f"segment_{reduce_op}")


def segment_sum(data, segment_ids, name=None):
    """Reference paddle.geometric.segment_sum (ids must be sorted in the
    reference; the scatter here accepts any order)."""
    return _segment(data, segment_ids, "sum")


def segment_mean(data, segment_ids, name=None):
    return _segment(data, segment_ids, "mean")


def segment_min(data, segment_ids, name=None):
    return _segment(data, segment_ids, "min")


def segment_max(data, segment_ids, name=None):
    return _segment(data, segment_ids, "max")


def reindex_graph(x, neighbors, count, name=None):
    """Host-side graph reindexing (reference graph_reindex): maps the
    node ids in ``x`` (unique target nodes) and ``neighbors`` (concat of
    per-node neighbor lists, lengths in ``count``) to a compact 0..n-1
    id space. Returns (reindex_src, reindex_dst, out_nodes). Eager-only
    (output size is data-dependent)."""
    xv = np.asarray(x._value if isinstance(x, Tensor) else x)
    nb = np.asarray(neighbors._value if isinstance(neighbors, Tensor)
                    else neighbors)
    cnt = np.asarray(count._value if isinstance(count, Tensor) else count)
    order = {int(v): i for i, v in enumerate(xv)}
    out_nodes = list(xv)
    for v in nb:
        v = int(v)
        if v not in order:
            order[v] = len(out_nodes)
            out_nodes.append(v)
    reindex_src = np.asarray([order[int(v)] for v in nb], np.int32)
    reindex_dst = np.repeat(np.arange(len(cnt), dtype=np.int32), cnt)
    return (Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(np.asarray(out_nodes, np.int32))))


def sample_neighbors(row, colptr, input_nodes, sample_size: int = -1,
                     eids=None, return_eids: bool = False,
                     perm_buffer=None, name=None):
    """Host-side uniform neighbor sampling over a CSC graph (reference
    graph_sample_neighbors). Returns (out_neighbors, out_count) — ragged
    output sizes are data-dependent, so this is eager-only like the
    reference's CPU path used for batching."""
    if return_eids or eids is not None:
        raise NotImplementedError(
            "sample_neighbors eids tracking is not implemented; sample "
            "without eids or index edge features by (dst, position)")
    rowv = np.asarray(row._value if isinstance(row, Tensor) else row)
    colv = np.asarray(colptr._value if isinstance(colptr, Tensor)
                      else colptr)
    nodes = np.asarray(input_nodes._value
                       if isinstance(input_nodes, Tensor) else input_nodes)
    rng = np.random.RandomState(np.random.randint(0, 2 ** 31))
    outs, counts = [], []
    for n in nodes:
        lo, hi = int(colv[n]), int(colv[n + 1])
        neigh = rowv[lo:hi]
        if sample_size >= 0 and len(neigh) > sample_size:
            neigh = rng.choice(neigh, size=sample_size, replace=False)
        outs.append(neigh)
        counts.append(len(neigh))
    flat = np.concatenate(outs) if outs else np.zeros((0,), rowv.dtype)
    return (Tensor(jnp.asarray(flat.astype(np.int32))),
            Tensor(jnp.asarray(np.asarray(counts, np.int32))))
