"""Recurrent layers: SimpleRNN / LSTM / GRU (+ cells).

Rebuild of python/paddle/nn/layer/rnn.py over the phi rnn kernels
(paddle/phi/kernels/gpu/rnn_kernel.cu — cuDNN-backed in the reference;
SURVEY.md §2.1 kernel corpus). TPU-native: the time loop is a ``lax.scan``
per layer/direction — one compiled program, weights as scan-invariant
captures, MXU-friendly stacked gate matmuls.

Conventions match paddle: batch-major inputs (batch, time, size) by
default (``time_major=True`` flips), gate order i,f,c,o for LSTM and
r,z,c for GRU, and outputs (outputs, final_states).
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from .layer import Layer, LayerList
from . import initializer as I
from ..core.dispatch import apply
from ..core.tensor import Tensor


def _uniform_init(fan):
    bound = 1.0 / math.sqrt(fan) if fan > 0 else 0.0
    return I.Uniform(-bound, bound)


class _RNNCellBase(Layer):
    n_gates = 1
    activation = staticmethod(jnp.tanh)

    def __init__(self, input_size: int, hidden_size: int, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        g = self.n_gates
        init = _uniform_init(hidden_size)
        self.weight_ih = self.create_parameter(
            (g * hidden_size, input_size), default_initializer=init)
        self.weight_hh = self.create_parameter(
            (g * hidden_size, hidden_size), default_initializer=init)
        self.bias_ih = self.create_parameter(
            (g * hidden_size,), default_initializer=init, is_bias=True)
        self.bias_hh = self.create_parameter(
            (g * hidden_size,), default_initializer=init, is_bias=True)

    def get_initial_states(self, batch):
        z = Tensor(jnp.zeros((batch, self.hidden_size), jnp.float32))
        return z


def _apply_gates(gates, state, n_gates, kind):
    h = gates.shape[-1] // n_gates
    if kind == "simple":
        new_h = jnp.tanh(gates)
        return new_h, new_h
    if kind == "lstm":
        h_prev, c_prev = state
        i, f, g, o = (gates[..., k * h:(k + 1) * h] for k in range(4))
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c = f * c_prev + i * jnp.tanh(g)
        new_h = o * jnp.tanh(c)
        return new_h, (new_h, c)
    raise AssertionError("gru is handled by _gru_step")


def _gru_step(params, x_t, h_prev):
    wih, whh, bih, bhh = params
    hs = whh.shape[1]
    xg = x_t @ wih.T + bih                      # (B, 3H)
    hg = h_prev @ whh.T + bhh
    xr, xz, xc = (xg[..., k * hs:(k + 1) * hs] for k in range(3))
    hr, hz, hc = (hg[..., k * hs:(k + 1) * hs] for k in range(3))
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    c = jnp.tanh(xc + r * hc)
    new_h = (1 - z) * c + z * h_prev
    return new_h, new_h


def _cell_step(kind, params, x_t, state):
    if kind == "gru":
        return _gru_step(params, x_t, state)
    if kind == "lstm":
        wih, whh, bih, bhh = params
        gates = x_t @ wih.T + bih + state[0] @ whh.T + bhh
        return _apply_gates(gates, state, 4, "lstm")
    wih, whh, bih, bhh = params
    gates = x_t @ wih.T + bih + state @ whh.T + bhh
    return _apply_gates(gates, state, 1, "simple")


class SimpleRNNCell(_RNNCellBase):
    n_gates = 1
    kind = "simple"

    def forward(self, inputs, states=None):
        st = states if states is not None else self.get_initial_states(
            inputs.shape[0])
        out = apply(lambda x, h, a, b, c, d: _cell_step(
            "simple", (a, b, c, d), x, h)[0], inputs, st,
            self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh,
            op_name="simple_rnn_cell")
        return out, out


class LSTMCell(_RNNCellBase):
    n_gates = 4
    kind = "lstm"

    def get_initial_states(self, batch):
        z = Tensor(jnp.zeros((batch, self.hidden_size), jnp.float32))
        return (z, z)

    def forward(self, inputs, states=None):
        st = states if states is not None else self.get_initial_states(
            inputs.shape[0])
        h, c = st

        def fn(x, hv, cv, a, b, bi, bh):
            nh, (nh2, nc) = _cell_step("lstm", (a, b, bi, bh), x, (hv, cv))
            return nh, nc

        nh, nc = apply(fn, inputs, h, c, self.weight_ih, self.weight_hh,
                       self.bias_ih, self.bias_hh, op_name="lstm_cell",
                       n_outputs=2)
        return nh, (nh, nc)


class GRUCell(_RNNCellBase):
    n_gates = 3
    kind = "gru"

    def forward(self, inputs, states=None):
        st = states if states is not None else self.get_initial_states(
            inputs.shape[0])
        out = apply(lambda x, h, a, b, c, d: _gru_step(
            (a, b, c, d), x, h)[0], inputs, st,
            self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh,
            op_name="gru_cell")
        return out, out


class _RNNBase(Layer):
    kind = "simple"
    n_gates = 1

    def __init__(self, input_size: int, hidden_size: int, num_layers: int = 1,
                 direction: str = "forward", time_major: bool = False,
                 dropout: float = 0.0, name=None, **kwargs):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"unknown direction {direction!r}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bidirectional = direction != "forward"
        self.time_major = time_major
        self.dropout = float(dropout)
        ndir = 2 if self.bidirectional else 1
        self.num_directions = ndir
        g = self.n_gates
        init = _uniform_init(hidden_size)
        self._weights = []
        for layer in range(num_layers):
            for d in range(ndir):
                isz = input_size if layer == 0 else hidden_size * ndir
                wih = self.create_parameter((g * hidden_size, isz),
                                            default_initializer=init)
                whh = self.create_parameter((g * hidden_size, hidden_size),
                                            default_initializer=init)
                bih = self.create_parameter((g * hidden_size,),
                                            default_initializer=init,
                                            is_bias=True)
                bhh = self.create_parameter((g * hidden_size,),
                                            default_initializer=init,
                                            is_bias=True)
                names = [f"weight_ih_l{layer}", f"weight_hh_l{layer}",
                         f"bias_ih_l{layer}", f"bias_hh_l{layer}"]
                if d == 1:
                    names = [n + "_reverse" for n in names]
                for n, p in zip(names, (wih, whh, bih, bhh)):
                    setattr(self, n, p)
                self._weights.append((wih, whh, bih, bhh))

    def _initial_state(self, batch):
        n = self.num_layers * self.num_directions
        z = jnp.zeros((n, batch, self.hidden_size), jnp.float32)
        return z

    def forward(self, inputs, initial_states=None, sequence_length=None):
        kind = self.kind
        nl, nd, hs = self.num_layers, self.num_directions, self.hidden_size
        time_major = self.time_major
        params = [p for tup in self._weights for p in tup]
        has_init = initial_states is not None
        has_seq = sequence_length is not None
        init_args = []
        if has_init:
            if kind == "lstm":
                init_args = [initial_states[0], initial_states[1]]
            else:
                init_args = [initial_states]
        if has_seq:
            init_args = init_args + [sequence_length]
        # inter-layer dropout (reference: applied to every stacked layer's
        # output except the last, training only)
        drop_keys = None
        if self.training and self.dropout > 0.0 and nl > 1:
            from .. import random as _random
            drop_keys = [_random.next_key() for _ in range(nl - 1)]

        def fn(x, *flat):
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)                 # (T, B, I)
            T, B = x.shape[0], x.shape[1]
            n_w = nl * nd * 4
            ws = [tuple(flat[i * 4:(i + 1) * 4]) for i in range(nl * nd)]
            pos = n_w
            init_h = init_c = None
            if has_init:
                init_h = flat[pos]
                pos += 1
                if kind == "lstm":
                    init_c = flat[pos]
                    pos += 1
            if has_seq:
                slen = flat[pos].astype(jnp.int32)        # (B,)
                mask = (jnp.arange(T)[:, None] < slen[None, :])  # (T, B)
                # per-example reversal of the VALID prefix; an involution,
                # so the same gather un-reverses scan outputs
                t_idx = jnp.arange(T)[:, None]
                rev_idx = jnp.where(t_idx < slen[None, :],
                                    slen[None, :] - 1 - t_idx, t_idx)
            else:
                mask = rev_idx = None
            finals_h, finals_c = [], []
            for layer in range(nl):
                outs = []
                for d in range(nd):
                    p = ws[layer * nd + d]
                    if d == 1:
                        xs = jnp.take_along_axis(
                            x, rev_idx[:, :, None], axis=0) if has_seq \
                            else x[::-1]
                    else:
                        xs = x
                    slot = layer * nd + d
                    h0 = init_h[slot] if has_init else jnp.zeros((B, hs),
                                                                 x.dtype)
                    if kind == "lstm":
                        c0 = init_c[slot] if has_init else jnp.zeros(
                            (B, hs), x.dtype)
                        state0 = (h0, c0)
                    else:
                        state0 = h0

                    def step(st, xt_m, p=p):
                        if has_seq:
                            xt, m = xt_m
                            keep = m[:, None]
                        else:
                            xt = xt_m
                        _, new = _cell_step(kind, p, xt, st)
                        if has_seq:
                            # freeze state and zero output past seq_len
                            if kind == "lstm":
                                new = (jnp.where(keep, new[0], st[0]),
                                       jnp.where(keep, new[1], st[1]))
                            else:
                                new = jnp.where(keep, new, st)
                        out = new[0] if kind == "lstm" else new
                        if has_seq:
                            out = out * keep.astype(out.dtype)
                        return new, out

                    xs_in = (xs, mask) if has_seq else xs
                    final, seq = jax.lax.scan(step, state0, xs_in)
                    if d == 1:
                        seq = jnp.take_along_axis(
                            seq, rev_idx[:, :, None], axis=0) if has_seq \
                            else seq[::-1]
                        if has_seq:
                            seq = seq * mask[:, :, None].astype(seq.dtype)
                    outs.append(seq)
                    if kind == "lstm":
                        finals_h.append(final[0])
                        finals_c.append(final[1])
                    else:
                        finals_h.append(final)
                x = jnp.concatenate(outs, axis=-1) if nd == 2 else outs[0]
                if drop_keys is not None and layer < nl - 1:
                    keep = jax.random.bernoulli(
                        drop_keys[layer], 1.0 - self.dropout, x.shape)
                    x = jnp.where(keep, x / (1.0 - self.dropout),
                                  0.0).astype(x.dtype)
            out = x if time_major else jnp.swapaxes(x, 0, 1)
            fh = jnp.stack(finals_h)
            if kind == "lstm":
                return out, fh, jnp.stack(finals_c)
            return out, fh

        n_outputs = 3 if kind == "lstm" else 2
        res = apply(fn, inputs, *params, *init_args,
                    op_name=f"{kind}_rnn", n_outputs=n_outputs)
        if kind == "lstm":
            out, fh, fc = res
            return out, (fh, fc)
        out, fh = res
        return out, fh


class SimpleRNN(_RNNBase):
    kind = "simple"
    n_gates = 1


class LSTM(_RNNBase):
    kind = "lstm"
    n_gates = 4


class GRU(_RNNBase):
    kind = "gru"
    n_gates = 3


class RNN(Layer):
    """Wraps a cell into a scanned sequence runner (paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse: bool = False,
                 time_major: bool = False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None):
        kind = self.cell.kind
        time_major = self.time_major
        rev = self.is_reverse
        hs = self.cell.hidden_size
        params = (self.cell.weight_ih, self.cell.weight_hh,
                  self.cell.bias_ih, self.cell.bias_hh)
        has_init = initial_states is not None
        init_args = []
        if has_init:
            init_args = list(initial_states) if kind == "lstm"                 else [initial_states]

        def fn(x, *p):
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)
            if rev:
                x = x[::-1]
            B = x.shape[1]
            if has_init:
                state0 = (p[4], p[5]) if kind == "lstm" else p[4]
            else:
                h0 = jnp.zeros((B, hs), x.dtype)
                state0 = (h0, h0) if kind == "lstm" else h0

            def step(st, xt):
                _, new = _cell_step(kind, p, xt, st)
                return new, (new[0] if kind == "lstm" else new)

            final, seq = jax.lax.scan(step, state0, x)
            if rev:
                seq = seq[::-1]
            out = seq if time_major else jnp.swapaxes(seq, 0, 1)
            if kind == "lstm":
                return out, final[0], final[1]
            return out, final

        n_outputs = 3 if kind == "lstm" else 2
        res = apply(fn, inputs, *params, *init_args, op_name="rnn",
                    n_outputs=n_outputs)
        if kind == "lstm":
            return res[0], (res[1], res[2])
        return res[0], res[1]
