"""``paddle_tpu.nn`` — layers, functional ops, initializers.

Parity with python/paddle/nn/ of the reference (SURVEY.md §2.5).
"""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from .layer import Layer, LayerList, Sequential, ParameterList, ParamAttr  # noqa: F401
from .common_layers import (  # noqa: F401
    Linear, Embedding, Identity, Flatten, Dropout, Dropout2D, Upsample,
    Conv1D, Conv2D, Conv3D, Conv2DTranspose,
    LayerNorm, RMSNorm, BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
    SyncBatchNorm, GroupNorm, InstanceNorm2D,
    MaxPool2D, MaxUnPool2D, AvgPool2D, AdaptiveAvgPool2D, AdaptiveMaxPool2D,
    ReLU, ReLU6, GELU, SiLU, Swish, Mish, Sigmoid, Tanh, Hardswish, Hardsigmoid,
    Hardtanh, ELU, SELU, CELU, Softplus, Softsign, Tanhshrink, Hardshrink,
    Softshrink, LogSoftmax, LeakyReLU, PReLU, Softmax,
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    SmoothL1Loss, KLDivLoss, Pad2D, PixelShuffle,
)
from .rnn import (  # noqa: F401
    SimpleRNN, LSTM, GRU, RNN, SimpleRNNCell, LSTMCell, GRUCell,
)
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from .transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .layers_extra import (  # noqa: F401
    MaxPool1D, AvgPool1D, AdaptiveAvgPool1D, Pad1D, Pad3D, ZeroPad2D,
    UpsamplingBilinear2D, GLU, AlphaDropout, LocalResponseNorm,
    InstanceNorm1D, Bilinear, CosineSimilarity, PairwiseDistance,
    Unfold, Fold, HuberLoss, MarginRankingLoss, TripletMarginLoss,
    SpectralNorm,
    ChannelShuffle, Softmax2D, ThresholdedReLU, RReLU, CTCLoss,
    CosineEmbeddingLoss, GaussianNLLLoss, HingeEmbeddingLoss,
    MultiLabelSoftMarginLoss, MultiMarginLoss, PoissonNLLLoss,
    SoftMarginLoss, AdaptiveLogSoftmaxWithLoss,
)

# imported LAST: quant pulls paddle_tpu.quantization, whose QAT module
# needs nn.Linear already bound (circular otherwise)
from . import quant  # noqa: E402,F401
