"""Transformer layer family: MultiHeadAttention, encoder/decoder layers and
stacks, and the seq2seq Transformer container.

Rebuild of python/paddle/nn/layer/transformer.py (SURVEY.md §2.5 incubate
row covers the FUSED variants; this is the standard paddle.nn surface).
Attention routes through F.scaled_dot_product_attention, which dispatches
to the Pallas flash kernel on TPU when shapes allow.
"""

from __future__ import annotations

import collections
import copy
from typing import Optional

import numpy as np
import jax.numpy as jnp

from . import functional as F
from .layer import Layer, LayerList
from .common_layers import Linear, LayerNorm, Dropout
from ..core.tensor import Tensor
from ..core.math_ops import concat


class MultiHeadAttention(Layer):
    """paddle.nn.MultiHeadAttention: (B, S, E) in/out, optional cross
    attention (kdim/vdim), additive attn_mask broadcastable to
    (B, H, Sq, Sk)."""

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        if self.head_dim * num_heads != embed_dim:
            raise ValueError("embed_dim must be divisible by num_heads")
        self.dropout = dropout
        self.need_weights = need_weights
        self.q_proj = Linear(embed_dim, embed_dim, bias_attr=bias_attr)
        self.k_proj = Linear(kdim or embed_dim, embed_dim,
                             bias_attr=bias_attr)
        self.v_proj = Linear(vdim or embed_dim, embed_dim,
                             bias_attr=bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, bias_attr=bias_attr)

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def gen_cache(self, key, value=None, type=None):
        """paddle parity: StaticCache holds precomputed cross-attention
        K/V; Cache accumulates self-attention K/V across decode steps.
        With both key and value given and type=Cache, the tensors are
        taken as ALREADY-projected K/V and wrapped raw (reference
        gen_cache third branch)."""
        if type is MultiHeadAttention.StaticCache:
            value = key if value is None else value
            b = key.shape[0]
            h, d = self.num_heads, self.head_dim
            k = self.k_proj(key).reshape([b, key.shape[1], h, d])
            v = self.v_proj(value).reshape([b, value.shape[1], h, d])
            return MultiHeadAttention.StaticCache(k, v)
        if value is not None:
            return MultiHeadAttention.Cache(key, value)
        b = key.shape[0]
        h, d = self.num_heads, self.head_dim
        dtype = getattr(key, "dtype", jnp.float32)
        z = Tensor(jnp.zeros((b, 0, h, d), dtype))
        return MultiHeadAttention.Cache(z, z)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        key = query if key is None else key
        value = key if value is None else value
        b, sq, _ = query.shape
        h, d = self.num_heads, self.head_dim
        q = self.q_proj(query).reshape([b, sq, h, d])
        if isinstance(cache, MultiHeadAttention.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self.k_proj(key).reshape([b, key.shape[1], h, d])
            v = self.v_proj(value).reshape([b, value.shape[1], h, d])
            if isinstance(cache, MultiHeadAttention.Cache):
                k = concat([cache.k, k], axis=1)
                v = concat([cache.v, v], axis=1)
                cache = MultiHeadAttention.Cache(k, v)
        if self.need_weights:
            # the masked XLA path materialises the probabilities
            import jax
            import math as _math

            def fn(qv, kv, vv, *rest):
                scale = 1.0 / _math.sqrt(d)
                s = jnp.einsum("bqhd,bkhd->bhqk", qv.astype(jnp.float32),
                               kv.astype(jnp.float32)) * scale
                if rest:
                    s = s + rest[0].astype(jnp.float32)
                p = jax.nn.softmax(s, axis=-1)
                o = jnp.einsum("bhqk,bkhd->bqhd", p,
                               vv.astype(jnp.float32)).astype(qv.dtype)
                return o, p

            from ..core.dispatch import apply as _apply
            args = (q, k, v) + ((attn_mask,) if attn_mask is not None
                                else ())
            o, weights = _apply(fn, *args, op_name="mha_weights",
                                n_outputs=2)
            out = self.out_proj(o.reshape([b, sq, h * d]))
            outs = (out, weights)
        else:
            o = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
                training=self.training, is_causal=False)
            outs = self.out_proj(o.reshape([b, sq, h * d]))
        if isinstance(cache, (MultiHeadAttention.Cache,
                              MultiHeadAttention.StaticCache)):
            if not isinstance(outs, tuple):
                outs = (outs,)
            return outs + (cache,)
        return outs


def _act(name):
    return {"relu": F.relu, "gelu": F.gelu}[name]


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout
            if attn_dropout is not None else dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout = Dropout(dropout)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(act_dropout
                                if act_dropout is not None else dropout)
        self.activation = activation
        self.normalize_before = normalize_before

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        x = self.norm1(src) if self.normalize_before else src
        x = residual + self.dropout1(self.self_attn(x, attn_mask=src_mask))
        if not self.normalize_before:
            x = self.norm1(x)
        residual = x
        y = self.norm2(x) if self.normalize_before else x
        y = self.linear2(self.dropout2(_act(self.activation)(
            self.linear1(y))))
        x = residual + self.dropout(y)
        if not self.normalize_before:
            x = self.norm2(x)
        return x


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [encoder_layer] + [copy.deepcopy(encoder_layer)
                               for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        ad = attn_dropout if attn_dropout is not None else dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=ad)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=ad)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(act_dropout
                                if act_dropout is not None else dropout)
        self.dropout_out = Dropout(dropout)
        self.activation = activation
        self.normalize_before = normalize_before

    def gen_cache(self, memory):
        """(incremental self-attn Cache, cross-attn StaticCache) — the
        tuple threaded through forward's ``cache`` (reference
        TransformerDecoderLayer.gen_cache)."""
        incremental = self.self_attn.gen_cache(
            memory, type=MultiHeadAttention.Cache)
        static = self.cross_attn.gen_cache(
            memory, memory, type=MultiHeadAttention.StaticCache)
        return incremental, static

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        new_cache = None
        residual = tgt
        x = self.norm1(tgt) if self.normalize_before else tgt
        if cache is None:
            y = self.self_attn(x, attn_mask=tgt_mask)
        else:
            y, incremental = self.self_attn(x, x, x, attn_mask=tgt_mask,
                                            cache=cache[0])
        x = residual + self.dropout1(y)
        if not self.normalize_before:
            x = self.norm1(x)
        residual = x
        y = self.norm2(x) if self.normalize_before else x
        if cache is None:
            y = self.cross_attn(y, memory, memory, attn_mask=memory_mask)
        else:
            y, static = self.cross_attn(y, memory, memory,
                                        attn_mask=memory_mask,
                                        cache=cache[1])
            new_cache = (incremental, static)
        x = residual + self.dropout2(y)
        if not self.normalize_before:
            x = self.norm2(x)
        residual = x
        y = self.norm3(x) if self.normalize_before else x
        y = self.linear2(self.dropout3(_act(self.activation)(
            self.linear1(y))))
        x = residual + self.dropout_out(y)
        if not self.normalize_before:
            x = self.norm3(x)
        return x if cache is None else (x, new_cache)


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [decoder_layer] + [copy.deepcopy(decoder_layer)
                               for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def gen_cache(self, memory, do_zip=False):
        """Per-layer (incremental, static) cache tuples; ``do_zip``
        transposes to ([incrementals...], [statics...]) for pipelined
        decode loops (reference TransformerDecoder.gen_cache)."""
        caches = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            return list(map(list, zip(*caches)))
        return caches

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        out = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                out = layer(out, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)
            else:
                out, nc = layer(out, memory, tgt_mask=tgt_mask,
                                memory_mask=memory_mask, cache=cache[i])
                new_caches.append(nc)
        if self.norm is not None:
            out = self.norm(out)
        return out if cache is None else (out, new_caches)


class Transformer(Layer):
    """paddle.nn.Transformer: encoder-decoder seq2seq container."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)

    @staticmethod
    def generate_square_subsequent_mask(length) -> Tensor:
        m = np.triu(np.full((length, length), -np.inf, np.float32), k=1)
        return Tensor(jnp.asarray(m))

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)
